"""In-process SQS fake (JSON protocol) for the S3 replication source.

Serves AmazonSQS.ReceiveMessage / AmazonSQS.DeleteMessage with visibility
timeouts: received messages go invisible until deleted or re-delivered
after `visibility` seconds — so tests exercise the at-least-once
commit-after-push discipline for real.  SigV4 is checked for presence +
access-key match (like the fake S3 recipe).
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class FakeSQS:
    def __init__(self, access_key: str = "test-ak",
                 visibility: float = 30.0):
        self.access_key = access_key
        self.visibility = visibility
        self.lock = threading.Lock()
        self.queue: list[dict] = []  # {id, body, receipt, invisible_until}
        self.deleted: list[str] = []
        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                auth = self.headers.get("Authorization", "")
                if ("AWS4-HMAC-SHA256" not in auth
                        or fake.access_key not in auth):
                    return self._send(403, {"message": "AccessDenied"})
                length = int(self.headers.get("Content-Length") or 0)
                req = json.loads(self.rfile.read(length) or b"{}")
                action = self.headers.get(
                    "X-Amz-Target", "").split(".")[-1]
                if action == "ReceiveMessage":
                    return self._send(200, fake.receive(req))
                if action == "DeleteMessage":
                    fake.delete(req.get("ReceiptHandle", ""))
                    return self._send(200, {})
                self._send(400, {"message": f"unknown action {action}"})

            def _send(self, status, obj):
                out = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type",
                                 "application/x-amz-json-1.0")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)

    # -- queue ops ----------------------------------------------------------
    def send_s3_event(self, key: str, bucket: str = "bucket",
                      event: str = "ObjectCreated:Put",
                      sns_wrapped: bool = False) -> None:
        body = json.dumps({"Records": [{
            "eventName": event,
            "eventTime": "2026-01-01T00:00:00Z",
            "s3": {"bucket": {"name": bucket},
                   "object": {"key": key, "size": 1}},
        }]})
        if sns_wrapped:
            body = json.dumps({"Type": "Notification", "Message": body})
        with self.lock:
            self.queue.append({
                "id": uuid.uuid4().hex, "body": body,
                "receipt": uuid.uuid4().hex, "invisible_until": 0.0,
            })

    def send_raw(self, body: str) -> None:
        with self.lock:
            self.queue.append({
                "id": uuid.uuid4().hex, "body": body,
                "receipt": uuid.uuid4().hex, "invisible_until": 0.0,
            })

    def receive(self, req: dict) -> dict:
        now = time.monotonic()
        out = []
        with self.lock:
            for m in self.queue:
                if m["invisible_until"] > now:
                    continue
                m["invisible_until"] = now + self.visibility
                out.append({
                    "MessageId": m["id"],
                    "ReceiptHandle": m["receipt"],
                    "Body": m["body"],
                })
                if len(out) >= req.get("MaxNumberOfMessages", 10):
                    break
        return {"Messages": out}

    def delete(self, receipt: str) -> None:
        with self.lock:
            self.queue = [m for m in self.queue
                          if m["receipt"] != receipt]
            self.deleted.append(receipt)

    @property
    def queue_url(self) -> str:
        return f"http://127.0.0.1:{self.port}/queue/events"

    def start(self) -> "FakeSQS":
        self.thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
