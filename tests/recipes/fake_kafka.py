"""In-process fake Kafka broker (wire-protocol subset).

Server side of what the provider's client speaks: ApiVersions ignored,
Metadata v1, Produce v3 (stores the raw record batch, re-serving it on
fetch — a real broker does the same), Fetch v4, ListOffsets v1.
"""

from __future__ import annotations

import socketserver
import struct
import threading
from typing import Optional

from transferia_tpu.providers.kafka.protocol import (
    Reader,
    decode_record_batches,
    enc_bytes,
    enc_str,
    enc_str as _enc_str,
    encode_record_batch,
)


def _index_frames(blob: bytes) -> Optional[list]:
    """[(frame_pos, record_count)] straight from the batch header(s) —
    no decode.  recordCount sits at fixed offset 57 of each v2 frame."""
    from transferia_tpu.providers.kafka.protocol import crc32c

    frames = []
    pos = 0
    n = len(blob)
    while pos + 61 <= n:
        batch_len = struct.unpack_from("!i", blob, pos + 8)[0]
        magic = blob[pos + 16]
        # a non-positive length would loop forever; corrupt frames must
        # land on the eager-decode path, which raises on produce
        if magic != 2 or batch_len <= 0 or pos + 12 + batch_len > n:
            return None
        # brokers validate the CRC at append time; so does this fake —
        # a corrupt batch errors the PRODUCER, not a later consumer
        expect = struct.unpack_from("!I", blob, pos + 17)[0]
        if crc32c(blob[pos + 21:pos + 12 + batch_len]) != expect:
            return None
        frames.append((pos, struct.unpack_from("!i", blob, pos + 57)[0]))
        pos += 12 + batch_len
    if pos != n:
        return None
    return frames


class _PartitionLog:
    """Partition storage as a real broker keeps it: raw produced batch
    blobs, decoded lazily when a fetch actually reads them.  Exposes the
    list surface the fixtures/tests use (len, slicing, iteration,
    append of decoded records)."""

    def __init__(self):
        # [base, count, blob|None, records|None]
        self._segments: list[list] = []
        self._n = 0

    def __len__(self) -> int:
        return self._n

    def append_blob(self, blob: bytes) -> bool:
        frames = _index_frames(blob)
        if frames is None:
            return False
        total = sum(c for _, c in frames)
        if not total:
            return True
        # assign offsets the broker way: rewrite each frame's baseOffset
        # in place, so the stored bytes can be served verbatim on fetch
        ba = bytearray(blob)
        base = self._n
        for pos, count in frames:
            struct.pack_into("!q", ba, pos, base)
            base += count
        self._segments.append([self._n, total, bytes(ba), None])
        self._n += total
        return True

    def raw_from(self, offset: int, max_records: int = 1000) -> bytes:
        """Stored frames covering [offset, ...), served verbatim (the
        client trims records below the requested offset, exactly as with
        a real broker's batch-aligned responses)."""
        out = []
        taken = 0
        for seg in self._segments:
            if seg[0] + seg[1] <= offset:
                continue
            if taken >= max_records:
                break
            if seg[2] is not None:
                out.append(seg[2])
            else:
                out.append(encode_record_batch(seg[3],
                                               base_offset=seg[0]))
            taken += seg[1]
        return b"".join(out)

    def append(self, rec) -> None:
        rec.offset = self._n
        if self._segments and self._segments[-1][2] is None:
            seg = self._segments[-1]
            seg[3].append(rec)
            seg[1] += 1
        else:
            self._segments.append([self._n, 1, None, [rec]])
        self._n += 1

    def _records_of(self, seg: list) -> list:
        if seg[3] is None:
            recs = decode_record_batches(seg[2])
            for i, r in enumerate(recs):
                r.offset = seg[0] + i
            seg[3] = recs
        return seg[3]

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            lo, hi, step = idx.indices(self._n)
            if step != 1:
                return [self[i] for i in range(lo, hi, step)]
            out = []
            for seg in self._segments:
                base, count = seg[0], seg[1]
                if base + count <= lo or base >= hi:
                    continue
                recs = self._records_of(seg)
                out.extend(recs[max(0, lo - base):hi - base])
            return out
        if idx < 0:
            idx += self._n
        for seg in self._segments:
            if seg[0] <= idx < seg[0] + seg[1]:
                return self._records_of(seg)[idx - seg[0]]
        raise IndexError(idx)

    def __iter__(self):
        for seg in self._segments:
            yield from self._records_of(seg)


class FakeKafka:
    def __init__(self, n_partitions: int = 2,
                 auto_create_topics: bool = True,
                 sasl: Optional[tuple] = None,
                 tls_cert: Optional[tuple] = None):
        """sasl: (mechanism, username, password) to REQUIRE auth;
        tls_cert: (certfile, keyfile) to serve TLS."""
        self.n_partitions = n_partitions
        self.auto_create = auto_create_topics
        # topic -> partition -> _PartitionLog (absolute offsets = index)
        self.topics: dict[str, list[_PartitionLog]] = {}
        self.lock = threading.RLock()
        self.port = 0
        self._srv = None
        self.sasl = sasl
        # transactional state (KIP-98 subset for the staged-commit
        # sink): transactional id -> {"pid", "epoch", "published":
        # [(topic, partition, segment)] of the LAST committed
        # transaction}, so a republish SUPERSEDES instead of appending
        # and a stale producer epoch is fenced
        self.txns: dict[str, dict] = {}
        self._next_pid = 1000
        self.auth_attempts = 0
        self._ssl_ctx = None
        if tls_cert is not None:
            import ssl

            self._ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            self._ssl_ctx.load_cert_chain(tls_cert[0], tls_cert[1])

    def create_topic(self, name: str,
                     n_partitions: Optional[int] = None) -> None:
        with self.lock:
            if name not in self.topics:
                self.topics[name] = [
                    _PartitionLog()
                    for _ in range(n_partitions or self.n_partitions)
                ]

    def records(self, topic: str, partition: int = 0) -> list:
        with self.lock:
            return list(self.topics.get(topic, [[]])[partition])

    def size(self, topic: str) -> int:
        with self.lock:
            return sum(len(p) for p in self.topics.get(topic, []))

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "FakeKafka":
        fake = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    if fake._ssl_ctx is not None:
                        self.request = fake._ssl_ctx.wrap_socket(
                            self.request, server_side=True)
                    session = {"authed": fake.sasl is None,
                               "verifier": None}
                    while True:
                        raw = self._recv_exact(4)
                        size = struct.unpack("!i", raw)[0]
                        payload = self._recv_exact(size)
                        resp = fake.handle_request(payload, session)
                        self.request.sendall(
                            struct.pack("!i", len(resp)) + resp
                        )
                except (ConnectionError, OSError):
                    return
                except Exception:
                    return  # TLS handshake failures etc.

            def _recv_exact(self, n):
                out = b""
                while len(out) < n:
                    chunk = self.request.recv(n - len(out))
                    if not chunk:
                        raise ConnectionError()
                    out += chunk
                return out

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Server(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()
        return self

    def stop(self):
        if self._srv:
            self._srv.shutdown()

    # -- dispatch -----------------------------------------------------------
    def handle_request(self, payload: bytes,
                       session: Optional[dict] = None) -> bytes:
        session = session if session is not None else {"authed": True}
        r = Reader(payload)
        api_key = r.i16()
        api_version = r.i16()
        corr = r.i32()
        r.string()  # client id
        if api_key == 17:
            return struct.pack("!i", corr) + self._sasl_handshake(r)
        if api_key == 36:
            return struct.pack("!i", corr) + \
                self._sasl_authenticate(r, session)
        if not session.get("authed"):
            # real brokers drop unauthenticated connections on SASL
            # listeners
            raise ConnectionError("unauthenticated request")
        body = {
            3: self._metadata,
            0: self._produce,
            1: self._fetch,
            2: self._list_offsets,
            22: self._init_producer_id,
        }.get(api_key, lambda _r: b"")(r)
        return struct.pack("!i", corr) + body

    def _sasl_handshake(self, r: Reader) -> bytes:
        mech = r.string() or ""
        want = self.sasl[0] if self.sasl else ""
        if not self.sasl or mech != want:
            return (struct.pack("!h", 33)  # UNSUPPORTED_SASL_MECHANISM
                    + struct.pack("!i", 1) + enc_str(want or "NONE"))
        return struct.pack("!h", 0) + struct.pack("!i", 1) + enc_str(want)

    def _sasl_authenticate(self, r: Reader, session: dict) -> bytes:
        from transferia_tpu.utils.scram import ScramError, ServerVerifier

        def resp(err: int, msg: Optional[str], auth: bytes) -> bytes:
            return (struct.pack("!h", err) + enc_str(msg)
                    + enc_bytes(auth) + struct.pack("!q", 0))

        data = r.bytes_() or b""
        mech, user, password = self.sasl
        self.auth_attempts += 1
        if mech == "PLAIN":
            parts = data.split(b"\x00")
            if len(parts) == 3 and parts[1].decode() == user \
                    and parts[2].decode() == password:
                session["authed"] = True
                return resp(0, None, b"")
            return resp(58, "bad credentials", b"")  # SASL_AUTH_FAILED
        try:
            if session.get("verifier") is None:
                session["verifier"] = ServerVerifier(mech, user, password)
                return resp(0, None, session["verifier"].first(data))
            out = session["verifier"].final(data)
            session["authed"] = True
            session["verifier"] = None
            return resp(0, None, out)
        except ScramError as e:
            session["verifier"] = None
            return resp(58, str(e), b"")

    def _metadata(self, r: Reader) -> bytes:
        n = r.i32()
        wanted = None
        if n >= 0:
            wanted = [r.string() for _ in range(n)]
        with self.lock:
            if wanted:
                for t in wanted:
                    if self.auto_create:
                        self.create_topic(t)
            names = wanted if wanted is not None else list(self.topics)
            out = struct.pack("!i", 1)  # one broker
            out += struct.pack("!i", 0) + _enc_str("127.0.0.1") \
                + struct.pack("!i", self.port) + _enc_str(None)
            out += struct.pack("!i", 0)  # controller
            out += struct.pack("!i", len(names))
            for name in names:
                parts = self.topics.get(name)
                err = 0 if parts is not None else 3
                out += struct.pack("!h", err) + _enc_str(name) + b"\x00"
                out += struct.pack("!i", len(parts or []))
                for pid in range(len(parts or [])):
                    out += struct.pack("!hiii", 0, pid, 0, 1)
                    out += struct.pack("!i", 0)       # replicas
                    out += struct.pack("!i", 0)       # isr
        return out

    def live_size(self, topic: str) -> int:
        """Record count excluding superseded transactional segments
        (offsets still cover them, like aborted-txn gaps on a real
        broker)."""
        with self.lock:
            n = 0
            for p in self.topics.get(topic, []):
                for seg in p._segments:
                    if seg[2] is None and seg[3] == []:
                        continue
                    n += seg[1]
            return n

    @staticmethod
    def _frame_producer_epoch(blob: bytes) -> int:
        """producerEpoch of the first v2 frame (offset 51 of the
        frame: 12-byte outer header + 39 bytes to the epoch field)."""
        if len(blob) < 61:
            return -1
        return struct.unpack_from("!h", blob, 51)[0]

    def _init_producer_id(self, r: Reader) -> bytes:
        """InitProducerId (KIP-360 shape): the client proposes its
        epoch; an OLDER proposal than the id's current epoch is fenced
        (error 90), else the id adopts the proposal."""
        txn_id = r.string()
        r.i32()              # transaction timeout
        r.i64()              # producer id proposal (-1)
        epoch = r.i16()
        with self.lock:
            state = self.txns.get(txn_id)
            if state is None:
                state = {"pid": self._next_pid, "epoch": epoch,
                         "published": []}
                self._next_pid += 1
                self.txns[txn_id] = state
            elif epoch < state["epoch"]:
                # fenced: disclose the id's current epoch so the
                # client's StaleEpochPublishError names the real winner
                return struct.pack("!ihqh", 0, 90, -1, state["epoch"])
            else:
                state["epoch"] = epoch
            return struct.pack("!ihqh", 0, 0, state["pid"],
                               state["epoch"])

    def _produce(self, r: Reader) -> bytes:
        txn_id = r.string()  # transactional id (None = plain produce)
        r.i16()              # acks
        r.i32()              # timeout
        incoming = []
        for _ in range(r.i32()):
            topic = r.string()
            for _ in range(r.i32()):
                partition = r.i32()
                blob = r.bytes_() or b""
                incoming.append((topic, partition, blob))
        err = 0
        bases = {}
        with self.lock:
            state = self.txns.get(txn_id) if txn_id else None
            if txn_id is not None:
                if state is None:
                    err = 47  # unknown producer for the txn id
                else:
                    for _t, _p, blob in incoming:
                        if self._frame_producer_epoch(blob) \
                                < state["epoch"]:
                            err = 47  # stale producer epoch: fenced
                            break
            if not err:
                if state is not None:
                    # one transactional produce = one committed
                    # transaction: SUPERSEDE the previous publish of
                    # this transactional id in place (offsets keep
                    # their slots, like aborted-txn gaps)
                    for _t, _p, seg in state["published"]:
                        seg[2] = None
                        seg[3] = []
                    state["published"] = []
                for topic, partition, blob in incoming:
                    self.create_topic(topic)
                    plist = self.topics[topic][partition]
                    bases[(topic, partition)] = len(plist)
                    segs_before = len(plist._segments)
                    # store the raw blob (a real broker never decodes);
                    # unparseable frames fall back to eager decode so
                    # protocol tests still see their errors on produce
                    if not plist.append_blob(blob):
                        for rec in decode_record_batches(blob):
                            plist.append(rec)
                    if state is not None:
                        for seg in plist._segments[segs_before:]:
                            state["published"].append(
                                (topic, partition, seg))
        out = struct.pack("!i", len(incoming))
        for topic, partition, _blob in incoming:
            base = bases.get((topic, partition), -1)
            out += _enc_str(topic) + struct.pack("!i", 1)
            out += struct.pack("!ihqq", partition, err, base, -1)
        out += struct.pack("!i", 0)  # throttle
        return out

    def _list_offsets(self, r: Reader) -> bytes:
        r.i32()  # replica id
        out = b""
        n_topics = r.i32()
        out += struct.pack("!i", n_topics)
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            out += _enc_str(topic) + struct.pack("!i", n_parts)
            for _ in range(n_parts):
                partition = r.i32()
                ts = r.i64()
                with self.lock:
                    plist = self.topics.get(topic, [[]] * (partition + 1))
                    n = len(plist[partition]) if partition < len(plist) \
                        else 0
                offset = 0 if ts == -2 else n
                out += struct.pack("!ihqq", partition, 0, -1, offset)
        return out

    def _fetch(self, r: Reader) -> bytes:
        r.i32()  # replica
        r.i32()  # max wait
        r.i32()  # min bytes
        r.i32()  # max bytes
        r.i8()   # isolation
        n_topics = r.i32()
        out = struct.pack("!i", 0)  # throttle
        out += struct.pack("!i", n_topics)
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            out += _enc_str(topic) + struct.pack("!i", n_parts)
            for _ in range(n_parts):
                partition = r.i32()
                offset = r.i64()
                r.i32()  # partition max bytes
                with self.lock:
                    plist = self.topics.get(topic)
                    if plist is not None:
                        log = plist[partition]
                        high = len(log)
                        # stored frames serve verbatim (batch-aligned,
                        # like a real broker; clients trim the head)
                        blob = log.raw_from(offset)
                    else:
                        blob = b""
                        high = 0
                out += struct.pack("!ihqq", partition, 0, high, high)
                out += struct.pack("!i", 0)   # aborted txns
                out += struct.pack("!i", len(blob)) + blob
        return out
