"""In-process fake Kafka broker (wire-protocol subset).

Server side of what the provider's client speaks: ApiVersions ignored,
Metadata v1, Produce v3 (stores the raw record batch, re-serving it on
fetch — a real broker does the same), Fetch v4, ListOffsets v1.
"""

from __future__ import annotations

import socketserver
import struct
import threading
from typing import Optional

from transferia_tpu.providers.kafka.protocol import (
    Reader,
    decode_record_batches,
    enc_bytes,
    enc_str,
    enc_str as _enc_str,
    encode_record_batch,
)


class FakeKafka:
    def __init__(self, n_partitions: int = 2,
                 auto_create_topics: bool = True,
                 sasl: Optional[tuple] = None,
                 tls_cert: Optional[tuple] = None):
        """sasl: (mechanism, username, password) to REQUIRE auth;
        tls_cert: (certfile, keyfile) to serve TLS."""
        self.n_partitions = n_partitions
        self.auto_create = auto_create_topics
        # topic -> partition -> list[Record] (absolute offsets = index)
        self.topics: dict[str, list[list]] = {}
        self.lock = threading.RLock()
        self.port = 0
        self._srv = None
        self.sasl = sasl
        self.auth_attempts = 0
        self._ssl_ctx = None
        if tls_cert is not None:
            import ssl

            self._ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            self._ssl_ctx.load_cert_chain(tls_cert[0], tls_cert[1])

    def create_topic(self, name: str,
                     n_partitions: Optional[int] = None) -> None:
        with self.lock:
            if name not in self.topics:
                self.topics[name] = [
                    [] for _ in range(n_partitions or self.n_partitions)
                ]

    def records(self, topic: str, partition: int = 0) -> list:
        with self.lock:
            return list(self.topics.get(topic, [[]])[partition])

    def size(self, topic: str) -> int:
        with self.lock:
            return sum(len(p) for p in self.topics.get(topic, []))

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "FakeKafka":
        fake = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    if fake._ssl_ctx is not None:
                        self.request = fake._ssl_ctx.wrap_socket(
                            self.request, server_side=True)
                    session = {"authed": fake.sasl is None,
                               "verifier": None}
                    while True:
                        raw = self._recv_exact(4)
                        size = struct.unpack("!i", raw)[0]
                        payload = self._recv_exact(size)
                        resp = fake.handle_request(payload, session)
                        self.request.sendall(
                            struct.pack("!i", len(resp)) + resp
                        )
                except (ConnectionError, OSError):
                    return
                except Exception:
                    return  # TLS handshake failures etc.

            def _recv_exact(self, n):
                out = b""
                while len(out) < n:
                    chunk = self.request.recv(n - len(out))
                    if not chunk:
                        raise ConnectionError()
                    out += chunk
                return out

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Server(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()
        return self

    def stop(self):
        if self._srv:
            self._srv.shutdown()

    # -- dispatch -----------------------------------------------------------
    def handle_request(self, payload: bytes,
                       session: Optional[dict] = None) -> bytes:
        session = session if session is not None else {"authed": True}
        r = Reader(payload)
        api_key = r.i16()
        api_version = r.i16()
        corr = r.i32()
        r.string()  # client id
        if api_key == 17:
            return struct.pack("!i", corr) + self._sasl_handshake(r)
        if api_key == 36:
            return struct.pack("!i", corr) + \
                self._sasl_authenticate(r, session)
        if not session.get("authed"):
            # real brokers drop unauthenticated connections on SASL
            # listeners
            raise ConnectionError("unauthenticated request")
        body = {
            3: self._metadata,
            0: self._produce,
            1: self._fetch,
            2: self._list_offsets,
        }.get(api_key, lambda _r: b"")(r)
        return struct.pack("!i", corr) + body

    def _sasl_handshake(self, r: Reader) -> bytes:
        mech = r.string() or ""
        want = self.sasl[0] if self.sasl else ""
        if not self.sasl or mech != want:
            return (struct.pack("!h", 33)  # UNSUPPORTED_SASL_MECHANISM
                    + struct.pack("!i", 1) + enc_str(want or "NONE"))
        return struct.pack("!h", 0) + struct.pack("!i", 1) + enc_str(want)

    def _sasl_authenticate(self, r: Reader, session: dict) -> bytes:
        from transferia_tpu.utils.scram import ScramError, ServerVerifier

        def resp(err: int, msg: Optional[str], auth: bytes) -> bytes:
            return (struct.pack("!h", err) + enc_str(msg)
                    + enc_bytes(auth) + struct.pack("!q", 0))

        data = r.bytes_() or b""
        mech, user, password = self.sasl
        self.auth_attempts += 1
        if mech == "PLAIN":
            parts = data.split(b"\x00")
            if len(parts) == 3 and parts[1].decode() == user \
                    and parts[2].decode() == password:
                session["authed"] = True
                return resp(0, None, b"")
            return resp(58, "bad credentials", b"")  # SASL_AUTH_FAILED
        try:
            if session.get("verifier") is None:
                session["verifier"] = ServerVerifier(mech, user, password)
                return resp(0, None, session["verifier"].first(data))
            out = session["verifier"].final(data)
            session["authed"] = True
            session["verifier"] = None
            return resp(0, None, out)
        except ScramError as e:
            session["verifier"] = None
            return resp(58, str(e), b"")

    def _metadata(self, r: Reader) -> bytes:
        n = r.i32()
        wanted = None
        if n >= 0:
            wanted = [r.string() for _ in range(n)]
        with self.lock:
            if wanted:
                for t in wanted:
                    if self.auto_create:
                        self.create_topic(t)
            names = wanted if wanted is not None else list(self.topics)
            out = struct.pack("!i", 1)  # one broker
            out += struct.pack("!i", 0) + _enc_str("127.0.0.1") \
                + struct.pack("!i", self.port) + _enc_str(None)
            out += struct.pack("!i", 0)  # controller
            out += struct.pack("!i", len(names))
            for name in names:
                parts = self.topics.get(name)
                err = 0 if parts is not None else 3
                out += struct.pack("!h", err) + _enc_str(name) + b"\x00"
                out += struct.pack("!i", len(parts or []))
                for pid in range(len(parts or [])):
                    out += struct.pack("!hiii", 0, pid, 0, 1)
                    out += struct.pack("!i", 0)       # replicas
                    out += struct.pack("!i", 0)       # isr
        return out

    def _produce(self, r: Reader) -> bytes:
        r.string()           # transactional id
        r.i16()              # acks
        r.i32()              # timeout
        out_topics = []
        for _ in range(r.i32()):
            topic = r.string()
            for _ in range(r.i32()):
                partition = r.i32()
                blob = r.bytes_() or b""
                records = decode_record_batches(blob)
                with self.lock:
                    self.create_topic(topic)
                    plist = self.topics[topic][partition]
                    base = len(plist)
                    for i, rec in enumerate(records):
                        rec.offset = base + i
                        plist.append(rec)
                out_topics.append((topic, partition, base))
        out = struct.pack("!i", len(out_topics))
        for topic, partition, base in out_topics:
            out += _enc_str(topic) + struct.pack("!i", 1)
            out += struct.pack("!ihqq", partition, 0, base, -1)
        out += struct.pack("!i", 0)  # throttle
        return out

    def _list_offsets(self, r: Reader) -> bytes:
        r.i32()  # replica id
        out = b""
        n_topics = r.i32()
        out += struct.pack("!i", n_topics)
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            out += _enc_str(topic) + struct.pack("!i", n_parts)
            for _ in range(n_parts):
                partition = r.i32()
                ts = r.i64()
                with self.lock:
                    plist = self.topics.get(topic, [[]] * (partition + 1))
                    n = len(plist[partition]) if partition < len(plist) \
                        else 0
                offset = 0 if ts == -2 else n
                out += struct.pack("!ihqq", partition, 0, -1, offset)
        return out

    def _fetch(self, r: Reader) -> bytes:
        r.i32()  # replica
        r.i32()  # max wait
        r.i32()  # min bytes
        r.i32()  # max bytes
        r.i8()   # isolation
        n_topics = r.i32()
        out = struct.pack("!i", 0)  # throttle
        out += struct.pack("!i", n_topics)
        for _ in range(n_topics):
            topic = r.string()
            n_parts = r.i32()
            out += _enc_str(topic) + struct.pack("!i", n_parts)
            for _ in range(n_parts):
                partition = r.i32()
                offset = r.i64()
                r.i32()  # partition max bytes
                with self.lock:
                    plist = self.topics.get(topic)
                    records = plist[partition][offset:offset + 1000] \
                        if plist else []
                    high = len(plist[partition]) if plist else 0
                if records:
                    blob = encode_record_batch(
                        records, base_offset=records[0].offset
                    )
                else:
                    blob = b""
                out += struct.pack("!ihqq", partition, 0, high, high)
                out += struct.pack("!i", 0)   # aborted txns
                out += struct.pack("!i", len(blob)) + blob
        return out
