"""In-process fake ClickHouse HTTP endpoint.

Implements the subset of the HTTP interface the provider uses: query param
parsing, CREATE/DROP/TRUNCATE TABLE, INSERT ... FORMAT RowBinary (payload
decoded with an independent minimal decoder), SELECT count()/system
queries with FORMAT JSON/JSONCompact.  Runs the real CHClient against real
sockets — only the server side is fake.
"""

from __future__ import annotations

import json
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class _LazyTable(dict):
    """Table entry whose RowBinary inserts decode lazily.

    INSERT bodies are structure-validated and row-COUNTED at insert time
    (cheap walk), but full Python row objects materialize only when
    someone reads ["rows"] — benches poll counts at high frequency and a
    real server never builds Python rows at all."""

    def __getitem__(self, key):
        if key == "rows":
            pend = dict.__getitem__(self, "pending")
            if pend:
                rows = dict.__getitem__(self, "rows")
                for body, col_names, types, _count in pend:
                    decoded = _decode_rowbinary_rows(body, types)
                    rows.extend(dict(zip(col_names, r)) for r in decoded)
                pend.clear()
        return dict.__getitem__(self, key)

    def __setitem__(self, key, value):
        if key == "rows":  # truncate: discard pending blobs too
            dict.__getitem__(self, "pending").clear()
        dict.__setitem__(self, key, value)

    def row_count(self) -> int:
        # materialized rows (tests may mutate that list directly) plus
        # not-yet-decoded inserts
        return (len(dict.__getitem__(self, "rows"))
                + sum(c for _, _, _, c in
                      dict.__getitem__(self, "pending")))


class FakeCH:
    def __init__(self):
        self.tables: dict[str, dict] = {}   # name -> {ddl, columns, rows}
        # system.clusters rows for topology discovery tests:
        # {cluster, shard_num, replica_num, host_name, host_address, port}
        self.clusters: list[dict] = []
        self.queries: list[str] = []
        self.lock = threading.Lock()
        self._srv: ThreadingHTTPServer | None = None
        self.port = 0

    def total_rows(self) -> int:
        """Inserted-row count WITHOUT materializing rows (cheap to
        poll).  Staging-plane tables (__trtpu_*: commits fence rows,
        per-part staging) are transferia machinery, not delivered
        data — excluded so pollers count what a consumer would see."""
        with self.lock:
            return sum(t.row_count() if isinstance(t, _LazyTable)
                       else len(t["rows"])
                       for n, t in self.tables.items()
                       if not n.startswith("__trtpu"))

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "FakeCH":
        fake = self

        class Handler(BaseHTTPRequestHandler):
            # real ClickHouse speaks HTTP/1.1 with keep-alive; the client
            # pools per-thread connections, so the fake must match
            protocol_version = "HTTP/1.1"

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length)
                qs = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query
                )
                query = (qs.get("query") or [""])[0]
                try:
                    out = fake.handle(query, body)
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(out)))
                    self.end_headers()
                    self.wfile.write(out)
                except Exception as e:
                    msg = str(e).encode()
                    self.send_response(500)
                    self.send_header("Content-Length", str(len(msg)))
                    self.end_headers()
                    self.wfile.write(msg)

            def log_message(self, *a):
                pass

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_port
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()
        return self

    def stop(self):
        if self._srv:
            self._srv.shutdown()

    # -- protocol -----------------------------------------------------------
    def handle(self, query: str, body: bytes) -> bytes:
        with self.lock:
            self.queries.append(query)
        q = query.strip()
        low = q.lower()
        if low == "select 1":
            return b"1\n"
        if "from system.clusters" in low:
            import json as _json

            m = re.search(r"cluster = '([^']*)'", q)
            name = m.group(1) if m else ""
            with self.lock:
                rows = [r for r in self.clusters if r["cluster"] == name]
            return _json.dumps({"data": rows}).encode()
        m = re.match(r"create table if not exists `?(\w+)`?\s*\((.*)\)\s*"
                     r"engine\s*=\s*(.*?)\s+order by", low, re.S)
        if m:
            name = re.match(
                r"CREATE TABLE IF NOT EXISTS `?(\w+)`?", q, re.I
            ).group(1)
            cols = self._parse_ddl_cols(q)
            mo = re.search(r"ORDER BY \(([^)]*)\)", q, re.I)
            order_by = [c.strip().strip("`")
                        for c in mo.group(1).split(",")] if mo else []
            with self.lock:
                if name not in self.tables:
                    self.tables[name] = _LazyTable({
                        "ddl": q, "columns": cols, "rows": [],
                        "pending": [],
                        "order_by": [c for c in order_by if c],
                    })
            return b""
        m = re.match(r"(drop|truncate) table if exists `?(\w+)`?", low)
        if m:
            with self.lock:
                if m.group(1) == "drop":
                    self.tables.pop(m.group(2), None)
                elif m.group(2) in self.tables:
                    self.tables[m.group(2)]["rows"] = []
            return b""
        m = re.match(r"alter table `?(\w+)`? replace partition id "
                     r"'([^']*)' from `?(\w+)`?", low)
        if m:
            # the staged-commit publish: partition `slug` of the final
            # table atomically becomes the staging table's rows (rows
            # carry partition membership in their __trtpu_part value)
            final = re.match(r"ALTER TABLE `?(\w+)`?", q, re.I).group(1)
            src_name = re.search(r"FROM `?(\w+)`?\s*$", q, re.I).group(1)
            slug = m.group(2)
            with self.lock:
                dst = self.tables.get(final)
                src = self.tables.get(src_name)
                if dst is None or src is None:
                    raise ValueError("no such table for REPLACE PARTITION")
                moved = []
                for row in src["rows"]:
                    row = dict(row)
                    row["__trtpu_part"] = slug
                    moved.append(row)
                kept = [r for r in dst["rows"]
                        if r.get("__trtpu_part") != slug]
                dst["rows"] = kept + moved
            return b""
        m = re.match(r"alter table `?(\w+)`? drop partition id '([^']*)'",
                     low)
        if m:
            final = re.match(r"ALTER TABLE `?(\w+)`?", q, re.I).group(1)
            slug = m.group(2)
            with self.lock:
                dst = self.tables.get(final)
                if dst is not None:
                    dst["rows"] = [r for r in dst["rows"]
                                   if r.get("__trtpu_part") != slug]
            return b""
        m = re.match(r"select max\(`?(\w+)`?\) from `?(\w+)`? "
                     r"where `?(\w+)`? = '([^']*)'", low)
        if m:
            col_name = re.search(r"max\(`?(\w+)`?\)", q, re.I).group(1)
            tbl = re.search(r"FROM `?(\w+)`?", q, re.I).group(1)
            kcol = re.search(r"WHERE `?(\w+)`?", q, re.I).group(1)
            kval = m.group(4)
            with self.lock:
                t = self.tables.get(tbl)
                vals = []
                if t is not None:
                    for r in t["rows"]:
                        rv = r.get(kcol)
                        if isinstance(rv, bytes):
                            rv = rv.decode()
                        if rv == kval and r.get(col_name) is not None:
                            vals.append(int(r[col_name]))
            best = max(vals) if vals else None
            return json.dumps({"data": [[best]]}).encode()
        m = re.match(r"insert into `?(\w+)`?\s*\((.*?)\)\s*format rowbinary",
                     low, re.S)
        if m:
            name = re.match(r"INSERT INTO `?(\w+)`?", q, re.I).group(1)
            col_names = [
                c.strip().strip("`")
                for c in re.search(r"\((.*?)\)", q, re.S).group(1).split(",")
            ]
            with self.lock:
                table = self.tables.get(name)
                if table is None:
                    raise ValueError(f"Table {name} does not exist")
                types = [table["columns"][c] for c in col_names]
                # validate structure + count rows now; decode lazily
                n = _count_rowbinary_rows(body, types)
                table["pending"].append((body, col_names, types, n))
            return b""
        m = re.match(r"select (.*) from `?(\w+)`?\s*(.*?)\s*"
                     r"format rowbinary", low, re.S)
        if m:
            name = re.search(r"FROM `?(\w+)`?", q, re.I).group(1)
            with self.lock:
                t = self.tables.get(name)
                if t is None:
                    raise ValueError(f"Table {name} does not exist")
                sel = re.match(r"SELECT (.*?) FROM", q, re.S | re.I).group(1)
                cols = []
                for expr in sel.split(","):
                    expr = expr.strip()
                    mm = re.match(r"toString\(`(\w+)`\) AS", expr)
                    cols.append(mm.group(1) if mm
                                else expr.strip("`"))
                rows = self._filter_rows(t["rows"], q)
                return _encode_rowbinary_rows(
                    rows, cols,
                    [t["columns"][c] for c in cols],
                )
        if "from system.tables" in low:
            mn = re.search(r"name = '(\w+)'", q)
            with self.lock:
                if mn and low.startswith("select count()"):
                    n = 1 if mn.group(1) in self.tables else 0
                    return json.dumps({"data": [[n]]}).encode()
                data = [
                    {"name": n, "total_rows": len(t["rows"])}
                    for n, t in self.tables.items()
                ]
            return json.dumps({"data": data}).encode()
        if "from system.parts" in low:
            m = re.search(r"table = '(\w+)'", q)
            with self.lock:
                t = self.tables.get(m.group(1)) if m else None
                size = len(t["rows"]) * 100 if t else 0
            return json.dumps({"data": [[size]]}).encode()
        m = re.match(r"select count\(\) from `?(\w+)`?", low)
        if m:
            with self.lock:
                t = self.tables.get(m.group(1))
                # .get("rows") would bypass _LazyTable and miss pending
                # undecoded inserts; row_count() covers them
                n = (t.row_count() if isinstance(t, _LazyTable)
                     else len(t["rows"]) if t else 0)
            return json.dumps({"data": [[n]]}).encode()
        if "from system.columns" in low:
            m = re.search(r"table = '(\w+)'", q)
            with self.lock:
                t = self.tables.get(m.group(1)) if m else None
                keys = t.get("order_by", []) if t else []
                data = [
                    {"name": c, "type": typ,
                     "is_in_primary_key": 1 if c in keys else 0}
                    for c, typ in (t["columns"].items() if t else [])
                ]
            return json.dumps({"data": data}).encode()
        raise ValueError(f"fake CH: unhandled query: {q[:120]}")

    @staticmethod
    def _filter_rows(rows: list[dict], sql: str) -> list[dict]:
        """Evaluate the WHERE/ORDER BY/LIMIT shapes the storage emits
        (checksum sampling: rand() cutoff, ORed key equality, top/bottom
        ordering)."""
        rows = list(rows)
        mw = re.search(r"WHERE (.*?)(?: ORDER BY | LIMIT | FORMAT )",
                       sql, re.S | re.I)
        if mw:
            cond = mw.group(1).strip()
            if "rand()" in cond:
                rows = rows[::7]   # deterministic "random" subsample
            elif "` = " in cond:
                keysets = []
                for group in re.findall(r"\(([^()]*)\)", cond):
                    want = {}
                    for eq in group.split(" AND "):
                        mk = re.match(r"\s*`(\w+)`\s*=\s*(.+)\s*", eq)
                        if mk:
                            want[mk.group(1)] = mk.group(2).strip()
                    if want:
                        keysets.append(want)

                def lit(v):
                    if v is None:
                        return "NULL"
                    if isinstance(v, bool):
                        return "1" if v else "0"
                    if isinstance(v, (int, float)):
                        return str(v)
                    s = str(v).replace("\\", "\\\\").replace("'", "\\'")
                    return f"'{s}'"

                rows = [
                    r for r in rows
                    if any(all(lit(r.get(k)) == v for k, v in ks.items())
                           for ks in keysets)
                ]
        mo = re.search(r"ORDER BY (.+?)(?: LIMIT | FORMAT )", sql,
                       re.S | re.I)
        if mo:
            for part in reversed(mo.group(1).split(",")):
                part = part.strip()
                desc = part.upper().endswith(" DESC")
                name = part.split()[0].strip("`")
                rows = sorted(
                    rows,
                    key=lambda r: (r.get(name) is None, r.get(name)),
                    reverse=desc,
                )
        ml = re.search(r"LIMIT (\d+)", sql, re.I)
        if ml:
            rows = rows[: int(ml.group(1))]
        return rows

    @staticmethod
    def _parse_ddl_cols(ddl: str) -> dict[str, str]:
        inner = re.search(r"\((.*)\)\s*ENGINE", ddl, re.S | re.I).group(1)
        cols = {}
        depth = 0
        current = ""
        parts = []
        for ch in inner:
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            if ch == "," and depth == 0:
                parts.append(current)
                current = ""
            else:
                current += ch
        if current.strip():
            parts.append(current)
        for p in parts:
            toks = p.strip().split(None, 1)
            cols[toks[0].strip("`")] = toks[1].strip()
        return cols

    def rows(self, table: str) -> list[dict]:
        with self.lock:
            t = self.tables.get(table)
            if t is None:
                return []
            # NOTE: dict.get would bypass _LazyTable.__getitem__ and miss
            # pending (undecoded) inserts — index, don't .get
            return list(t["rows"])


# -- independent minimal RowBinary decoder (not the framework's) ------------

import struct

_FIXED = {
    "Int8": ("<b", 1), "Int16": ("<h", 2), "Int32": ("<i", 4),
    "Int64": ("<q", 8), "UInt8": ("<B", 1), "UInt16": ("<H", 2),
    "UInt32": ("<I", 4), "UInt64": ("<Q", 8), "Float32": ("<f", 4),
    "Float64": ("<d", 8), "Bool": ("<B", 1), "Date32": ("<i", 4),
    "DateTime": ("<I", 4), "DateTime64(6)": ("<q", 8),
}


def _encode_varint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _encode_rowbinary_rows(rows: list[dict], cols: list[str],
                           types: list[str]) -> bytes:
    out = b""
    for row in rows:
        for c, t in zip(cols, types):
            v = row.get(c)
            nullable = t.startswith("Nullable(")
            base = t[9:-1] if nullable else t
            if nullable:
                if v is None:
                    out += b"\x01"
                    continue
                out += b"\x00"
            if base in _FIXED:
                fmt, w = _FIXED[base]
                if base in ("Float32", "Float64"):
                    v = float(v or 0)
                elif base == "Bool":
                    v = 1 if v in (True, "True", "true", 1) else 0
                else:
                    v = int(v or 0)
                out += struct.pack(fmt, v)
            else:
                raw = v if isinstance(v, bytes) else str(v or "").encode()
                out += _encode_varint(len(raw)) + raw
    return out


def _count_rowbinary_rows(data: bytes, types: list[str]) -> int:
    """Walk-only structural validation + row count (no Python objects).
    Raises on malformed payloads exactly where the decoder would."""
    pos = 0
    n = len(data)
    count = 0
    while pos < n:
        for t in types:
            nullable = t.startswith("Nullable(")
            base = t[9:-1] if nullable else t
            if nullable:
                if data[pos] == 1:
                    pos += 1
                    continue
                pos += 1
            if base in _FIXED:
                pos += _FIXED[base][1]
            elif base == "String":
                ln = 0
                shift = 0
                while True:
                    b = data[pos]
                    pos += 1
                    ln |= (b & 0x7F) << shift
                    if not b & 0x80:
                        break
                    shift += 7
                pos += ln
            else:
                raise ValueError(f"fake CH decoder: type {t}")
        if pos > n:
            raise ValueError("rowbinary payload truncated")
        count += 1
    return count


def _decode_rowbinary_rows(data: bytes, types: list[str]) -> list[list]:
    pos = 0
    rows = []
    while pos < len(data):
        row = []
        for t in types:
            nullable = t.startswith("Nullable(")
            base = t[9:-1] if nullable else t
            if nullable:
                flag = data[pos]
                pos += 1
                if flag == 1:
                    row.append(None)
                    continue
            if base in _FIXED:
                fmt, w = _FIXED[base]
                v = struct.unpack_from(fmt, data, pos)[0]
                pos += w
                row.append(bool(v) if base == "Bool" else v)
            elif base == "String":
                ln = 0
                shift = 0
                while True:
                    b = data[pos]
                    pos += 1
                    ln |= (b & 0x7F) << shift
                    if not b & 0x80:
                        break
                    shift += 7
                row.append(data[pos:pos + ln])
                pos += ln
            else:
                raise ValueError(f"fake CH decoder: type {t}")
        rows.append(row)
    return rows
