"""In-process fake Greenplum: FakePG plus the segment side of gpfdist.

The provider's segment-direct path issues only CONTROL statements over
the master connection (CREATE EXTERNAL TABLE / INSERT...SELECT); the
data moves between "segments" and the worker's gpfdist HTTP endpoint.
This fake plays the segments: on INSERT INTO a writable external table
it splits the source rows across n_segments and POSTs each share as CSV
to the table's gpfdist location (with the X-GP headers and the final
X-GP-DONE marker); on INSERT...SELECT from a readable external table it
GETs CSV chunks until an empty body and stores the rows.
"""

from __future__ import annotations

import csv
import io
import re
import threading
import urllib.request

from tests.recipes.fake_postgres import FakePG

_CREATE_EXT = re.compile(
    r"create (writable |readable )?external table "
    r'"?([\w]+)"?\."?([\w]+)"? \((.*?)\) '
    r"location \('gpfdist://([^']+)'\) format 'csv'", re.I)
_LIKE = re.compile(r'like "?([\w]+)"?\."?([\w]+)"?', re.I)
_DROP_EXT = re.compile(
    r'drop external table (?:if exists )?"?([\w]+)"?\."?([\w]+)"?', re.I)
_INSERT_SELECT = re.compile(
    r'insert into "?([\w]+)"?\."?([\w]+)"?(?: \(([^)]*)\))? '
    r'select (.*?) from "?([\w]+)"?\."?([\w]+)"?\s*$', re.I | re.S)


class FakeGP(FakePG):
    def __init__(self, n_segments: int = 4, **kw):
        super().__init__(**kw)
        self.n_segments = n_segments
        # (ns, name) -> {"mode": "w"|"r", "url": ..., "like": (ns, name)}
        self.ext_tables: dict[tuple, dict] = {}
        self.sql_hook = self._gp_sql

    # -- segment data plane --------------------------------------------------
    def _segment_post(self, url: str, seg: int, rows: list[dict],
                      columns: list[str]) -> None:
        buf = io.StringIO()
        w = csv.writer(buf, lineterminator="\n")
        for r in rows:
            w.writerow([r.get(c, "") for c in columns])
        headers = {
            "X-GP-XID": "fake-xid",
            "X-GP-SEGMENT-ID": str(seg),
            "X-GP-SEGMENT-COUNT": str(self.n_segments),
            "Content-Type": "text/csv",
        }
        req = urllib.request.Request(
            url, data=buf.getvalue().encode(), headers=headers,
            method="POST")
        urllib.request.urlopen(req, timeout=30).read()
        done = urllib.request.Request(
            url, data=b"", headers={**headers, "X-GP-DONE": "1"},
            method="POST")
        urllib.request.urlopen(done, timeout=30).read()

    def _segment_get_all(self, url: str) -> list[list[str]]:
        out: list[list[str]] = []
        while True:
            req = urllib.request.Request(url, headers={
                "X-GP-XID": "fake-xid",
                "X-GP-SEGMENT-ID": "0",
                "X-GP-SEGMENT-COUNT": str(self.n_segments),
            })
            body = urllib.request.urlopen(req, timeout=30).read()
            if not body:
                return out
            out.extend(csv.reader(io.StringIO(
                body.decode("utf-8", "replace"))))

    # -- control-plane hook ---------------------------------------------------
    def _gp_sql(self, sql: str, low: str, session) -> bool:
        if "from gp_segment_configuration" in low:
            session.send_rows(["count"], [[self.n_segments]])
            return True
        m = _CREATE_EXT.match(" ".join(sql.split()))
        if m:
            mode = "r" if (m.group(1) or "").strip().lower() \
                == "readable" else "w"
            body = m.group(4)
            lk = _LIKE.match(body.strip())
            self.ext_tables[(m.group(2), m.group(3))] = {
                "mode": mode,
                "url": "http://" + m.group(5),
                "like": (lk.group(1), lk.group(2)) if lk else None,
            }
            session.send(b"C", b"CREATE EXTERNAL TABLE\x00")
            return True
        m = _DROP_EXT.match(" ".join(sql.split()))
        if m:
            self.ext_tables.pop((m.group(1), m.group(2)), None)
            session.send(b"C", b"DROP EXTERNAL TABLE\x00")
            return True
        m = _INSERT_SELECT.match(" ".join(sql.split()))
        if m:
            dst = (m.group(1), m.group(2))
            src = (m.group(5), m.group(6))
            ext = self.ext_tables.get(dst)
            if ext is not None and ext["mode"] == "w":
                # unload: play the segments POSTing the source's rows
                table = self.tables[src]
                cols = [c.strip().strip('"')
                        for c in m.group(4).split(",")] \
                    if m.group(4).strip() != "*" \
                    else [c[0] for c in table.columns]
                rows = list(table.rows)
                n = self.n_segments
                threads = []
                for seg in range(n):
                    share = rows[seg::n]
                    th = threading.Thread(
                        target=self._segment_post,
                        args=(ext["url"], seg, share, cols),
                        daemon=True)
                    th.start()
                    threads.append(th)
                for th in threads:
                    th.join(timeout=60)
                session.send(b"C", f"INSERT 0 {len(rows)}\x00".encode())
                return True
            ext = self.ext_tables.get(src)
            if ext is not None and ext["mode"] == "r":
                # load: play the segments GETting chunks until EOF
                target = self.tables[dst]
                cols = [c.strip().strip('"')
                        for c in m.group(3).split(",")] \
                    if m.group(3) else [c[0] for c in target.columns]
                got = self._segment_get_all(ext["url"])
                for vals in got:
                    target.rows.append(dict(zip(cols, vals)))
                session.send(b"C",
                             f"INSERT 0 {len(got)}\x00".encode())
                return True
        return False
