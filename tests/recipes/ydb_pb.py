"""Compile+import the YDB proto subset (cross-validation side).

Two paths to the generated message classes:

- `protoc` (the environment's native toolchain) when present — the
  canonical cross-validation parser, byte-for-byte what ydb-api-protos
  users run;
- a dynamic-descriptor fallback when only the protobuf RUNTIME is
  installed: `_parse_proto` is a minimal .proto parser covering exactly
  the subset grammar ydb_subset.proto uses (proto3 messages, nested
  oneofs, enums, repeated fields, one map<>), building a
  FileDescriptorProto the runtime turns into real message classes.
  Still an independent parser from the hand codec in
  transferia_tpu/providers/ydb/wire.py — the cross-validation property
  (both sides can't share one misread of the wire format) holds.

Tests that need it call load_pb() and skip when neither path works.
"""

from __future__ import annotations

import importlib
import os
import re
import shutil
import subprocess
import sys
import tempfile
import types

_cached = None

_SCALARS = {
    # proto scalar -> FieldDescriptorProto.Type value
    "double": 1, "float": 2, "int64": 3, "uint64": 4, "int32": 5,
    "bool": 8, "string": 9, "bytes": 12, "uint32": 13,
}
_TYPE_MESSAGE = 11
_TYPE_ENUM = 14
_LABEL_OPTIONAL = 1
_LABEL_REPEATED = 3


def _strip_comments(text: str) -> str:
    return re.sub(r"//[^\n]*", "", text)


def _blocks(text: str, kind: str):
    """Yield (name, body) for every top-level `kind name { ... }`."""
    for m in re.finditer(rf"\b{kind}\s+(\w+)\s*\{{", text):
        depth = 1
        pos = m.end()
        while depth:
            ch = text[pos]
            if ch == "{":
                depth += 1
            elif ch == "}":
                depth -= 1
            pos += 1
        yield m.group(1), text[m.end():pos - 1]


def _parse_proto(text: str, package: str):
    """ydb_subset.proto -> FileDescriptorProto (subset grammar only)."""
    from google.protobuf import descriptor_pb2

    text = _strip_comments(text)
    enums = dict(_blocks(text, "enum"))
    messages = dict(_blocks(text, "message"))

    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = "ydb_subset.proto"
    fdp.package = package
    fdp.syntax = "proto3"

    for name, body in enums.items():
        ed = fdp.enum_type.add()
        ed.name = name
        for vm in re.finditer(r"(\w+)\s*=\s*(\d+)\s*;", body):
            val = ed.value.add()
            val.name, val.number = vm.group(1), int(vm.group(2))

    def add_field(msg, name, number, type_name, repeated, oneof_index):
        f = msg.field.add()
        f.name = name
        f.number = number
        f.label = _LABEL_REPEATED if repeated else _LABEL_OPTIONAL
        if type_name in _SCALARS:
            f.type = _SCALARS[type_name]
        elif type_name in enums:
            f.type = _TYPE_ENUM
            f.type_name = f".{package}.{type_name}"
        elif type_name in messages:
            f.type = _TYPE_MESSAGE
            f.type_name = f".{package}.{type_name}"
        else:
            raise ValueError(f"unknown proto type {type_name!r}")
        if oneof_index is not None:
            f.oneof_index = oneof_index
        return f

    field_re = re.compile(
        r"(repeated\s+)?(map\s*<\s*(\w+)\s*,\s*(\w+)\s*>|\w+)\s+"
        r"(\w+)\s*=\s*(\d+)\s*;")

    for name, body in messages.items():
        md = fdp.message_type.add()
        md.name = name
        # carve out oneof groups first; remaining text = plain fields
        plain = body
        oneof_parts = []
        for om in re.finditer(r"oneof\s+(\w+)\s*\{([^}]*)\}", body):
            oneof_parts.append((om.group(1), om.group(2)))
            plain = plain.replace(om.group(0), "")
        for oneof_name, oneof_body in oneof_parts:
            idx = len(md.oneof_decl)
            md.oneof_decl.add().name = oneof_name
            for fm in field_re.finditer(oneof_body):
                add_field(md, fm.group(5), int(fm.group(6)),
                          fm.group(2), False, idx)
        for fm in field_re.finditer(plain):
            repeated = bool(fm.group(1))
            if fm.group(3):  # map<K, V>: synthesized entry message
                key_t, val_t = fm.group(3), fm.group(4)
                entry = md.nested_type.add()
                entry.name = "".join(
                    p.capitalize() for p in fm.group(5).split("_")
                ) + "Entry"
                entry.options.map_entry = True
                kf = entry.field.add()
                kf.name, kf.number = "key", 1
                kf.label = _LABEL_OPTIONAL
                kf.type = _SCALARS[key_t]
                vf = entry.field.add()
                vf.name, vf.number = "value", 2
                vf.label = _LABEL_OPTIONAL
                if val_t in _SCALARS:
                    vf.type = _SCALARS[val_t]
                else:
                    vf.type = _TYPE_MESSAGE
                    vf.type_name = f".{package}.{val_t}"
                f = md.field.add()
                f.name = fm.group(5)
                f.number = int(fm.group(6))
                f.label = _LABEL_REPEATED
                f.type = _TYPE_MESSAGE
                f.type_name = f".{package}.{name}.{entry.name}"
                continue
            add_field(md, fm.group(5), int(fm.group(6)), fm.group(2),
                      repeated, None)
    return fdp


def _dynamic_pb():
    """Build the message classes with the protobuf runtime only (no
    protoc binary).  Returns a module-like namespace exposing message
    classes and top-level enum values, like a generated pb2 module."""
    try:
        from google.protobuf import descriptor_pool, message_factory
    except ImportError:
        return None
    if not hasattr(message_factory, "GetMessageClass"):
        return None  # ancient runtime: keep the protoc-only behavior
    proto_path = os.path.join(os.path.dirname(__file__), "ydb_protos",
                              "ydb_subset.proto")
    with open(proto_path) as fh:
        fdp = _parse_proto(fh.read(), "ydb_subset")
    pool = descriptor_pool.DescriptorPool()
    file_desc = pool.Add(fdp)
    ns = types.SimpleNamespace()
    for name in file_desc.message_types_by_name:
        setattr(ns, name, message_factory.GetMessageClass(
            pool.FindMessageTypeByName(f"ydb_subset.{name}")))
    for enum in file_desc.enum_types_by_name.values():
        for value in enum.values:
            setattr(ns, value.name, value.number)
    return ns


def load_pb():
    global _cached
    if _cached is not None:
        return _cached
    if shutil.which("protoc") is None:
        _cached = _dynamic_pb()
        return _cached
    proto_dir = os.path.join(os.path.dirname(__file__), "ydb_protos")
    out_dir = tempfile.mkdtemp(prefix="ydb_pb_")
    subprocess.run(
        ["protoc", f"--python_out={out_dir}", "-I", proto_dir,
         "ydb_subset.proto"],
        check=True, capture_output=True,
    )
    sys.path.insert(0, out_dir)
    _cached = importlib.import_module("ydb_subset_pb2")
    return _cached
