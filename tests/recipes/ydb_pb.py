"""Compile+import the YDB proto subset (cross-validation side).

protoc is part of the environment's native toolchain; the generated module
is cached per test session in a temp dir.  Tests that need it call
load_pb() and skip when protoc is unavailable.
"""

from __future__ import annotations

import importlib
import os
import shutil
import subprocess
import sys
import tempfile

_cached = None


def load_pb():
    global _cached
    if _cached is not None:
        return _cached
    if shutil.which("protoc") is None:
        return None
    proto_dir = os.path.join(os.path.dirname(__file__), "ydb_protos")
    out_dir = tempfile.mkdtemp(prefix="ydb_pb_")
    subprocess.run(
        ["protoc", f"--python_out={out_dir}", "-I", proto_dir,
         "ydb_subset.proto"],
        check=True, capture_output=True,
    )
    sys.path.insert(0, out_dir)
    _cached = importlib.import_module("ydb_subset_pb2")
    return _cached
