"""In-memory fake YTsaurus HTTP proxy (api/v4 subset).

Implements what providers/yt/client.py speaks: light cypress commands
(get/list/exists/create/remove/set), no-op transactions, and the heavy
read_table/write_table pair with json list_fragment bodies, rich-YPath
row ranges (``[#lo:#hi]``) and the ``<append=%bool>`` modifier.  Optional
OAuth token enforcement so e2e suites exercise real auth.
"""

from __future__ import annotations

import json
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

RANGE_RE = re.compile(r"^(?P<path>.*?)\[#(?P<lo>\d*):#?(?P<hi>\d*)\]$")
APPEND_RE = re.compile(r"^<append=%(?P<append>true|false)>(?P<path>.*)$")


class FakeYT:
    def __init__(self, token: str = ""):
        self.token = token
        self.lock = threading.Lock()
        # path -> {"type": ..., "attrs": {...}, "rows": [...]}
        self.nodes: dict[str, dict] = {
            "//": {"type": "map_node", "attrs": {}},
        }
        self.port = 0
        self._srv = None
        self.requests: list[str] = []
        self._tx = 0

    # -- cypress helpers ----------------------------------------------------
    def add_table(self, path: str, schema: list[dict],
                  rows: list[dict]) -> None:
        with self.lock:
            self._mk_parents(path)
            self.nodes[path] = {
                "type": "table",
                "attrs": {"schema": schema},
                "rows": list(rows),
            }

    def _mk_parents(self, path: str) -> None:
        parts = path.lstrip("/").split("/")
        cur = "/"
        for p in parts[:-1]:
            cur = f"{cur}/{p}"
            self.nodes.setdefault(
                cur, {"type": "map_node", "attrs": {}})

    def _children(self, path: str) -> list[str]:
        prefix = path.rstrip("/") + "/"
        out = set()
        for p in self.nodes:
            if p.startswith(prefix) and p != path:
                rest = p[len(prefix):]
                if rest and "/" not in rest:
                    out.add(rest)
        return sorted(out)

    # -- server -------------------------------------------------------------
    def start(self) -> "FakeYT":
        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _auth_ok(self) -> bool:
                if not fake.token:
                    return True
                return (self.headers.get("Authorization", "")
                        == f"OAuth {fake.token}")

            def _send(self, status, obj=None, raw: bytes = b""):
                body = raw if raw else (
                    json.dumps(obj).encode() if obj is not None else b"")
                self.send_response(status)
                self.send_header("Content-Type",
                                 "application/octet-stream" if raw
                                 else "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _handle(self, method: str):
                parsed = urllib.parse.urlparse(self.path)
                command = parsed.path.rsplit("/", 1)[-1]
                fake.requests.append(command)
                q = {k: v[0] for k, v in
                     urllib.parse.parse_qs(parsed.query).items()}
                if not self._auth_ok():
                    return self._send(401, {"message": "unauthorized"})
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                try:
                    out = fake.dispatch(command, q, body)
                except KeyError as e:
                    return self._send(404, {"message": f"missing {e}"})
                except ValueError as e:
                    return self._send(400, {"message": str(e)})
                if isinstance(out, bytes):
                    return self._send(200, raw=out)
                return self._send(200, out)

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def do_PUT(self):
                self._handle("PUT")

            def log_message(self, *a):
                pass

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()
        return self

    def stop(self) -> None:
        if self._srv:
            self._srv.shutdown()
            self._srv.server_close()

    # -- command dispatch ---------------------------------------------------
    def dispatch(self, command: str, q: dict, body: bytes):
        with self.lock:
            if command == "get":
                return {"value": self._get_attr(q["path"])}
            if command == "list":
                node = self._node(q["path"])
                if node["type"] != "map_node":
                    raise ValueError("not a map node")
                return {"value": self._children(q["path"])}
            if command == "exists":
                return {"value": q["path"] in self.nodes}
            if command == "create":
                return self._create(q)
            if command == "remove":
                self.nodes.pop(q["path"], None)
                return {}
            if command == "set":
                path, _, attr = q["path"].rpartition("/@")
                self._node(path)["attrs"][attr] = json.loads(body)
                return {}
            if command == "start_transaction":
                self._tx += 1
                return {"transaction_id": f"tx-{self._tx}"}
            if command in ("commit_transaction", "abort_transaction"):
                return {}
            if command == "read_table":
                return self._read_table(q["path"])
            if command == "write_table":
                return self._write_table(q["path"], body)
            if command == "mount_table":
                node = self._node(q["path"])
                if not node["attrs"].get("dynamic"):
                    raise ValueError("cannot mount a static table")
                node["attrs"]["tablet_state"] = "mounted"
                node["attrs"].setdefault(
                    "pivot_keys",
                    node["attrs"].pop("_pivot_keys_on_mount", [[]]))
                node.setdefault("keyed_rows", {})
                return {}
            if command == "unmount_table":
                node = self._node(q["path"])
                node["attrs"]["tablet_state"] = "unmounted"
                return {}
            if command == "insert_rows":
                return self._insert_rows(q, body)
            if command == "delete_rows":
                return self._delete_rows(q, body)
        raise ValueError(f"unknown command {command}")

    # -- dynamic tables -----------------------------------------------------
    def _dyn_node(self, path: str) -> dict:
        node = self._node(path)
        if not node["attrs"].get("dynamic"):
            raise ValueError(f"{path} is not dynamic")
        if node["attrs"].get("tablet_state") != "mounted":
            raise ValueError(f"{path} is not mounted")
        return node

    def _key_names(self, node: dict) -> list[str]:
        return [c["name"] for c in node["attrs"].get("schema", [])
                if c.get("sort_order")]

    def _insert_rows(self, q: dict, body: bytes):
        node = self._dyn_node(q["path"])
        rows = [json.loads(line) for line in body.splitlines()
                if line.strip()]
        schema = {c["name"] for c in node["attrs"].get("schema", [])}
        for r in rows:
            unknown = set(r) - schema
            if unknown:
                raise ValueError(
                    f"columns {sorted(unknown)} not in schema")
        keys = self._key_names(node)
        if keys:  # sorted dyntable: upsert by key
            update = json.loads(q.get("update", "false"))
            store = node.setdefault("keyed_rows", {})
            for r in rows:
                k = tuple(r.get(n) for n in keys)
                if update and k in store:
                    store[k].update(r)
                else:
                    store[k] = dict(r)
            node["rows"] = [store[k] for k in sorted(
                store, key=lambda t: tuple(
                    (v is None, v) for v in t))]
        else:     # ordered dyntable: append-only log
            node["rows"].extend(rows)
        return {}

    def _delete_rows(self, q: dict, body: bytes):
        node = self._dyn_node(q["path"])
        keys = self._key_names(node)
        if not keys:
            raise ValueError("delete_rows needs a sorted table")
        store = node.setdefault("keyed_rows", {})
        for line in body.splitlines():
            if not line.strip():
                continue
            r = json.loads(line)
            store.pop(tuple(r.get(n) for n in keys), None)
        node["rows"] = [store[k] for k in sorted(
            store, key=lambda t: tuple((v is None, v) for v in t))]
        return {}

    def _node(self, path: str) -> dict:
        node = self.nodes.get(path)
        if node is None:
            raise KeyError(path)
        return node

    def _get_attr(self, path: str):
        if "/@" in path:
            base, _, attr = path.rpartition("/@")
            node = self._node(base)
            if attr == "type":
                return node["type"]
            if attr == "row_count":
                return len(node.get("rows", []))
            if attr in node["attrs"]:
                return node["attrs"][attr]
            raise KeyError(attr)
        node = self._node(path)
        if node["type"] == "map_node":
            return {c: {} for c in self._children(path)}
        return None

    def _create(self, q: dict):
        path = q["path"]
        if path in self.nodes:
            if json.loads(q.get("ignore_existing", "false")):
                return {}
            raise ValueError(f"node {path} already exists")
        if json.loads(q.get("recursive", "false")):
            self._mk_parents(path)
        attrs = json.loads(q.get("attributes", "{}"))
        node = {"type": q["type"], "attrs": attrs}
        if q["type"] == "table":
            node["rows"] = []
        self.nodes[path] = node
        return {}

    def _read_table(self, ypath: str) -> bytes:
        m = RANGE_RE.match(ypath)
        lo = hi = None
        if m:
            ypath = m.group("path")
            lo = int(m.group("lo")) if m.group("lo") else None
            hi = int(m.group("hi")) if m.group("hi") else None
        node = self._node(ypath)
        rows = node.get("rows", [])
        rows = rows[lo:hi]
        return b"".join(json.dumps(r).encode() + b"\n" for r in rows)

    def _write_table(self, ypath: str, body: bytes):
        append = True
        m = APPEND_RE.match(ypath)
        if m:
            append = m.group("append") == "true"
            ypath = m.group("path")
        node = self.nodes.get(ypath)
        if node is None or node["type"] != "table":
            raise KeyError(ypath)
        rows = [json.loads(line) for line in body.splitlines()
                if line.strip()]
        schema = {c["name"] for c in node["attrs"].get("schema", [])}
        if schema:
            for r in rows:
                unknown = set(r) - schema
                if unknown:
                    raise ValueError(
                        f"columns {sorted(unknown)} not in schema")
        if append:
            node["rows"].extend(rows)
        else:
            node["rows"] = rows
        return {}
