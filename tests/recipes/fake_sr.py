"""In-process Confluent Schema Registry fake (register + fetch)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class FakeSchemaRegistry:
    def __init__(self):
        self.schemas: dict[int, dict] = {}          # id -> {schema, type}
        self.by_subject: dict[str, list[int]] = {}  # subject -> versions
        self._dedup: dict[tuple[str, str], int] = {}
        self.lock = threading.Lock()
        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, status, obj):
                out = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            def do_GET(self):
                if self.path.startswith("/schemas/ids/"):
                    sid = int(self.path.rsplit("/", 1)[-1])
                    with fake.lock:
                        reg = fake.schemas.get(sid)
                    if reg is None:
                        return self._send(404, {"error_code": 40403})
                    return self._send(200, {
                        "schema": reg["schema"],
                        "schemaType": reg["type"],
                    })
                self._send(404, {"error_code": 404})

            def do_POST(self):
                if self.path.endswith("/versions") and \
                        self.path.startswith("/subjects/"):
                    subject = self.path.split("/")[2]
                    length = int(self.headers.get("Content-Length") or 0)
                    req = json.loads(self.rfile.read(length))
                    with fake.lock:
                        key = (subject, req["schema"])
                        sid = fake._dedup.get(key)
                        if sid is None:
                            sid = len(fake.schemas) + 1
                            fake.schemas[sid] = {
                                "schema": req["schema"],
                                "type": req.get("schemaType", "AVRO"),
                            }
                            fake._dedup[key] = sid
                            fake.by_subject.setdefault(
                                subject, []).append(sid)
                    return self._send(200, {"id": sid})
                self._send(404, {"error_code": 404})

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "FakeSchemaRegistry":
        self.thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
