"""Test recipes: in-process fakes of external systems.

Reference parity: tests/tcrecipes/ spins real services via testcontainers;
this image has no docker, so recipes are faithful in-process protocol fakes
(CH HTTP server, etc.) exercising the real wire clients.
"""
