"""In-process YDB fake: grpcio server speaking the API subset.

Implements table-service sessions, a small YQL evaluator covering the
query shapes the provider emits (paged SELECT with keyset cursors,
MIN/MAX, DELETE, CREATE/DROP TABLE), BulkUpsert, DescribeTable,
ListDirectory, and changefeed topics over StreamRead with per-consumer
committed offsets (redelivery on uncommitted reads).

Requests are decoded with protoc-generated code from
tests/recipes/ydb_protos/ydb_subset.proto — an independent parser from
the client's hand codec, so wire-format misunderstandings fail loudly in
e2e instead of passing both self-consistent sides.
"""

from __future__ import annotations

import json
import queue
import re
import threading
import time
from typing import Any, Optional

from tests.recipes.ydb_pb import load_pb


class FakeTable:
    def __init__(self, path: str, columns: list[tuple[str, str]],
                 primary_key: list[str]):
        self.path = path
        self.columns = columns        # [(name, ydb type name)]
        self.primary_key = primary_key
        self.rows: dict[tuple, dict] = {}
        self.changefeed_events: "queue.Queue[bytes]" = queue.Queue()
        self.feed_log: list[bytes] = []   # retained for redelivery

    def key_of(self, row: dict) -> tuple:
        return tuple(row.get(k) for k in self.primary_key)

    def upsert(self, row: dict, emit_cdc: bool = True) -> None:
        key = self.key_of(row)
        self.rows[key] = dict(row)
        if emit_cdc:
            ev = {"key": _cdc_json(list(key)),
                  "ts": [int(time.time()), len(self.feed_log)],
                  "update": _cdc_json({
                      k: v for k, v in row.items()
                      if k not in self.primary_key
                  })}
            self.feed_log.append(json.dumps(ev).encode())

    def erase(self, key: tuple) -> None:
        self.rows.pop(key, None)
        ev = {"key": list(key), "erase": {},
              "ts": [int(time.time()), len(self.feed_log)]}
        self.feed_log.append(json.dumps(ev).encode())


def _cdc_json(v):
    """YDB changefeed JSON encodes String (bytes) values as base64."""
    import base64

    if isinstance(v, bytes):
        return base64.b64encode(v).decode()
    if isinstance(v, list):
        return [_cdc_json(x) for x in v]
    if isinstance(v, dict):
        return {k: _cdc_json(x) for k, x in v.items()}
    return v


_TYPE_IDS = {
    "Bool": "BOOL", "Int8": "INT8", "Int16": "INT16", "Int32": "INT32",
    "Int64": "INT64", "Uint8": "UINT8", "Uint16": "UINT16",
    "Uint32": "UINT32", "Uint64": "UINT64", "Float": "FLOAT",
    "Double": "DOUBLE", "String": "STRING", "Utf8": "UTF8",
    "Json": "JSON", "JsonDocument": "JSON_DOCUMENT", "Date": "DATE",
    "Datetime": "DATETIME", "Timestamp": "TIMESTAMP",
    "Interval": "INTERVAL",
}


class FakeYDB:
    def __init__(self, database: str = "/local"):
        self.pb = load_pb()
        if self.pb is None:
            raise RuntimeError("protoc unavailable for the YDB fake")
        self.database = database.rstrip("/")
        self.tables: dict[str, FakeTable] = {}
        self.consumer_offsets: dict[tuple[str, str], int] = {}
        self.lock = threading.RLock()
        self.port = 0
        self._server = None
        self.queries: list[str] = []

    # -- data helpers -------------------------------------------------------
    def add_table(self, name: str, columns: list[tuple[str, str]],
                  primary_key: list[str],
                  rows: Optional[list[dict]] = None) -> FakeTable:
        t = FakeTable(name, columns, primary_key)
        for r in rows or []:
            t.upsert(r, emit_cdc=False)
        with self.lock:
            self.tables[name] = t
        return t

    def _resolve(self, path: str) -> Optional[FakeTable]:
        rel = path
        if rel.startswith(self.database + "/"):
            rel = rel[len(self.database) + 1:]
        rel = rel.strip("/")
        return self.tables.get(rel)

    # -- type helpers -------------------------------------------------------
    def _pb_type(self, ydb_name: str):
        t = self.pb.Type()
        t.optional_type.item.type_id = getattr(
            self.pb, _TYPE_IDS.get(ydb_name, "UTF8"))
        return t

    def _pb_value(self, ydb_name: str, v):
        val = self.pb.Value()
        if v is None:
            val.null_flag_value = 0
            return val
        if ydb_name == "Bool":
            val.bool_value = bool(v)
        elif ydb_name in ("Int8", "Int16", "Int32"):
            val.int32_value = int(v)
        elif ydb_name in ("Uint8", "Uint16", "Uint32", "Date",
                          "Datetime"):
            val.uint32_value = int(v)
        elif ydb_name in ("Int64", "Interval"):
            val.int64_value = int(v)
        elif ydb_name in ("Uint64", "Timestamp"):
            val.uint64_value = int(v)
        elif ydb_name == "Float":
            val.float_value = float(v)
        elif ydb_name == "Double":
            val.double_value = float(v)
        elif ydb_name == "String":
            val.bytes_value = v if isinstance(v, bytes) else \
                str(v).encode()
        else:
            val.text_value = v if isinstance(v, str) else str(v)
        return val

    # -- YQL evaluator (the provider's query shapes only) -------------------
    def run_yql(self, yql: str):
        self.queries.append(yql)
        yql = yql.strip()
        stmts = [s.strip() for s in yql.split(";") if s.strip()]
        if len(stmts) > 1:
            # multi-statement interactive transaction (staged-commit
            # publish): apply atomically — roll every table back when
            # any statement fails
            import copy

            with self.lock:
                snapshot = {name: copy.deepcopy(t.rows)
                            for name, t in self.tables.items()}
                try:
                    out = ([], 0)
                    for stmt in stmts:
                        out = self._run_one_yql(stmt)
                    return out
                except Exception:
                    for name, rows in snapshot.items():
                        if name in self.tables:
                            self.tables[name].rows = rows
                    raise
        return self._run_one_yql(yql)

    def _run_one_yql(self, yql: str):
        m = re.match(r"UPSERT INTO `(.+?)` SELECT \*, (.+?) AS `(.+?)` "
                     r"FROM `(.+?)`$", yql, re.DOTALL)
        if m:
            # staged-commit publish: copy the staging table's rows into
            # the final table with the literal part column appended
            dst = self._resolve(m.group(1))
            src = self._resolve(m.group(4))
            if dst is None or src is None:
                raise ValueError(f"no such table in {yql[:120]}")
            lit, _ = _parse_literal(m.group(2))
            col = m.group(3)
            with self.lock:
                for row in list(src.rows.values()):
                    row = dict(row)
                    row[col] = lit
                    dst.upsert(row, emit_cdc=False)
            return [], 0
        m = re.match(r"UPSERT INTO `(.+?)` \((.+?)\) VALUES \((.+)\)$",
                     yql, re.DOTALL)
        if m:
            t = self._resolve(m.group(1))
            if t is None:
                raise ValueError(f"no such table {m.group(1)}")
            cols = [c.strip().strip("`") for c in m.group(2).split(",")]
            vals = []
            rest = m.group(3)
            while rest.strip():
                v, ln = _parse_literal(rest)
                vals.append(v)
                rest = rest[ln:].lstrip().lstrip(",")
            with self.lock:
                t.upsert(dict(zip(cols, vals)), emit_cdc=False)
            return [], 0
        m = re.match(r"SELECT MIN\(`(.+?)`\) AS lo, MAX\(`(.+?)`\) AS hi "
                     r"FROM `(.+?)`", yql)
        if m:
            k, _, path = m.groups()
            t = self._resolve(path)
            vals = [r.get(k) for r in t.rows.values()] if t else []
            vals = [v for v in vals if v is not None]
            lo = min(vals) if vals else None
            hi = max(vals) if vals else None
            ktype = dict(t.columns).get(k, "Int64") if t else "Int64"
            return [("lo", ktype, [lo]), ("hi", ktype, [hi])], 1
        m = re.match(r"SELECT (.+?) FROM `(.+?)`(.*)$", yql, re.DOTALL)
        if m:
            cols_s, path, rest = m.groups()
            t = self._resolve(path)
            if t is None:
                raise ValueError(f"no such table {path}")
            names = [c.strip().strip("`") for c in cols_s.split(",")]
            rows = list(t.rows.values())
            rest = rest.strip()
            wm = re.match(r"WHERE (.*?)(ORDER BY .*)?$", rest, re.DOTALL)
            if wm and wm.group(1).strip():
                cond = wm.group(1).strip()
                rows = [r for r in rows if _eval_where(cond, r)]
            om = re.search(r"ORDER BY (.+?)( LIMIT (\d+))?$", rest,
                           re.DOTALL)
            if om:
                order = [c.strip().strip("`")
                         for c in om.group(1).split(",")]
                rows.sort(key=lambda r: tuple(r.get(k) for k in order))
                if om.group(3):
                    rows = rows[:int(om.group(3))]
            types = dict(t.columns)
            return ([(n, types.get(n, "Utf8"),
                      [r.get(n) for r in rows]) for n in names],
                    len(rows))
        m = re.match(r"DELETE FROM `(.+?)`(?: WHERE (.*))?$", yql,
                     re.DOTALL)
        if m:
            path, cond = m.groups()
            t = self._resolve(path)
            if t is not None:
                if cond:
                    doomed = [k for k, r in t.rows.items()
                              if _eval_where(cond.strip(), r)]
                    for k in doomed:
                        t.rows.pop(k)
                else:
                    t.rows.clear()
            return [], 0
        raise ValueError(f"fake ydb cannot evaluate: {yql[:200]}")

    def run_scheme(self, yql: str) -> None:
        self.queries.append(yql)
        yql = yql.strip()
        m = re.match(
            r"CREATE TABLE (?:IF NOT EXISTS )?`(.+?)` \((.+)\)$",
            yql, re.DOTALL)
        if m:
            path, body = m.groups()
            rel = path
            if rel.startswith(self.database + "/"):
                rel = rel[len(self.database) + 1:]
            pk = re.search(r"PRIMARY KEY \((.+?)\)", body)
            keys = [k.strip().strip("`")
                    for k in pk.group(1).split(",")] if pk else []
            cols = []
            for part in body[:pk.start()].rstrip(", ").split(","):
                part = part.strip()
                if not part:
                    continue
                cm = re.match(r"`(.+?)` (\w+)", part)
                if cm:
                    cols.append((cm.group(1), cm.group(2)))
            with self.lock:
                if rel not in self.tables:
                    self.add_table(rel, cols, keys)
            return
        m = re.match(r"ALTER TABLE `(.+?)` ADD COLUMN `(.+?)` (\w+)$",
                     yql)
        if m:
            t = self._resolve(m.group(1))
            if t is None:
                raise ValueError(f"no such table {m.group(1)}")
            if any(c[0] == m.group(2) for c in t.columns):
                raise ValueError(
                    f"column {m.group(2)} already exists")
            t.columns.append((m.group(2), m.group(3)))
            return
        m = re.match(r"DROP TABLE `(.+?)`$", yql)
        if m:
            rel = m.group(1)
            if rel.startswith(self.database + "/"):
                rel = rel[len(self.database) + 1:]
            with self.lock:
                if rel not in self.tables:
                    raise ValueError(f"no such table {rel}")
                self.tables.pop(rel)
            return
        raise ValueError(f"fake ydb cannot run scheme: {yql[:200]}")

    # -- grpc plumbing ------------------------------------------------------
    def start(self) -> "FakeYDB":
        import grpc
        from concurrent import futures

        pb = self.pb
        fake = self

        def _op_response(resp_cls, result_msg=None, status=400000,
                         issues=()):
            resp = resp_cls()
            resp.operation.ready = True
            resp.operation.status = status
            for text in issues:
                im = resp.operation.issues.add()
                im.message = text
            if result_msg is not None:
                resp.operation.result.type_url = "type.googleapis.com/x"
                resp.operation.result.value = \
                    result_msg.SerializeToString()
            return resp.SerializeToString()

        def create_session(request: bytes, context):
            return _op_response(
                pb.CreateSessionResponse,
                pb.CreateSessionResult(session_id="fake-session"))

        def execute_data_query(request: bytes, context):
            req = pb.ExecuteDataQueryRequest.FromString(request)
            try:
                with fake.lock:
                    cols, _n = fake.run_yql(req.query.yql_text)
            except ValueError as e:
                return _op_response(pb.ExecuteDataQueryResponse,
                                    status=400010, issues=[str(e)])
            result = pb.ExecuteQueryResult()
            rs = result.result_sets.add()
            n_rows = len(cols[0][2]) if cols else 0
            for name, ydb_t, _vals in cols:
                col = rs.columns.add()
                col.name = name
                col.type.CopyFrom(fake._pb_type(ydb_t))
            for i in range(n_rows):
                row = rs.rows.add()
                for _name, ydb_t, vals in cols:
                    item = row.items.add()
                    item.CopyFrom(fake._pb_value(ydb_t, vals[i]))
            return _op_response(pb.ExecuteDataQueryResponse, result)

        def execute_scheme_query(request: bytes, context):
            req = pb.ExecuteSchemeQueryRequest.FromString(request)
            try:
                with fake.lock:
                    fake.run_scheme(req.yql_text)
            except ValueError as e:
                return _op_response(pb.ExecuteSchemeQueryResponse,
                                    status=400010, issues=[str(e)])
            return _op_response(pb.ExecuteSchemeQueryResponse)

        def bulk_upsert(request: bytes, context):
            req = pb.BulkUpsertRequest.FromString(request)
            t = fake._resolve(req.table)
            if t is None:
                return _op_response(pb.BulkUpsertResponse, status=400010,
                                    issues=[f"no table {req.table}"])
            members = [
                (m.name, m.type)
                for m in req.rows.type.list_type.item.struct_type.members
            ]
            with fake.lock:
                for row_v in req.rows.value.items:
                    row = {}
                    for (name, _t), item in zip(members, row_v.items):
                        which = item.WhichOneof("value")
                        if which == "null_flag_value" or which is None:
                            row[name] = None
                        elif which == "nested_value":
                            row[name] = None
                        else:
                            row[name] = getattr(item, which)
                    t.upsert(row)
            return _op_response(pb.BulkUpsertResponse,
                                pb.BulkUpsertResult())

        def describe_table(request: bytes, context):
            req = pb.DescribeTableRequest.FromString(request)
            t = fake._resolve(req.path)
            if t is None:
                return _op_response(pb.DescribeTableResponse,
                                    status=400140,  # SCHEME_ERROR
                                    issues=[f"no table {req.path}"])
            result = pb.DescribeTableResult()
            result.self.name = t.path.rsplit("/", 1)[-1]
            result.self.type = 2
            for name, ydb_t in t.columns:
                cm = result.columns.add()
                cm.name = name
                cm.type.CopyFrom(fake._pb_type(ydb_t))
            result.primary_key.extend(t.primary_key)
            return _op_response(pb.DescribeTableResponse, result)

        def list_directory(request: bytes, context):
            req = pb.ListDirectoryRequest.FromString(request)
            rel = req.path
            if rel.startswith(fake.database):
                rel = rel[len(fake.database):]
            rel = rel.strip("/")
            result = pb.ListDirectoryResult()
            result.self.name = rel or "/"
            result.self.type = 1
            seen = set()
            with fake.lock:
                for path in sorted(fake.tables):
                    if rel and not path.startswith(rel + "/"):
                        continue
                    tail = path[len(rel) + 1:] if rel else path
                    head = tail.split("/", 1)[0]
                    if head in seen:
                        continue
                    seen.add(head)
                    entry = result.children.add()
                    entry.name = head
                    entry.type = 2 if "/" not in tail else 1
            return _op_response(pb.ListDirectoryResponse, result)

        def stream_read(request_iterator, context):
            session = {"topic": "", "consumer": "", "sent": 0}
            psid = 1
            for raw in request_iterator:
                msg = pb.StreamReadFromClient.FromString(raw)
                which = msg.WhichOneof("client_message")
                if which == "init_request":
                    session["topic"] = \
                        msg.init_request.topics_read_settings[0].path
                    session["consumer"] = msg.init_request.consumer
                    out = pb.StreamReadFromServer()
                    out.init_response.session_id = "read-1"
                    yield out.SerializeToString()
                    start = pb.StreamReadFromServer()
                    ps = start.start_partition_session_request \
                        .partition_session
                    ps.partition_session_id = psid
                    ps.path = session["topic"]
                    ps.partition_id = 0
                    yield start.SerializeToString()
                elif which == "start_partition_session_response":
                    pass
                elif which == "commit_offset_request":
                    for off in (msg.commit_offset_request
                                .commit_offsets):
                        key = (session["topic"], session["consumer"])
                        with fake.lock:
                            cur = fake.consumer_offsets.get(key, 0)
                            fake.consumer_offsets[key] = max(
                                cur, off.offsets.end)
                    out = pb.StreamReadFromServer()
                    out.commit_offset_response.SetInParent()
                    yield out.SerializeToString()
                elif which == "read_request":
                    # serve any uncommitted+unsent events of the feed
                    topic = session["topic"]
                    rel = topic
                    if rel.startswith(fake.database + "/"):
                        rel = rel[len(fake.database) + 1:]
                    table_path, _feed = rel.rsplit("/", 1)
                    t = fake.tables.get(table_path)
                    if t is None:
                        continue
                    key = (topic, session["consumer"])
                    with fake.lock:
                        committed = fake.consumer_offsets.get(key, 0)
                        start_off = max(committed, session["sent"])
                        events = list(enumerate(t.feed_log))[start_off:]
                    deadline = time.monotonic() + 0.3
                    while not events and time.monotonic() < deadline:
                        time.sleep(0.02)
                        with fake.lock:
                            committed = fake.consumer_offsets.get(key, 0)
                            start_off = max(committed, session["sent"])
                            events = list(enumerate(
                                t.feed_log))[start_off:]
                    if not events:
                        out = pb.StreamReadFromServer()
                        out.read_response.SetInParent()
                        yield out.SerializeToString()
                        continue
                    out = pb.StreamReadFromServer()
                    pd = out.read_response.partition_data.add()
                    pd.partition_session_id = psid
                    batch = pd.batches.add()
                    for off, data in events:
                        m = batch.messages.add()
                        m.offset = off
                        m.data = data
                    session["sent"] = events[-1][0] + 1
                    yield out.SerializeToString()

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                method = handler_call_details.method
                unary = {
                    "/Ydb.Table.V1.TableService/CreateSession":
                        create_session,
                    "/Ydb.Table.V1.TableService/ExecuteDataQuery":
                        execute_data_query,
                    "/Ydb.Table.V1.TableService/ExecuteSchemeQuery":
                        execute_scheme_query,
                    "/Ydb.Table.V1.TableService/BulkUpsert": bulk_upsert,
                    "/Ydb.Table.V1.TableService/DescribeTable":
                        describe_table,
                    "/Ydb.Scheme.V1.SchemeService/ListDirectory":
                        list_directory,
                }
                if method in unary:
                    fn = unary[method]
                    return grpc.unary_unary_rpc_method_handler(
                        fn, request_deserializer=lambda b: b,
                        response_serializer=lambda b: b)
                if method == "/Ydb.Topic.V1.TopicService/StreamRead":
                    return grpc.stream_stream_rpc_method_handler(
                        stream_read, request_deserializer=lambda b: b,
                        response_serializer=lambda b: b)
                return None

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((Handler(),))
        self.port = self._server.add_insecure_port("127.0.0.1:0")
        self._server.start()
        return self

    @property
    def endpoint(self) -> str:
        return f"127.0.0.1:{self.port}"

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=0.2)


def _eval_where(cond: str, row: dict) -> bool:
    """Evaluate the provider's WHERE grammar: backticked idents compared
    to literals with AND/OR and parentheses."""
    pos = 0

    def skip_ws():
        nonlocal pos
        while pos < len(cond) and cond[pos].isspace():
            pos += 1

    def parse_or():
        left = parse_and()
        while True:
            skip_ws()
            if cond[pos:pos + 2].upper() == "OR" and (
                    pos + 2 >= len(cond) or not cond[pos + 2].isalnum()):
                nonlocal_pos(2)
                right = parse_and()
                left = left or right
            else:
                return left

    def nonlocal_pos(n):
        nonlocal pos
        pos += n

    def parse_and():
        left = parse_atom()
        while True:
            skip_ws()
            if cond[pos:pos + 3].upper() == "AND" and (
                    pos + 3 >= len(cond) or not cond[pos + 3].isalnum()):
                nonlocal_pos(3)
                right = parse_atom()
                left = left and right
            else:
                return left

    def parse_atom():
        nonlocal pos
        skip_ws()
        if pos < len(cond) and cond[pos] == "(":
            pos += 1
            v = parse_or()
            skip_ws()
            assert cond[pos] == ")", cond[pos:]
            pos += 1
            return v
        m = re.match(r"`(.+?)`\s*(>=|<=|!=|=|>|<)\s*", cond[pos:])
        assert m, cond[pos:pos + 60]
        name, op = m.group(1), m.group(2)
        pos += m.end()
        lit, ln = _parse_literal(cond[pos:])
        pos += ln
        val = row.get(name)
        if val is None:
            return False
        try:
            return {
                "=": val == lit, "!=": val != lit, ">": val > lit,
                "<": val < lit, ">=": val >= lit, "<=": val <= lit,
            }[op]
        except TypeError:
            return False

    result = parse_or()
    return bool(result)


def _parse_literal(s: str) -> tuple[Any, int]:
    s0 = s.lstrip()
    off = len(s) - len(s0)
    if s0.startswith('"'):
        # json string literal
        dec = json.JSONDecoder()
        val, end = dec.raw_decode(s0)
        return val, off + end
    m = re.match(r"-?\d+\.\d+(e[-+]?\d+)?", s0, re.IGNORECASE)
    if m:
        return float(m.group(0)), off + m.end()
    m = re.match(r"-?\d+", s0)
    if m:
        return int(m.group(0)), off + m.end()
    m = re.match(r"(true|false|NULL)", s0)
    if m:
        v = {"true": True, "false": False, "NULL": None}[m.group(1)]
        return v, off + m.end()
    raise ValueError(f"bad literal: {s0[:40]}")
