"""In-process fake Oracle server (TNS framing + the client's TTC subset).

A protocol fake, not a SQL engine: speaks real sockets against the
provider's OracleConnection (CONNECT/ACCEPT, protocol negotiation,
two-phase salted auth, execute/fetch with DESCRIBE + ROW messages and the
ORA-1403 end-of-fetch convention) and pattern-matches the exact SQL the
provider emits (all_tables / all_tab_columns / constraints / v$database /
data SELECTs with AS OF SCN, keyset paging, ORA_HASH shards, samples).

Flashback semantics: every mutation bumps current_scn and snapshots the
table's row list, so ``AS OF SCN n`` reads serve the version that was
current at n — which is what the SCN-consistency e2e asserts.
"""

from __future__ import annotations

import hashlib
import re
import secrets
import socketserver
import struct
import threading

from transferia_tpu.providers.oracle import tns
from transferia_tpu.providers.oracle.tns import (
    ORA_BINARY_DOUBLE,
    ORA_BINARY_FLOAT,
    ORA_BLOB,
    ORA_CHAR,
    ORA_CLOB,
    ORA_DATE,
    ORA_NUMBER,
    ORA_RAW,
    ORA_TIMESTAMP,
    ORA_VARCHAR2,
    PKT_ACCEPT,
    PKT_CONNECT,
    PKT_DATA,
    encode_value,
    read_str,
    read_uint,
    write_str,
    write_uint,
)
from transferia_tpu.providers.oracle.wire import (
    FN_AUTH_PHASE_ONE,
    FN_AUTH_PHASE_TWO,
    FN_EXECUTE,
    FN_FETCH,
    FN_LOGOFF,
    MSG_DESCRIBE,
    MSG_ERROR,
    MSG_FUNCTION,
    MSG_PARAMETER,
    MSG_PROTOCOL,
    MSG_ROW_DATA,
    MSG_STATUS,
    ORA_INVALID_LOGIN,
    ORA_NO_DATA_FOUND,
)

_TYPE_CODES = {
    "VARCHAR2": ORA_VARCHAR2, "NVARCHAR2": ORA_VARCHAR2,
    "CHAR": ORA_CHAR, "NCHAR": ORA_CHAR,
    "NUMBER": ORA_NUMBER, "FLOAT": ORA_NUMBER,
    "BINARY_FLOAT": ORA_BINARY_FLOAT, "BINARY_DOUBLE": ORA_BINARY_DOUBLE,
    "DATE": ORA_DATE, "TIMESTAMP": ORA_TIMESTAMP,
    "RAW": ORA_RAW, "BLOB": ORA_BLOB, "CLOB": ORA_CLOB,
}

_ROWS_PER_BATCH = 100


class FakeOraTable:
    def __init__(self, owner: str, name: str, columns: list[tuple],
                 rows: list[dict] | None = None, scn: int = 0):
        # columns: (name, oracle_type e.g. "NUMBER(10)", is_pk, notnull)
        self.owner = owner
        self.name = name
        self.columns = columns
        self.rows = list(rows or [])
        # flashback versions: (scn, snapshot-of-rows)
        self.versions: list[tuple[int, list[dict]]] = [(scn, list(self.rows))]

    def base_type(self, spec: str) -> str:
        base = spec.split("(")[0].strip().upper()
        return base

    def type_code(self, spec: str) -> int:
        return _TYPE_CODES.get(self.base_type(spec), ORA_VARCHAR2)

    def rows_as_of(self, scn: int | None) -> list[dict]:
        if scn is None:
            return self.rows
        best = self.versions[0][1]
        for vs, rows in self.versions:
            if vs <= scn:
                best = rows
            else:
                break
        return best


class FakeOracle:
    def __init__(self, service_name: str = "XEPDB1", user: str = "scott",
                 password: str = "tiger"):
        self.service_name = service_name
        self.user = user
        self.password = password
        self.tables: dict[tuple[str, str], FakeOraTable] = {}
        self.queries: list[str] = []
        self.current_scn = 1000
        # redo rows served via V$LOGMNR_CONTENTS between START/END_LOGMNR
        self.redo: list[dict] = []
        self.lock = threading.RLock()
        self.port = 0
        self._srv = None

    def add_table(self, table: FakeOraTable) -> None:
        with self.lock:
            table.versions = [(self.current_scn, list(table.rows))]
            self.tables[(table.owner.upper(), table.name.upper())] = table

    def mutate(self, owner: str, name: str, change) -> int:
        """Apply `change(rows)` under a new SCN (flashback versioning)."""
        with self.lock:
            t = self.tables[(owner.upper(), name.upper())]
            self.current_scn += 10
            rows = list(t.rows)
            change(rows)
            t.rows = rows
            t.versions.append((self.current_scn, list(rows)))
            return self.current_scn

    def feed_redo(self, owner: str, table: str, op_code: int,
                  sql_redo: str, xid: str = "1.2.3",
                  csf_parts: int = 1) -> int:
        """Append redo rows for LogMiner mining.  csf_parts > 1 splits the
        statement across continuation rows (CSF=1 on all but the last) the
        way V$LOGMNR_CONTENTS chunks long SQL."""
        import datetime as dt

        with self.lock:
            self.current_scn += 1
            scn = self.current_scn
            ts = dt.datetime(2026, 7, 29, 12, 0, 0)
            rs_id = f"0x{len(self.redo):06x}"
            if csf_parts <= 1:
                self.redo.append({
                    "scn": scn, "ts": ts, "xid": xid, "op": op_code,
                    "owner": owner.upper(), "table": table.upper(),
                    "sql": sql_redo, "csf": 0, "rs_id": rs_id, "ssn": 0,
                })
                return scn
            step = max(1, len(sql_redo) // csf_parts)
            chunks = [sql_redo[i:i + step]
                      for i in range(0, len(sql_redo), step)]
            for i, chunk in enumerate(chunks):
                self.redo.append({
                    "scn": scn, "ts": ts, "xid": xid, "op": op_code,
                    "owner": owner.upper(), "table": table.upper(),
                    "sql": chunk,
                    "csf": 0 if i == len(chunks) - 1 else 1,
                    "rs_id": rs_id, "ssn": i,
                })
            return scn

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "FakeOracle":
        fake = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    _Session(fake, self.request).run()
                except (ConnectionError, tns.TNSError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Server(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()
        return self

    def stop(self) -> None:
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None


class _Session:
    def __init__(self, fake: FakeOracle, sock):
        self.fake = fake
        self.sock = sock
        self.salt = secrets.token_bytes(16)
        self.authed = False
        # cursor state: remaining rows + their column type codes
        self.pending_rows: list[list[bytes]] = []

    # -- transport ----------------------------------------------------------
    def send(self, ptype: int, payload: bytes) -> None:
        self.sock.sendall(tns.pack_packet(ptype, payload))

    def send_data(self, payload: bytes) -> None:
        self.send(PKT_DATA, struct.pack(">H", 0) + payload)

    def send_error(self, code: int, message: str) -> None:
        self.send_data(bytes([MSG_ERROR]) + write_uint(code)
                       + write_str(message))

    def run(self) -> None:
        ptype, payload = tns.read_packet(self.sock)
        if ptype != PKT_CONNECT:
            raise tns.TNSError(f"expected CONNECT, got {ptype}")
        desc = tns.parse_connect(payload)
        cd = tns.parse_connect_data(desc)
        want = cd.get("service_name") or cd.get("sid") or ""
        if want.upper() != self.fake.service_name.upper():
            self.send(tns.PKT_REFUSE, tns.build_refuse(
                f"ORA-12514: service {want!r} is not registered"))
            return
        self.send(PKT_ACCEPT, tns.build_accept())
        while True:
            ptype, payload = tns.read_packet(self.sock)
            if ptype != PKT_DATA:
                return
            buf = payload[2:]
            if not buf:
                continue
            if buf[0] == MSG_PROTOCOL:
                self.send_data(bytes([MSG_PROTOCOL]) + b"\x06"
                               + b"fake-oracle\x00")
                continue
            if buf[0] != MSG_FUNCTION:
                self.send_error(600, f"unexpected message 0x{buf[0]:02x}")
                continue
            if not self.dispatch_function(buf):
                return

    def dispatch_function(self, buf: bytes) -> bool:
        fn = buf[1]
        pos = 2
        if fn == FN_LOGOFF:
            return False
        if fn == FN_AUTH_PHASE_ONE:
            user, pos = read_str(buf, pos)
            self.send_data(
                bytes([MSG_PARAMETER]) + write_uint(1)
                + write_str("AUTH_VFR_DATA") + write_str(self.salt.hex()))
            return True
        if fn == FN_AUTH_PHASE_TWO:
            user, pos = read_str(buf, pos)
            verifier, pos = read_str(buf, pos)
            want = hashlib.sha256(
                self.salt + self.fake.password.encode()).hexdigest()
            if user != self.fake.user or verifier != want:
                self.send_error(ORA_INVALID_LOGIN,
                                "ORA-01017: invalid username/password")
                return True
            self.authed = True
            self.send_data(bytes([MSG_STATUS]) + write_uint(0))
            return True
        if not self.authed:
            self.send_error(1012, "ORA-01012: not logged on")
            return True
        if fn == FN_EXECUTE:
            sql, pos = read_str(buf, pos)
            _prefetch, pos = read_uint(buf, pos)
            with self.fake.lock:
                self.fake.queries.append(sql)
                try:
                    self.execute(sql)
                except Exception as e:  # noqa: BLE001 — surface as ORA-
                    self.send_error(900, f"ORA-00900: {e}")
            return True
        if fn == FN_FETCH:
            _cursor, pos = read_uint(buf, pos)
            _n, pos = read_uint(buf, pos)
            self.flush_rows()
            return True
        self.send_error(600, f"unknown function 0x{fn:02x}")
        return True

    # -- SQL dispatch -------------------------------------------------------
    def execute(self, sql: str) -> None:
        low = " ".join(sql.lower().split())
        fake = self.fake
        if low.startswith("begin dbms_logmnr.start_logmnr"):
            m = re.search(r"STARTSCN\s*=>\s*(\d+)", sql, re.I)
            self.logmnr_scn = int(m.group(1)) if m else 0
            self.describe_and_rows([("RESULT", ORA_VARCHAR2)], [])
            return
        if low.startswith("begin dbms_logmnr.end_logmnr"):
            self.logmnr_scn = None
            self.describe_and_rows([("RESULT", ORA_VARCHAR2)], [])
            return
        if "v$logmnr_contents" in low:
            if getattr(self, "logmnr_scn", None) is None:
                raise ValueError(
                    "ORA-01306: START_LOGMNR must be invoked first")
            m = re.search(r"SCN >(=?) (\d+)", sql, re.I)
            floor = int(m.group(2)) if m else 0
            inclusive = bool(m and m.group(1))
            mo = re.search(r"SEG_OWNER = '([^']*)'", sql, re.I)
            owner = mo.group(1) if mo else ""
            mc = re.search(r"OPERATION_CODE IN \(([^)]*)\)", sql, re.I)
            ops = {int(x) for x in mc.group(1).split(",")} if mc else None
            with fake.lock:
                rows = [
                    r for r in fake.redo
                    if (r["scn"] >= floor if inclusive
                        else r["scn"] > floor)
                    and (not owner or r["owner"] == owner)
                    and (ops is None or r["op"] in ops)
                ]
            encoded = [
                [encode_value(ORA_NUMBER, r["scn"]),
                 encode_value(ORA_VARCHAR2, r.get("rs_id", "")),
                 encode_value(ORA_NUMBER, r.get("ssn", 0)),
                 encode_value(ORA_DATE, r["ts"]),
                 encode_value(ORA_VARCHAR2, r["xid"]),
                 encode_value(ORA_NUMBER, r["op"]),
                 encode_value(ORA_VARCHAR2, r["owner"]),
                 encode_value(ORA_VARCHAR2, r["table"]),
                 encode_value(ORA_VARCHAR2, r["sql"]),
                 encode_value(ORA_NUMBER, r["csf"])]
                for r in rows
            ]
            self.describe_and_rows(
                [("SCN", ORA_NUMBER), ("RS_ID", ORA_VARCHAR2),
                 ("SSN", ORA_NUMBER), ("TIMESTAMP", ORA_DATE),
                 ("XID", ORA_VARCHAR2), ("OPERATION_CODE", ORA_NUMBER),
                 ("SEG_OWNER", ORA_VARCHAR2),
                 ("TABLE_NAME", ORA_VARCHAR2),
                 ("SQL_REDO", ORA_VARCHAR2), ("CSF", ORA_NUMBER)],
                encoded)
            return
        if low == "select 1 from dual":
            self.describe_and_rows(
                [("1", ORA_NUMBER)], [[encode_value(ORA_NUMBER, 1)]])
            return
        if "from v$database" in low:
            self.describe_and_rows(
                [("CURRENT_SCN", ORA_NUMBER)],
                [[encode_value(ORA_NUMBER, fake.current_scn)]])
            return
        if "from all_tables" in low:
            m = re.search(r"owner = '([^']*)'", sql, re.I)
            owner = (m.group(1) if m else "").upper()
            rows = [
                [encode_value(ORA_VARCHAR2, t.name),
                 encode_value(ORA_NUMBER, len(t.rows))]
                for (o, _), t in fake.tables.items() if o == owner
            ]
            self.describe_and_rows(
                [("TABLE_NAME", ORA_VARCHAR2), ("NUM_ROWS", ORA_NUMBER)],
                rows)
            return
        if "from all_tab_columns" in low:
            t = self._table_from_filters(sql)
            rows = []
            for (name, spec, _pk, notnull) in t.columns:
                base = t.base_type(spec)
                m = re.search(r"\((\d+)(?:,\s*(-?\d+))?\)", spec)
                prec = int(m.group(1)) if m else 0
                scale = int(m.group(2)) if m and m.group(2) else 0
                rows.append([
                    encode_value(ORA_VARCHAR2, name),
                    encode_value(ORA_VARCHAR2, base),
                    encode_value(ORA_NUMBER, prec),
                    encode_value(ORA_NUMBER, scale),
                    encode_value(ORA_CHAR, "N" if notnull else "Y"),
                ])
            self.describe_and_rows(
                [("COLUMN_NAME", ORA_VARCHAR2), ("DATA_TYPE", ORA_VARCHAR2),
                 ("DATA_PRECISION", ORA_NUMBER), ("DATA_SCALE", ORA_NUMBER),
                 ("NULLABLE", ORA_CHAR)], rows)
            return
        if "from all_constraints" in low or "all_cons_columns" in low:
            t = self._table_from_filters(sql)
            rows = [[encode_value(ORA_VARCHAR2, name)]
                    for (name, _spec, pk, _nn) in t.columns if pk]
            self.describe_and_rows([("COLUMN_NAME", ORA_VARCHAR2)], rows)
            return
        if "from all_segments" in low:
            t = self._table_from_filters(sql, owner_key="owner",
                                         name_key="segment_name")
            self.describe_and_rows(
                [("SUM(BYTES)", ORA_NUMBER)],
                [[encode_value(ORA_NUMBER, len(t.rows) * 100)]])
            return
        m = re.match(r'select count\(\*\) from "([^"]+)"\."([^"]+)"', low)
        if m:
            t = fake.tables.get((m.group(1).upper(), m.group(2).upper()))
            n = len(t.rows) if t else 0
            self.describe_and_rows(
                [("COUNT(*)", ORA_NUMBER)], [[encode_value(ORA_NUMBER, n)]])
            return
        m = re.match(r'SELECT (.+?) FROM "([^"]+)"\."([^"]+)"(.*)$',
                     sql, re.S | re.I)
        if m:
            self.execute_data_select(m.group(1), m.group(2), m.group(3),
                                     m.group(4))
            return
        raise ValueError(f"fake Oracle: unhandled query: {sql[:120]}")

    def _table_from_filters(self, sql: str, owner_key: str = "owner",
                            name_key: str = "table_name") -> FakeOraTable:
        mo = re.search(rf"{owner_key} = '([^']*)'", sql, re.I)
        mn = re.search(rf"(?:{name_key}|cons\.table_name) = '([^']*)'",
                       sql, re.I)
        key = ((mo.group(1) if mo else "").upper(),
               (mn.group(1) if mn else "").upper())
        t = self.fake.tables.get(key)
        if t is None:
            raise ValueError(f"table {key} does not exist")
        return t

    # -- data SELECT evaluation --------------------------------------------
    def execute_data_select(self, collist: str, owner: str, name: str,
                            tail: str) -> None:
        t = self.fake.tables.get((owner.upper(), name.upper()))
        if t is None:
            raise ValueError(f"ORA-00942: table {owner}.{name} not found")
        cols = [c.strip().strip('"') for c in collist.split(",")]
        specs = {n: spec for (n, spec, _pk, _nn) in t.columns}

        scn = None
        m = re.search(r"AS OF SCN (\d+)", tail, re.I)
        if m:
            scn = int(m.group(1))
        rows = list(t.rows_as_of(scn))

        m = re.search(r"WHERE (.*?)(?: ORDER BY | FETCH |$)", tail, re.S)
        if m:
            rows = self._apply_where(m.group(1).strip(), rows)
        m = re.search(r"ORDER BY (.+?)(?: FETCH |$)", tail, re.S)
        if m:
            for part in reversed(m.group(1).split(",")):
                part = part.strip()
                desc = part.upper().endswith(" DESC")
                cname = part.split()[0].strip('"')

                def key_fn(r, _n=cname):
                    v = r.get(_n)
                    if v is None:
                        return (2, 0)
                    try:
                        return (0, float(v))
                    except (TypeError, ValueError):
                        return (1, str(v))
                rows = sorted(rows, key=key_fn, reverse=desc)
        m = re.search(r"FETCH NEXT (\d+) ROWS ONLY", tail, re.I)
        if m:
            rows = rows[: int(m.group(1))]

        header = [(c, t.type_code(specs.get(c, "VARCHAR2"))) for c in cols]
        encoded = [
            [encode_value(code, r.get(cname)) for cname, code in header]
            for r in rows
        ]
        self.describe_and_rows(header, encoded)

    def _apply_where(self, cond: str, rows: list[dict]) -> list[dict]:
        """Apply every recognized predicate of the conjunction in turn
        (shard MOD filters compose with keyset pagination)."""
        m = re.search(r"MOD\(ORA_HASH\(ROWID\), (\d+)\) = (\d+)", cond)
        if m:
            n, i = int(m.group(1)), int(m.group(2))
            rows = [r for idx, r in enumerate(rows) if idx % n == i]
            cond = cond.replace(m.group(0), "").strip()
        if "DBMS_RANDOM.VALUE" in cond:
            return rows[::7]
        if '" = ' in cond:
            keysets = []
            for group in re.findall(r"\(([^()]*)\)", cond):
                want = {}
                for eq in group.split(" AND "):
                    mk = re.match(r'\s*"([^"]+)"\s*=\s*(.+)\s*', eq)
                    if mk:
                        want[mk.group(1)] = mk.group(2).strip().strip("'")
                if want:
                    keysets.append(want)
            return [
                r for r in rows
                if any(all(str(r.get(k)) == v for k, v in ks.items())
                       for ks in keysets)
            ]
        m = re.search(r'"([^"]+)" > (.+)', cond)
        if m:
            cname, raw = m.group(1), m.group(2).strip().strip("'")

            def gt(v):
                if v is None:
                    return False
                try:
                    return float(v) > float(raw)
                except (TypeError, ValueError):
                    return str(v) > raw
            return [r for r in rows if gt(r.get(cname))]
        return rows

    # -- TTC responses ------------------------------------------------------
    def describe_and_rows(self, header: list[tuple[str, int]],
                          encoded_rows: list[list[bytes]]) -> None:
        out = bytes([MSG_DESCRIBE]) + write_uint(1) + write_uint(len(header))
        for name, code in header:
            out += (write_str(name) + write_uint(code) + write_uint(0)
                    + write_uint(0) + write_uint(1) + write_str(""))
        self.send_data(out)
        self.pending_rows = [b"".join(vals) for vals in encoded_rows]
        self.flush_rows()

    def flush_rows(self) -> None:
        batch = self.pending_rows[:_ROWS_PER_BATCH]
        self.pending_rows = self.pending_rows[_ROWS_PER_BATCH:]
        for row in batch:
            self.send_data(bytes([MSG_ROW_DATA]) + row)
        if self.pending_rows:
            self.send_data(bytes([MSG_STATUS]) + write_uint(0))
        else:
            self.send_error(ORA_NO_DATA_FOUND,
                            "ORA-01403: no data found")
