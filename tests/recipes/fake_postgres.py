"""In-process fake PostgreSQL server (wire protocol v3 subset).

Speaks real sockets against the provider's PGConnection: startup, optional
SCRAM-SHA-256 auth, simple queries (matched against the exact catalog/DML
statements the provider issues — a protocol fake, not a SQL engine), and
COPY OUT/IN streaming.
"""

from __future__ import annotations

import csv
import hashlib
import hmac
import io
import json
import re
import socket
import socketserver
import struct
import threading
from base64 import b64decode, b64encode


class _PGStateError(Exception):
    def __init__(self, message: str, code: str = "XX000"):
        super().__init__(message)
        self.code = code


class FakeTable:
    def __init__(self, namespace: str, name: str, columns: list[tuple],
                 rows: list[dict] | None = None):
        # columns: (name, pg_type, is_pk, notnull)
        self.namespace = namespace
        self.name = name
        self.columns = columns
        self.rows = rows or []


class FakePG:
    def __init__(self, password: str = "", scram: bool = False,
                 echo_dml_to_wal: bool = False):
        """echo_dml_to_wal: INSERT/UPDATE/DELETE statements also emit
        wal2json events, like real logical decoding — the DBLog e2e needs
        its signal-table writes echoed into the CDC stream."""
        self.tables: dict[tuple[str, str], FakeTable] = {}
        self.queries: list[str] = []
        self.password = password
        self.scram = scram
        self.echo_dml_to_wal = echo_dml_to_wal
        self.lock = threading.RLock()
        self.port = 0
        self._srv = None
        # replication state
        self.slots: dict[str, str] = {}          # slot -> plugin
        self.wal: list[tuple[int, bytes]] = []   # (lsn, wal2json payload)
        self.flushed_lsn = 0                     # last standby-status flush
        self.wal_event = threading.Event()
        # DDL-object catalog served via pg_indexes/pg_views/pg_sequences
        self.indexes: list[tuple[str, str, str, str]] = []
        #   (schema, table, indexname, indexdef)
        self.views: list[tuple[str, str, str]] = []
        #   (schema, viewname, definition)
        self.sequences: list[tuple[str, str, int, int, int]] = []
        #   (schema, seqname, start, increment, last_value)
        self.executed_ddl: list[str] = []

    def feed_wal(self, payload: bytes, lsn: int | None = None) -> None:
        """Append one wal2json message for streaming to subscribers."""
        with self.lock:
            lsn = lsn if lsn is not None else (
                (self.wal[-1][0] + 8) if self.wal else 0x2000
            )
            self.wal.append((lsn, payload))
        self.wal_event.set()

    def add_table(self, table: FakeTable) -> None:
        with self.lock:
            self.tables[(table.namespace, table.name)] = table

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "FakePG":
        fake = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    _Session(self.request, fake).run()
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Server(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()
        return self

    def stop(self):
        if self._srv:
            self._srv.shutdown()


class _Session:
    def __init__(self, sock: socket.socket, fake: FakePG):
        self.sock = sock
        self.fake = fake

    # -- framing ------------------------------------------------------------
    def send(self, t: bytes, payload: bytes = b"") -> None:
        self.sock.sendall(t + struct.pack("!I", len(payload) + 4) + payload)

    def recv_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("client gone")
            out += chunk
        return out

    def recv_msg(self) -> tuple[bytes, bytes]:
        header = self.recv_exact(5)
        ln = struct.unpack("!I", header[1:5])[0]
        return header[:1], self.recv_exact(ln - 4) if ln > 4 else b""

    def ready(self):
        self.send(b"Z", b"I")

    def error(self, message: str, code: str = "XX000"):
        fields = b"SERROR\x00" + f"C{code}".encode() + b"\x00" \
            + f"M{message}".encode() + b"\x00\x00"
        self.send(b"E", fields)

    # -- auth ---------------------------------------------------------------
    def run(self):
        # startup message (untyped)
        ln = struct.unpack("!I", self.recv_exact(4))[0]
        payload = self.recv_exact(ln - 4)
        proto = struct.unpack("!I", payload[:4])[0]
        if proto == 80877103:  # SSLRequest -> deny, expect retry
            self.sock.sendall(b"N")
            return self.run()
        if self.fake.scram:
            self._scram_server()
        elif self.fake.password:
            self.send(b"R", struct.pack("!I", 3))  # cleartext
            t, pw = self.recv_msg()
            if pw.rstrip(b"\x00").decode() != self.fake.password:
                self.error("password authentication failed", "28P01")
                return
            self.send(b"R", struct.pack("!I", 0))
        else:
            self.send(b"R", struct.pack("!I", 0))
        self.send(b"S", b"server_version\x0016.1 (fake)\x00")
        self.send(b"K", struct.pack("!II", 4242, 0))
        self.ready()
        while True:
            t, payload = self.recv_msg()
            if t == b"X":
                return
            if t == b"Q":
                self.handle_query(payload.rstrip(b"\x00").decode())

    def _scram_server(self):
        self.send(b"R", struct.pack("!I", 10) + b"SCRAM-SHA-256\x00\x00")
        t, payload = self.recv_msg()
        # SASLInitialResponse: mech\0 int32 len, body
        mech_end = payload.index(b"\x00")
        body = payload[mech_end + 5:].decode()
        client_first_bare = body.split(",", 2)[2]
        client_nonce = dict(
            p.split("=", 1) for p in client_first_bare.split(",")
        )["r"]
        salt = b"saltsalt"
        iterations = 4096
        server_nonce = client_nonce + "srv"
        server_first = (
            f"r={server_nonce},s={b64encode(salt).decode()},i={iterations}"
        )
        self.send(b"R", struct.pack("!I", 11) + server_first.encode())
        t, payload = self.recv_msg()
        client_final = payload.decode()
        parts = dict(p.split("=", 1) for p in client_final.split(",", 2)
                     if "=" in p)
        salted = hashlib.pbkdf2_hmac(
            "sha256", self.fake.password.encode(), salt, iterations
        )
        client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored_key = hashlib.sha256(client_key).digest()
        without_proof = client_final.rsplit(",p=", 1)[0]
        auth_message = ",".join([
            client_first_bare, server_first, without_proof,
        ])
        client_sig = hmac.new(stored_key, auth_message.encode(),
                              hashlib.sha256).digest()
        expect_proof = b64encode(bytes(
            a ^ b for a, b in zip(client_key, client_sig)
        )).decode()
        if parts.get("p") != expect_proof:
            self.error("SCRAM authentication failed", "28P01")
            raise ConnectionError("bad scram")
        server_key = hmac.new(salted, b"Server Key",
                              hashlib.sha256).digest()
        server_sig = hmac.new(server_key, auth_message.encode(),
                              hashlib.sha256).digest()
        final = f"v={b64encode(server_sig).decode()}"
        self.send(b"R", struct.pack("!I", 12) + final.encode())
        self.send(b"R", struct.pack("!I", 0))

    # -- query dispatch -----------------------------------------------------
    def send_rows(self, columns: list[str], rows: list[list]):
        desc = struct.pack("!H", len(columns))
        for c in columns:
            desc += c.encode() + b"\x00" + struct.pack(
                "!IhIhih", 0, 0, 25, -1, -1, 0
            )
        self.send(b"T", desc)
        for row in rows:
            payload = struct.pack("!H", len(row))
            for v in row:
                if v is None:
                    payload += struct.pack("!i", -1)
                else:
                    b = str(v).encode()
                    payload += struct.pack("!i", len(b)) + b
            self.send(b"D", payload)
        self.send(b"C", b"SELECT\x00")

    def handle_query(self, sql: str):
        with self.fake.lock:
            self.fake.queries.append(sql)
        try:
            self.dispatch(sql)
        except _PGStateError as e:
            self.error(str(e), e.code)
        except ConnectionError:
            raise
        except Exception as e:
            self.error(str(e))
        self.ready()

    def dispatch(self, sql: str):
        low = " ".join(sql.lower().split())
        fake = self.fake
        # subclass hook (FakeGP external tables etc.): truthy = handled
        hook = getattr(fake, "sql_hook", None)
        if hook is not None and hook(sql, low, self):
            return None
        if low == "select 1":
            return self.send_rows(["?column?"], [[1]])
        if low == "identify_system":
            return self.send_rows(
                ["systemid", "timeline", "xlogpos", "dbname"],
                [["7000", "1", "0/1000", "db"]],
            )
        m = re.match(r"create_replication_slot (\w+) logical (\w+)", low)
        if m:
            with fake.lock:
                if m.group(1) in fake.slots:
                    raise _PGStateError(
                        f'replication slot "{m.group(1)}" already exists',
                        "42710",
                    )
                fake.slots[m.group(1)] = m.group(2)
            return self.send_rows(
                ["slot_name", "consistent_point", "snapshot_name",
                 "output_plugin"],
                [[m.group(1), "0/1000", None, m.group(2)]],
            )
        m = re.match(r"drop_replication_slot (\w+)", low)
        if m:
            with fake.lock:
                fake.slots.pop(m.group(1), None)
            return self.send(b"C", b"DROP_REPLICATION_SLOT\x00")
        if low.startswith("start_replication"):
            return self.stream_replication()
        if "pg_wal_lsn_diff" in low:
            return self.send_rows(["diff"], [[1024]])
        if "from pg_class c join pg_namespace" in low:
            rows = [
                [t.namespace, t.name, len(t.rows)]
                for t in fake.tables.values()
            ]
            return self.send_rows(["ns", "name", "eta"], rows)
        if "from pg_attribute" in low:
            m = re.search(r"'\"?([\w]+)\"?\.\"?([\w]+)\"?'::regclass", sql)
            t = fake.tables.get((m.group(1), m.group(2))) if m else None
            if t is None:
                raise ValueError("relation does not exist")
            rows = [
                [name, typ, "t" if notnull else "f", "t" if pk else "f"]
                for (name, typ, pk, notnull) in t.columns
            ]
            return self.send_rows(["name", "typ", "notnull", "is_pk"], rows)
        m = re.match(r"select count\(\*\) from \"?(\w+)\"?\.\"?(\w+)\"?",
                     low)
        if m:
            t = fake.tables.get((m.group(1), m.group(2)))
            return self.send_rows(["count"], [[len(t.rows) if t else 0]])
        if "pg_current_wal_lsn" in low:
            return self.send_rows(["lsn"], [["0/ABCDEF0"]])
        if "pg_relation_size" in low:
            m = re.search(r"'\"?(\w+)\"?\.\"?(\w+)\"?'", sql)
            t = fake.tables.get((m.group(1), m.group(2))) if m else None
            size = len(t.rows) * 100 if t else 0
            return self.send_rows(["size"], [[size]])
        if "relpages" in low:
            return self.send_rows(["relpages"], [[1]])
        if low.startswith("copy (") and "to stdout" in low:
            return self.copy_out(sql)
        if low.startswith("copy ") and "from stdin" in low:
            return self.copy_in(sql)
        if "from pg_indexes" in low:
            with fake.lock:
                rows = [[s_, t_, n_, d_] for s_, t_, n_, d_
                        in fake.indexes]
            return self.send_rows(
                ["schemaname", "tablename", "indexname", "indexdef"],
                rows)
        if "from pg_views" in low:
            with fake.lock:
                rows = [[s_, v_, d_] for s_, v_, d_ in fake.views]
            return self.send_rows(
                ["schemaname", "viewname", "definition"], rows)
        if "from pg_sequences" in low:
            with fake.lock:
                rows = [[s_, n_, st, inc, lv] for s_, n_, st, inc, lv
                        in fake.sequences]
            return self.send_rows(
                ["schemaname", "sequencename", "start_value",
                 "increment_by", "last_value"], rows)
        if low.startswith("select setval("):
            with fake.lock:
                fake.executed_ddl.append(sql)
            return self.send_rows(["setval"], [[1]])
        if low.startswith(("create index", "create unique index",
                           "create or replace view",
                           "create sequence")):
            with fake.lock:
                fake.executed_ddl.append(sql)
            return self.send(b"C", b"OK\x00")
        if low.startswith(("create ", "drop ", "truncate ", "alter ")):
            self.apply_ddl(sql)
            return self.send(b"C", b"OK\x00")
        if low.startswith("begin"):
            return self.apply_transaction(sql)
        if low.startswith(("insert ", "update ", "delete ")):
            self.apply_dml(sql)
            return self.send(b"C", b"OK\x00")
        if low.startswith("select "):
            # generic single-table SELECT (fence reads etc.)
            cols, rows = self._eval_select(sql)
            return self.send_rows(
                cols, [[r.get(c) for c in cols] for r in rows])
        raise ValueError(f"fake PG: unhandled query: {sql[:120]}")

    def apply_transaction(self, sql: str):
        """A `BEGIN; ...; COMMIT` simple-query block: apply the inner
        statements atomically — all table mutations roll back when any
        statement fails, like the implicit transaction a real server
        wraps a multi-statement Q message in."""
        import copy

        stmts = [s.strip() for s in sql.split(";") if s.strip()]
        fake = self.fake
        with fake.lock:
            snapshot = {
                k: copy.deepcopy(t.rows) for k, t in fake.tables.items()
            }
            try:
                for stmt in stmts:
                    low = stmt.lower()
                    if low in ("begin", "commit", "rollback"):
                        continue
                    if low.startswith(("insert ", "update ", "delete ")):
                        self.apply_dml(stmt)
                    elif low.startswith(("create ", "drop ",
                                         "truncate ")):
                        self.apply_ddl(stmt)
                    else:
                        raise ValueError(
                            f"fake PG: unhandled txn stmt: {stmt[:80]}")
            except Exception:
                for k, rows in snapshot.items():
                    if k in fake.tables:
                        fake.tables[k].rows = rows
                raise
        return self.send(b"C", b"COMMIT\x00")

    # -- replication streaming ---------------------------------------------
    def stream_replication(self):
        import select
        import time as _time

        self.send(b"W", struct.pack("!bh", 0, 0))
        sent = 0
        fake = self.fake
        while True:
            with fake.lock:
                wal = list(fake.wal)
            progressed = sent < len(wal)
            while sent < len(wal):
                lsn, payload = wal[sent]
                msg = b"w" + struct.pack("!QQQ", lsn, lsn, 0) + payload
                self.send(b"d", msg)
                sent += 1
            # keepalive so the client flushes its status
            last = wal[-1][0] if wal else 0
            self.send(b"d", b"k" + struct.pack("!QQB", last, 0, 0))
            readable, _, _ = select.select([self.sock], [], [], 0.05)
            if readable:
                t, payload = self.recv_msg()
                if t == b"d" and payload[:1] == b"r":
                    flushed = struct.unpack("!Q", payload[9:17])[0]
                    with fake.lock:
                        fake.flushed_lsn = flushed - 1
                elif t in (b"X", b"c"):
                    raise ConnectionError("replication client done")
            if not progressed:
                _time.sleep(0.02)

    # -- COPY ---------------------------------------------------------------
    def _eval_select(self, sql: str) -> tuple[list[str], list[dict]]:
        """Evaluate the SELECT shapes the provider emits: plain scans,
        checksum top/bottom UNION ALL samples, random()-filtered samples,
        and ORed key-set lookups with ORDER BY/LIMIT."""
        sql = sql.strip()
        if sql.startswith("(") and " UNION ALL " in sql:
            left, _, right = sql.partition(" UNION ALL ")
            lc, lr = self._eval_select(left.strip()[1:-1])
            _, rr = self._eval_select(right.strip()[1:-1])
            return lc, lr + rr
        m = re.search(r"FROM \"?(\w+)\"?\.\"?(\w+)\"?", sql)
        t = self.fake.tables.get((m.group(1), m.group(2))) if m else None
        if t is None:
            raise ValueError("relation does not exist")
        cols = [c[0] for c in t.columns]
        m2 = re.search(r"SELECT (.*?) FROM", sql, re.S)
        if m2 and m2.group(1).strip() != "*":
            cols = [c.strip().strip('"') for c in m2.group(1).split(",")]
        rows = list(t.rows)
        mw = re.search(
            r"WHERE (.*?)(?: ORDER BY | LIMIT |$)", sql, re.S)
        if mw:
            cond = mw.group(1).strip()
            if "random()" in cond:
                rows = rows[::7]  # deterministic "random" subsample
            elif "ctid" in cond:
                pass  # single-page tables: every part sees all rows
            elif '" = ' in cond or '"=' in cond:
                keysets = []
                for group in re.findall(r"\(([^()]*)\)", cond):
                    want = {}
                    for eq in group.split(" AND "):
                        mk = re.match(r'\s*"(\w+)"\s*=\s*(.+)\s*', eq)
                        if mk:
                            want[mk.group(1)] = mk.group(2).strip()
                    if want:
                        keysets.append(want)

                def lit(v):
                    if v is None:
                        return "NULL"
                    if isinstance(v, bool):
                        return "TRUE" if v else "FALSE"
                    if isinstance(v, (int, float)):
                        return str(v)
                    return "'" + str(v).replace("'", "''") + "'"

                rows = [
                    r for r in rows
                    if any(all(lit(r.get(k)) == v for k, v in ks.items())
                           for ks in keysets)
                ]
            elif re.match(r'"\w+" > ', cond):
                mk = re.match(r'"(\w+)" > (.+)', cond)
                col, raw = mk.group(1), mk.group(2).strip().strip("'")

                def gt(v):
                    if v is None:
                        return False
                    try:
                        return float(v) > float(raw)
                    except (TypeError, ValueError):
                        return str(v) > raw
                rows = [r for r in rows if gt(r.get(col))]
        mo = re.search(r"ORDER BY (.+?)(?: LIMIT |$)", sql, re.S)
        if mo:
            for part in reversed(mo.group(1).split(",")):
                part = part.strip()
                desc = part.upper().endswith(" DESC")
                name = part.split()[0].strip('"')

                def sort_key(r, _n=name):
                    v = r.get(_n)
                    if v is None:
                        return (2, 0)
                    try:
                        return (0, float(v))
                    except (TypeError, ValueError):
                        return (1, str(v))
                rows = sorted(rows, key=sort_key, reverse=desc)
        ml = re.search(r"LIMIT (\d+)", sql)
        if ml:
            rows = rows[: int(ml.group(1))]
        return cols, rows

    def copy_out(self, sql: str):
        inner = re.search(r"COPY \((.*)\) TO STDOUT", sql, re.S)
        cols, rows = self._eval_select(inner.group(1) if inner else sql)
        self.send(b"H", struct.pack("!bh", 0, 0))
        # C-speed bulk CSV (csv.writer.writerows quotes + stringifies),
        # framed as record-ALIGNED CopyData chunks: real PG frames on row
        # boundaries and the client's 32MB reflush relies on it.  The
        # previous per-row Python loop capped the fake ~3x below what the
        # client under test can ingest.
        out = io.StringIO()
        w = csv.writer(out, lineterminator="\n")
        chunk_rows = 4096
        for lo in range(0, len(rows), chunk_rows):
            out.seek(0)
            out.truncate()
            w.writerows(
                [["" if row.get(c) is None else row.get(c)
                  for c in cols]
                 for row in rows[lo:lo + chunk_rows]])
            payload = out.getvalue().encode()
            self.sock.sendall(
                b"d" + struct.pack("!I", len(payload) + 4) + payload)
        self.send(b"c")
        self.send(b"C", b"COPY\x00")

    def copy_in(self, sql: str):
        m = re.search(r"COPY \"?(\w+)\"?\.\"?(\w+)\"? \((.*?)\)", sql)
        t = self.fake.tables.get((m.group(1), m.group(2))) if m else None
        if t is None:
            raise ValueError("relation does not exist")
        cols = [c.strip().strip('"') for c in m.group(3).split(",")]
        self.send(b"G", struct.pack("!bh", 0, 0))
        data = b""
        while True:
            mt, payload = self.recv_msg()
            if mt == b"d":
                data += payload
            elif mt in (b"c", b"f"):
                break
        reader = csv.reader(io.StringIO(data.decode()))
        with self.fake.lock:
            for row in reader:
                t.rows.append({
                    c: (None if v == "" else v) for c, v in zip(cols, row)
                })
        self.send(b"C", b"COPY\x00")

    # -- naive DDL/DML ------------------------------------------------------
    def apply_ddl(self, sql: str):
        low = sql.lower()
        fake = self.fake
        m = re.match(r'create table if not exists "?(\w+)"?\."?(\w+)"?\s*'
                     r"\((.*)\)", sql, re.I | re.S)
        if m:
            ns, name, body = m.group(1), m.group(2), m.group(3)
            if (ns, name) not in fake.tables:
                cols = []
                pk_cols = set()
                pkm = re.search(r"PRIMARY KEY \((.*?)\)", body)
                if pkm:
                    pk_cols = {c.strip().strip('"')
                               for c in pkm.group(1).split(",")}
                    body = body[:pkm.start()].rstrip(", \n")
                for part in body.split(","):
                    toks = part.strip().split(None, 1)
                    if not toks or toks[0].upper() == "PRIMARY":
                        continue
                    cname = toks[0].strip('"')
                    ctype = toks[1].replace(" NOT NULL", "") \
                        if len(toks) > 1 else "text"
                    cols.append((cname, ctype.strip(), cname in pk_cols,
                                 "NOT NULL" in (toks[1] if len(toks) > 1
                                                else "")))
                fake.add_table(FakeTable(ns, name, cols))
            return
        m = re.match(r'alter table "?(\w+)"?\."?(\w+)"? add column '
                     r'if not exists "?(\w+)"? (\w+)', sql, re.I)
        if m:
            t = fake.tables.get((m.group(1), m.group(2)))
            if t is None:
                raise ValueError("relation does not exist")
            if all(c[0] != m.group(3) for c in t.columns):
                t.columns.append((m.group(3), m.group(4), False, False))
            return
        m = re.match(r'drop table if exists "?(\w+)"?\."?(\w+)"?', sql, re.I)
        if m:
            fake.tables.pop((m.group(1), m.group(2)), None)
            return
        m = re.match(r'truncate table "?(\w+)"?\."?(\w+)"?', sql, re.I)
        if m:
            t = fake.tables.get((m.group(1), m.group(2)))
            if t is None:
                raise ValueError(
                    f'relation "{m.group(1)}.{m.group(2)}" does not exist'
                )
            t.rows = []
            return
        # create schema etc: no-op

    def apply_dml(self, sql: str):
        fake = self.fake
        m = re.match(r'insert into "?(\w+)"?\."?(\w+)"? \((.*?)\) '
                     r'select (.*?) from "?(\w+)"?\."?(\w+)"?\s*$',
                     sql, re.I | re.S)
        if m:
            # INSERT ... SELECT (staged-commit publish): copy the source
            # table's rows, evaluating literal select items ('slug')
            dst = fake.tables.get((m.group(1), m.group(2)))
            src = fake.tables.get((m.group(5), m.group(6)))
            if dst is None or src is None:
                raise ValueError("relation does not exist")
            cols = [c.strip().strip('"') for c in m.group(3).split(",")]
            items = [s.strip() for s in m.group(4).split(",")]
            for row in list(src.rows):
                out = {}
                for col, item in zip(cols, items):
                    if item.startswith("'") and item.endswith("'"):
                        out[col] = item[1:-1].replace("''", "'")
                    else:
                        out[col] = row.get(item.strip('"'))
                dst.rows.append(out)
            return
        m = re.match(r'insert into "?(\w+)"?\."?(\w+)"? \((.*?)\) '
                     r"values \((.*)\)",
                     re.split(r" ON CONFLICT", sql, flags=re.I)[0],
                     re.I | re.S)
        if m:
            t = fake.tables.get((m.group(1), m.group(2)))
            if t is None:
                raise ValueError("relation does not exist")
            cols = [c.strip().strip('"') for c in m.group(3).split(",")]
            vals = [v.strip().strip("'")
                    for v in re.split(r",(?=(?:[^']*'[^']*')*[^']*$)",
                                      m.group(4))]
            mc = re.search(r'ON CONFLICT \(([^)]*)\) DO '
                           r'(NOTHING|UPDATE SET)', sql, re.I)
            if mc:
                # minimal upsert: conflict keys matched by value;
                # DO NOTHING skips, DO UPDATE replaces (fence-table
                # shapes)
                keys = [k.strip().strip('"')
                        for k in mc.group(1).split(",")]
                new = dict(zip(cols, vals))
                for r in t.rows:
                    if all(str(r.get(k)) == str(new.get(k))
                           for k in keys):
                        if mc.group(2).upper() == "UPDATE SET":
                            r.update(new)
                        return
                t.rows.append(new)
                return
            t.rows.append(dict(zip(cols, vals)))
            if fake.echo_dml_to_wal:
                types = {c[0]: c[1] for c in t.columns}
                fake.feed_wal(json.dumps({
                    "action": "I",
                    "schema": m.group(1), "table": m.group(2),
                    "columns": [
                        {"name": c, "type": types.get(c, "text"),
                         "value": v}
                        for c, v in zip(cols, vals)
                    ],
                    "pk": [{"name": c[0], "type": c[1]}
                           for c in t.columns if c[2]],
                }).encode())
            return
        m = re.match(r'delete from "?(\w+)"?\."?(\w+)"? where (.*)', sql,
                     re.I | re.S)
        if m:
            t = fake.tables.get((m.group(1), m.group(2)))
            cond = self._parse_where(m.group(3))
            gone = [r for r in t.rows
                    if all(str(r.get(k)) == v for k, v in cond.items())]
            t.rows = [r for r in t.rows if r not in gone]
            if fake.echo_dml_to_wal:
                types = {c[0]: c[1] for c in t.columns}
                pks = [c[0] for c in t.columns if c[2]]
                for r in gone:
                    fake.feed_wal(json.dumps({
                        "action": "D",
                        "schema": m.group(1), "table": m.group(2),
                        "identity": [
                            {"name": k, "type": types.get(k, "text"),
                             "value": r.get(k)} for k in pks],
                        "pk": [{"name": k, "type": types.get(k, "text")}
                               for k in pks],
                    }).encode())
            return
        m = re.match(r'update "?(\w+)"?\."?(\w+)"? set (.*) where (.*)',
                     sql, re.I | re.S)
        if m:
            t = fake.tables.get((m.group(1), m.group(2)))
            sets = self._parse_where(m.group(3), sep=",")
            cond = self._parse_where(m.group(4))
            for r in t.rows:
                if all(str(r.get(k)) == v for k, v in cond.items()):
                    r.update(sets)
                    if fake.echo_dml_to_wal:
                        types = {c[0]: c[1] for c in t.columns}
                        pks = [c[0] for c in t.columns if c[2]]
                        fake.feed_wal(json.dumps({
                            "action": "U",
                            "schema": m.group(1), "table": m.group(2),
                            "columns": [
                                {"name": k,
                                 "type": types.get(k, "text"),
                                 "value": v} for k, v in r.items()],
                            "identity": [
                                {"name": k,
                                 "type": types.get(k, "text"),
                                 "value": r.get(k)} for k in pks],
                            "pk": [{"name": k,
                                    "type": types.get(k, "text")}
                                   for k in pks],
                        }).encode())
            return

    @staticmethod
    def _parse_where(text: str, sep: str = "AND") -> dict:
        out = {}
        parts = text.split(sep if sep == "," else " AND ")
        for p in parts:
            if "=" in p:
                k, v = p.split("=", 1)
                out[k.strip().strip('"')] = v.strip().strip("'")
        return out
