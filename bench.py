"""Benchmark: ClickBench-style parquet snapshot through the TPU data plane.

Measures the north-star path (BASELINE.json): S3/fs parquet -> columnar
batches (arrow, no row pivot) -> transformer chain (HMAC-SHA256 PII mask on
the device + vectorized predicate filter) -> sink.  Prints ONE JSON line:

    {"metric": "clickbench_snapshot_rows_per_sec", "value": N,
     "unit": "rows/sec", "vs_baseline": N / 10_000_000}

vs_baseline is relative to the BASELINE.md target (>=10M rows/sec/chip on
v5e-1); the reference publishes no absolute numbers (BASELINE.md), so the
target ratio is the honest comparator.

Runs on the real TPU (no conftest import).  Dataset: a synthetic subset of
ClickBench `hits` (docs/benchmarks.md:9-17 in the reference — ~100M rows,
70 cols; here fewer rows/cols, same shape of workload: wide numerics +
URL/title strings), generated once into /tmp/trtpu_bench.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

import numpy as np

from transferia_tpu.runtime import knobs

ROWS = knobs.env_int("BENCH_ROWS", 2_000_000)
WIDE_ROWS = knobs.env_int("BENCH_WIDE_ROWS", 10_000_000)
BATCH_ROWS = knobs.env_int("BENCH_BATCH_ROWS", 131_072)
DATA_DIR = knobs.env_str("BENCH_DIR", "/tmp/trtpu_bench")
PARQUET = os.path.join(DATA_DIR, f"hits_{ROWS}.parquet")
WIDE_PARQUET = os.path.join(DATA_DIR, f"hits_wide_{WIDE_ROWS}.parquet")


def _auto_process_count() -> int:
    """Upload workers for the bench runs.

    The loader's parts are CPU-bound here (decode + hash + pivot all on
    the host), so oversubscribing the available cores only adds GIL
    churn and context switches — on the 1-core bench boxes the r03 run
    spent 345% of wall in 4 time-sliced decode threads.  Use the real
    affinity count, capped at the reference's ProcessCount default of 4
    (pkg/abstract/runtime.go:105-107)."""
    pinned = knobs.env_int("BENCH_PROCESS_COUNT", 0)
    if pinned:
        return pinned
    return max(1, min(4, int(_effective_cpus())))


def generate_dataset() -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(DATA_DIR, exist_ok=True)
    if os.path.exists(PARQUET):
        if not os.path.exists(PARQUET + ".expected.json"):
            # dataset from an older bench.py: derive the ground truth
            # from the two filter columns (cheap columnar read)
            t = pq.read_table(PARQUET,
                              columns=["RegionID", "ResolutionWidth"])
            kept = int(((t["RegionID"].to_numpy() < 400)
                        & (t["ResolutionWidth"].to_numpy() >= 390)).sum())
            with open(PARQUET + ".expected.json", "w") as fh:
                json.dump({"rows": t.num_rows, "kept": kept}, fh)
        return
    rng = np.random.default_rng(42)
    n = ROWS
    watch_id = rng.integers(0, 2**62, n, dtype=np.int64)
    user_id = rng.integers(0, 10_000_000, n, dtype=np.int64)
    counter_id = rng.integers(0, 5000, n).astype(np.int32)
    region_id = rng.integers(0, 500, n).astype(np.int32)
    event_time = (1_700_000_000 + rng.integers(0, 86_400 * 30, n)).astype(
        "datetime64[s]"
    )
    res_w = rng.choice(
        np.array([1280, 1366, 1536, 1920, 2560, 360, 390], dtype=np.int32), n
    )
    is_mobile = (rng.random(n) < 0.4).astype(np.int8)
    # URLs ~30-90 bytes (vectorized string build)
    host_ids = rng.integers(0, 997, n)
    path_ids = rng.integers(0, 10_000_019, n)
    urls = np.char.add(
        np.char.add("https://example-", host_ids.astype("U4")),
        np.char.add(".com/page/", path_ids.astype("U9")),
    )
    titles = np.char.add("Title ", rng.integers(0, 99_991, n).astype("U6"))
    phrase_pool = np.array(["", "", "", "buy tpu", "fast etl",
                            "weather tomorrow", "наушники"], dtype=object)
    phrases = phrase_pool[rng.integers(0, len(phrase_pool), n)]
    table = pa.table({
        "WatchID": watch_id,
        "UserID": user_id,
        "CounterID": counter_id,
        "RegionID": region_id,
        "EventTime": pa.array(event_time),
        "ResolutionWidth": res_w,
        "IsMobile": is_mobile,
        "URL": pa.array(urls.tolist(), type=pa.string()),
        "Title": pa.array(titles.tolist(), type=pa.string()),
        "SearchPhrase": pa.array(phrases.tolist(), type=pa.string()),
    })
    pq.write_table(table, PARQUET, row_group_size=BATCH_ROWS,
                   compression="snappy")
    # ground truth for the bench's completeness check: rows the transfer
    # chain keeps (make_transfer's filter) — catches silent row loss in
    # pushdown/transform regardless of where rows get dropped
    kept = int(((region_id < 400) & (res_w >= 390)).sum())
    with open(PARQUET + ".expected.json", "w") as fh:
        json.dump({"rows": n, "kept": kept}, fh)


def expected_kept(parquet: str = PARQUET) -> Optional[int]:
    try:
        with open(parquet + ".expected.json") as fh:
            return int(json.load(fh)["kept"])
    except (OSError, ValueError, KeyError):
        return None  # dataset generated by an older bench.py


# ~70-column ClickBench `hits` shape (docs/benchmarks.md:3,9-17 in the
# reference: ~100M rows x 70 cols).  Column names/types follow the public
# hits schema; values are synthetic.  (name, dtype, cardinality-ish knob):
# i8/i16/i32/i64 numerics plus a string tail with realistic repeat rates.
_WIDE_NUM_COLS = [
    # (name, numpy dtype, high exclusive bound)
    ("WatchID", "int64", 2**62), ("JavaEnable", "int8", 2),
    ("GoodEvent", "int8", 2), ("CounterID", "int32", 5000),
    ("ClientIP", "int32", 2**31 - 1), ("RegionID", "int32", 500),
    ("UserID", "int64", 10_000_000), ("CounterClass", "int8", 3),
    ("OS", "int8", 100), ("UserAgent", "int8", 80),
    ("IsRefresh", "int8", 2), ("RefererCategoryID", "int16", 3000),
    ("RefererRegionID", "int32", 5000), ("URLCategoryID", "int16", 3000),
    ("URLRegionID", "int32", 5000), ("ResolutionWidth", "int16", 0),
    ("ResolutionHeight", "int16", 2200), ("ResolutionDepth", "int8", 33),
    ("FlashMajor", "int8", 12), ("FlashMinor", "int8", 12),
    ("NetMajor", "int8", 5), ("NetMinor", "int8", 10),
    ("UserAgentMajor", "int16", 120), ("CookieEnable", "int8", 2),
    ("JavascriptEnable", "int8", 2), ("IsMobile", "int8", 2),
    ("MobilePhone", "int8", 90), ("IPNetworkID", "int32", 4_000_000),
    ("TraficSourceID", "int8", 10), ("SearchEngineID", "int16", 100),
    ("AdvEngineID", "int8", 60), ("IsArtifical", "int8", 2),
    ("WindowClientWidth", "int16", 2560), ("WindowClientHeight", "int16", 1600),
    ("ClientTimeZone", "int16", 1440), ("SilverlightVersion1", "int8", 6),
    ("SilverlightVersion2", "int8", 10), ("SilverlightVersion3", "int32", 70000),
    ("SilverlightVersion4", "int16", 200), ("CodeVersion", "int32", 3000),
    ("IsLink", "int8", 2), ("IsDownload", "int8", 2),
    ("IsNotBounce", "int8", 2), ("FUniqID", "int64", 2**62),
    ("HID", "int32", 2**31 - 1), ("IsOldCounter", "int8", 2),
    ("IsEvent", "int8", 2), ("IsParameter", "int8", 2),
    ("DontCountHits", "int8", 2), ("WithHash", "int8", 2),
    ("Age", "int8", 100), ("Sex", "int8", 3), ("Income", "int8", 10),
    ("Interests", "int16", 0x7FFF), ("Robotness", "int8", 5),
    ("RemoteIP", "int32", 2**31 - 1), ("WindowName", "int32", 10000),
    ("OpenerName", "int32", 10000), ("HistoryLength", "int16", 64),
    ("HTTPError", "int16", 600), ("SendTiming", "int32", 30000),
    ("DNSTiming", "int32", 5000),
]


def _string_pool(rng, n: int, prefix: str, lo: int, hi: int) -> "object":
    """Pool of n distinct strings, lengths in [lo, hi) (vectorized)."""
    import pyarrow as pa

    ids = np.arange(n)
    pads = rng.integers(lo, hi, n)
    vals = [f"{prefix}{i}" for i in ids]
    out = [v + "x" * max(0, int(p) - len(v)) for v, p in zip(vals, pads)]
    return pa.array(out, type=pa.string())


def generate_wide_dataset() -> None:
    """ClickBench-shaped wide dataset: ~70 cols, WIDE_ROWS rows, written
    chunk-at-a-time so generation stays inside a few hundred MB of RAM.
    Strings sample from pools (URLs/titles repeat in real weblogs); the
    two filter columns keep the 10-col set's predicate semantics so the
    same transfer spec drives both datasets."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(DATA_DIR, exist_ok=True)
    if os.path.exists(WIDE_PARQUET) and os.path.exists(
            WIDE_PARQUET + ".expected.json"):
        return
    rng = np.random.default_rng(7)
    res_choices = np.array([1280, 1366, 1536, 1920, 2560, 360, 390],
                           dtype=np.int16)
    url_pool = _string_pool(rng, 500_000, "https://example.test/p/", 30, 90)
    title_pool = _string_pool(rng, 120_000, "Title ", 12, 40)
    referer_pool = _string_pool(rng, 200_000, "https://ref.test/r/", 20, 70)
    phrase_pool = pa.array(["", "", "", "buy tpu", "fast etl",
                            "weather tomorrow", "наушники", "котики"],
                           type=pa.string())
    charset_pool = pa.array(["utf-8", "windows-1251", "koi8-r", ""],
                            type=pa.string())
    model_pool = _string_pool(rng, 2000, "phone-", 6, 18)
    lang_pool = pa.array(["ru", "en", "de", "tr", "zh"], type=pa.string())
    color_pool = pa.array(list("KWGYRB"), type=pa.string())

    def dict_col(pool, idx):
        # materialize plain strings (arrow C++ take) and let the parquet
        # writer build per-row-group dict pages with its real fallback
        # behavior — writing a prebuilt DictionaryArray would embed the
        # FULL pool as every row group's dict page (a pathological file
        # no real writer produces)
        import pyarrow.compute as pc

        return pc.take(pool, pa.array(idx, type=pa.int32()))

    writer = None
    kept = 0
    chunk = 500_000
    try:
        for lo in range(0, WIDE_ROWS, chunk):
            n = min(chunk, WIDE_ROWS - lo)
            cols: dict[str, object] = {}
            for name, dt, bound in _WIDE_NUM_COLS:
                if name == "ResolutionWidth":
                    cols[name] = rng.choice(res_choices, n)
                elif bound == 2:
                    cols[name] = (rng.random(n) < 0.3).astype(np.int8)
                else:
                    cols[name] = rng.integers(0, bound, n).astype(dt)
            ev = (1_700_000_000 + rng.integers(0, 86_400 * 30, n)).astype(
                "datetime64[s]")
            cols["EventTime"] = pa.array(ev)
            cols["ClientEventTime"] = pa.array(ev + rng.integers(0, 120, n))
            cols["LocalEventTime"] = pa.array(ev + rng.integers(0, 3600, n))
            cols["URL"] = dict_col(url_pool,
                                   rng.integers(0, len(url_pool), n))
            cols["Title"] = dict_col(title_pool,
                                     rng.integers(0, len(title_pool), n))
            cols["Referer"] = dict_col(referer_pool,
                                       rng.integers(0, len(referer_pool), n))
            cols["SearchPhrase"] = dict_col(
                phrase_pool, rng.integers(0, len(phrase_pool), n))
            cols["PageCharset"] = dict_col(
                charset_pool, rng.integers(0, len(charset_pool), n))
            cols["MobilePhoneModel"] = dict_col(
                model_pool, rng.integers(0, len(model_pool), n))
            cols["BrowserLanguage"] = dict_col(
                lang_pool, rng.integers(0, len(lang_pool), n))
            cols["HitColor"] = dict_col(
                color_pool, rng.integers(0, len(color_pool), n))
            kept += int(((cols["RegionID"] < 400)
                         & (cols["ResolutionWidth"] >= 390)).sum())
            tbl = pa.table(cols)
            if writer is None:
                writer = pq.ParquetWriter(WIDE_PARQUET, tbl.schema,
                                          compression="snappy")
            writer.write_table(tbl, row_group_size=BATCH_ROWS)
    finally:
        if writer is not None:
            writer.close()
    with open(WIDE_PARQUET + ".expected.json", "w") as fh:
        json.dump({"rows": WIDE_ROWS, "kept": kept}, fh)


def make_transfer(process_count: int, parquet: str = PARQUET):
    from transferia_tpu.models import Transfer
    from transferia_tpu.models.transfer import (
        Runtime,
        ShardingUploadParams,
    )
    from transferia_tpu.providers.file import FileSourceParams
    from transferia_tpu.providers.stdout import NullTargetParams

    return Transfer(
        id="bench",
        src=FileSourceParams(path=parquet, format="parquet", table="hits",
                             batch_rows=BATCH_ROWS),
        dst=NullTargetParams(),
        transformation={"transformers": [
            {"mask_field": {"columns": ["URL"], "salt": "bench-salt"}},
            {"filter_rows": {
                "filter": "RegionID < 400 AND ResolutionWidth >= 390"}},
        ]},
        runtime=Runtime(sharding=ShardingUploadParams(
            process_count=process_count)),
    )


def run_pipeline(limit_rows: int | None = None,
                 process_count: int | None = None,
                 parquet: str = PARQUET,
                 total_rows: int = ROWS) -> tuple[int, float]:
    """Timed: parquet -> transform chain -> devnull sink, through the real
    snapshot loader (row-group parts in parallel so host decode, H2D,
    device hash, and D2H overlap across parts).  Returns (rows, seconds)."""
    from transferia_tpu.abstract.table import TableDescription
    from transferia_tpu.abstract.schema import TableID
    from transferia_tpu.coordinator import MemoryCoordinator
    from transferia_tpu.factories import make_sinker, new_storage
    from transferia_tpu.tasks import SnapshotLoader

    # the transformer chain fuses mask+filter into one device program by
    # default (transform/fused.py); no explicit backend switch needed
    if process_count is None:
        process_count = _auto_process_count()
    transfer = make_transfer(process_count, parquet)
    t0 = time.perf_counter()
    if limit_rows is not None:
        # warmup path: single-thread partial run to compile all programs
        storage = new_storage(transfer)
        sink = make_sinker(transfer, snapshot_stage=False)
        rows = 0

        class _Enough(Exception):
            pass

        def pusher(batch):
            nonlocal rows
            sink.push(batch)
            rows += batch.n_rows
            if rows >= limit_rows:
                raise _Enough()

        try:
            storage.load_table(
                TableDescription(id=TableID("fs", "hits")), pusher
            )
        except _Enough:
            pass
        return rows, time.perf_counter() - t0

    cp = MemoryCoordinator()
    loader = SnapshotLoader(transfer, cp, operation_id="bench-op")
    loader.upload_tables()
    dt = time.perf_counter() - t0
    prog = cp.operation_progress("bench-op")
    # completeness gate: with scan pushdown the coordinator counts
    # post-filter rows, so compare against the generator's ground truth
    # — a pushdown/transform bug that drops rows fails the bench loudly
    # instead of hiding inside a throughput number
    want = expected_kept(parquet)
    if want is not None and prog.completed_rows != want:
        raise AssertionError(
            f"row loss: sink got {prog.completed_rows} rows, chain "
            f"semantics require {want}")
    # the throughput denominator is the SOURCE table size: the snapshot's
    # job is to process the whole table, however much of it pushdown let
    # it skip
    return total_rows, dt


_PROBE_SCRIPT = r"""
import faulthandler, sys, time
trace = open(sys.argv[1], "w")
faulthandler.enable(file=trace)
faulthandler.dump_traceback_later(60, repeat=True, file=trace)
t0 = time.time()
import jax
print(f"probe: jax {jax.__version__} imported +{time.time()-t0:.1f}s",
      flush=True)
d = jax.devices()
print(f"probe: devices +{time.time()-t0:.1f}s "
      f"{[x.platform for x in d]}", flush=True)
x = jax.numpy.ones((512, 512), dtype=jax.numpy.bfloat16)
(x @ x).block_until_ready()
print(f"ok {d[0].platform.lower()} init_s={time.time()-t0:.1f}",
      flush=True)
"""


def _device_available(timeout_s: float | None = None) -> bool:
    """Probe jax device init in a subprocess — a wedged TPU runtime hangs
    indefinitely in-process, and the bench must always print its JSON.

    One probe with a long budget (cold axon-plugin init can exceed 90s —
    both r01/r02 probes died at shorter timeouts), faulthandler stack
    dumps every 60s so a wedge is diagnosable post-mortem, and a tiny
    matmul so 'available' means 'actually computes', not just
    'registered'.  BENCH_PROBE_TIMEOUT overrides the budget."""
    import subprocess
    import tempfile

    if timeout_s is None:
        timeout_s = knobs.env_float("BENCH_PROBE_TIMEOUT", 330.0)
    trace_path = os.path.join(tempfile.gettempdir(),
                              "trtpu_bench_probe_trace.log")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SCRIPT, trace_path],
            capture_output=True, timeout=timeout_s,
        )
        out = proc.stdout.decode(errors="replace").strip()
        for line in out.splitlines():
            print(f"# {line}", file=sys.stderr)
        last = out.splitlines()[-1] if out else ""
        if last.startswith("ok "):
            platform = last.split()[1]
            # an accelerator platform only: a jax that silently fell
            # back to CPU must NOT be recorded as a device number
            if platform in ("tpu", "axon", "neuron"):
                return True
            print(f"# device probe found non-accelerator platform "
                  f"{platform!r}; treating as unavailable",
                  file=sys.stderr)
            return False
        print(f"# device probe failed: rc={proc.returncode} "
              f"stderr={proc.stderr[-300:].decode(errors='replace')}",
              file=sys.stderr)
    except subprocess.TimeoutExpired:
        print(f"# device probe timed out ({timeout_s:.0f}s) — TPU "
              f"runtime init hung; last stacks:", file=sys.stderr)
        try:
            with open(trace_path) as fh:
                tail = fh.read().strip().splitlines()[-12:]
            for line in tail:
                print(f"#   {line}", file=sys.stderr)
        except OSError:
            pass
    return False


def _force_cpu_backend() -> bool:
    """Persistent TPU-init failure: fall back to the host pipeline so the
    bench still measures end-to-end (labeled as a fallback in the JSON —
    NOT a TPU number).  Device fusion is disabled: with no accelerator, the
    C++ batched HMAC + numpy predicate host path outruns XLA-on-CPU.
    Returns False when a jax backend is already live (too late to flip)."""
    from transferia_tpu.testing import force_virtual_cpu_mesh
    from transferia_tpu.transform.fused import set_device_fusion

    set_device_fusion(False)
    return force_virtual_cpu_mesh(1)


def measure_device_kernel(rows: int = 1 << 20) -> Optional[dict]:
    """Sustained on-chip HMAC-SHA256 mask throughput, data resident.

    This isolates the device kernel from the host↔device link: one large
    launch amortizes the per-launch overhead (through a tunneled dev
    device that overhead is ~70ms — see ops/linkprobe.py), and timing
    spans several back-to-back launches on resident buffers.  It is the
    honest measure of what the chip itself sustains on the mask op; the
    end-to-end number above includes the link, which on this environment
    is the binding constraint (the tail prints both so the gap is
    attributable).
    """
    import jax
    import jax.numpy as jnp

    from transferia_tpu.ops.sha256 import _hmac_key_states, hmac_device_core

    backend = jax.default_backend()
    if backend == "cpu":
        return None
    mb = 2  # 2 SHA blocks/row: a ~60-90 byte URL, the ClickBench shape
    rng = np.random.default_rng(11)
    blocks = rng.integers(0, 256, size=(rows, mb * 64), dtype=np.uint8)
    nblocks = np.full(rows, mb, dtype=np.int32)
    inner, outer = _hmac_key_states(b"bench-salt")
    st_i, st_o = jnp.asarray(inner[0]), jnp.asarray(outer[0])
    fn = jax.jit(lambda b, nb: hmac_device_core(b, nb, st_i, st_o, mb))
    db = jax.device_put(blocks)
    dnb = jax.device_put(nblocks)
    fn(db, dnb).block_until_ready()  # compile + warm
    iters = 4
    t0 = time.perf_counter()
    outs = [fn(db, dnb) for _ in range(iters)]
    for o in outs:
        o.block_until_ready()
    dt = time.perf_counter() - t0
    rps = rows * iters / dt
    return {
        "metric": "device_mask_kernel_rows_per_sec",
        "value": round(rps),
        "unit": "rows/sec",
        "vs_baseline": round(rps / 10_000_000, 4),
        "backend": backend,
        "launch_rows": rows,
        "sha_blocks_per_row": mb,
    }


def measure_device_decode(rows: int = 1 << 22) -> Optional[dict]:
    """Sustained ON-CHIP RLE-dictionary decode: bit-unpack of packed
    codes + dictionary gather on resident buffers (ops/decode.py).

    This is the "columnar decode on TPU" clause of BASELINE.json
    config 3, proven the same way as the mask kernel: the end-to-end
    pipeline keeps decode on the host because the tunneled link loses to
    the C++ path (auto-placement's call), but the chip itself must be
    shown sustaining the op.  Shape mirrors the wide bench's URL column:
    bit_width 17 codes against a 131072-entry pool."""
    import jax

    backend = jax.default_backend()
    if backend == "cpu":
        return None
    from transferia_tpu.ops.decode import decode_dict_run

    bw = 17
    rng = np.random.default_rng(13)
    n_pool = 1 << bw
    pool = rng.integers(-10**9, 10**9, n_pool).astype(np.int32)
    codes = rng.integers(0, n_pool, rows, dtype=np.uint64)
    # pack on host (numpy): little-endian bit stream
    nbits = rows * bw
    words64 = np.zeros((nbits + 31) // 32, dtype=np.uint64)
    starts = np.arange(rows, dtype=np.uint64) * np.uint64(bw)
    wi = (starts >> np.uint64(5)).astype(np.int64)
    off = (starts & np.uint64(31))
    np.bitwise_or.at(words64, wi,
                     (codes << off) & np.uint64(0xFFFFFFFF))
    spill = off + np.uint64(bw) > np.uint64(32)
    np.bitwise_or.at(words64, wi[spill] + 1,
                     codes[spill] >> (np.uint64(32) - off[spill]))
    words = words64.astype(np.uint32)
    from transferia_tpu.ops.decode import decode_dict_loop

    dwords = jax.device_put(words)
    dpool = jax.device_put(pool)
    out = decode_dict_run(dwords, dpool, bw, rows)
    out.block_until_ready()  # compile + warm
    # prove the chip really decoded: sample-compare against the host
    sample = np.asarray(out[:4096])
    expect = pool[codes[:4096].astype(np.int64)]
    if not np.array_equal(sample, expect):
        raise AssertionError("device decode mismatch vs host reference")
    # Sustained rate: the op is pure HBM traffic (~8 bytes/row), so a
    # tunneled link's ~100ms launch overhead would dominate any
    # launch-per-iteration loop.  decode_dict_loop runs the decode
    # back-to-back INSIDE one launch (carry-serialized against CSE) —
    # calibrate iterations so on-chip work dwarfs one launch.
    # NOTE the int(): on the tunneled runtime block_until_ready returns
    # early for scalar results — fetching the VALUE is the only honest
    # sync.  64 in-launch iterations keep the single launch well under
    # runtime watchdogs (a 4096-iteration launch faulted the device).
    iters = 64
    int(decode_dict_loop(dwords, dpool, bw, rows, iters))  # compile+warm
    t0 = time.perf_counter()
    int(decode_dict_loop(dwords, dpool, bw, rows, iters))
    dt = time.perf_counter() - t0
    rps = rows * iters / dt
    # HBM per decode: words in + code gather + values out (+pool, small)
    bytes_per_iter = words.nbytes * 2 + 4 * rows + 4 * rows
    return {
        "metric": "device_decode_rows_per_sec",
        "value": round(rps),
        "unit": "rows/sec",
        "vs_baseline": round(rps / 10_000_000, 4),
        "backend": backend,
        "bit_width": bw,
        "pool_entries": n_pool,
        "launch_rows": rows,
        "loop_iters": iters,
        "hbm_gb_per_sec": round(rps / rows * bytes_per_iter / 1e9, 1),
        # gatherless lane unpack made the bit-unpack VPU work; the
        # remaining bound is the dictionary gather itself (~140M
        # random gathers/s on v5e)
        "note": "single-launch fori_loop, resident buffers; gather-bound",
    }


def _run_isolated(fn_name: str, timeout: float) -> Optional[dict]:
    """Run one measure_* function in a subprocess with a hard timeout;
    returns its JSON result or None.  Used for measures whose device
    compiles could hang a wedged runtime."""
    import subprocess

    code = (
        "import json, sys; sys.path.insert(0, %r); import bench; "
        "out = bench.%s(); "
        "print('@@RESULT@@' + json.dumps(out) if out else '')"
        % (os.path.dirname(os.path.abspath(__file__)), fn_name)
    )
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        print(f"# {fn_name}: timed out after {timeout}s (skipped)",
              file=sys.stderr)
        return None
    for line in proc.stdout.decode("utf-8", "replace").splitlines():
        if line.startswith("@@RESULT@@"):
            return json.loads(line[len("@@RESULT@@"):])
    # a crash must not masquerade as a benign skip
    if proc.returncode != 0:
        tail = proc.stderr.decode("utf-8", "replace")[-300:]
        print(f"# {fn_name}: subprocess exited {proc.returncode}: {tail}",
              file=sys.stderr)
    return None


def measure_device_fingerprint(rows: int = 1 << 20) -> Optional[dict]:
    """Sustained ON-CHIP checksum-fingerprint rate (ops/rowhash.py
    DeviceFingerprintProgram) — the proof-point the mask and decode
    kernels already have.  End-to-end fingerprinting stays on the host
    here because 72 bytes/row H2D through the tunneled link loses to the
    C++ polyhash (auto-placement's call); this isolates what the chip
    sustains on resident buffers.  Shape: one int64 column + one 64-byte
    var-width column, the checksum task's typical mix."""
    import jax
    import jax.numpy as jnp

    backend = jax.default_backend()
    if backend == "cpu":
        return None
    from transferia_tpu.abstract.schema import (
        CanonicalType,
        ColSchema,
        TableID,
        TableSchema,
    )
    from transferia_tpu.columnar.batch import Column, ColumnBatch
    from transferia_tpu.ops import rowhash

    rng = np.random.default_rng(17)
    ids = rng.integers(0, 2**62, rows)
    urls = [f"https://example.test/p/{i % 997:04d}/x" for i in range(256)]
    data = np.frombuffer(("".join(urls[i % 256] for i in range(rows))
                          ).encode(), dtype=np.uint8)
    lens = np.array([len(urls[i % 256]) for i in range(rows)],
                    dtype=np.int64)
    offsets = np.zeros(rows + 1, dtype=np.int32)
    np.cumsum(lens, out=offsets[1:])
    schema = TableSchema([
        ColSchema("id", CanonicalType.INT64, primary_key=True),
        ColSchema("url", CanonicalType.UTF8),
    ])
    batch = ColumnBatch(TableID("b", "fp"), schema, {
        "id": Column("id", CanonicalType.INT64, ids.astype(np.int64)),
        "url": Column("url", CanonicalType.UTF8, data, offsets),
    })
    cols, n_rows = rowhash.prep_batch(batch)
    prog = rowhash.DeviceFingerprintProgram()
    # build the resident argument set exactly as dispatch() does, once
    from transferia_tpu.columnar.batch import bucket_rows

    bucket = bucket_rows(n_rows)
    assert bucket == n_rows  # power-of-two rows: no padding
    sig = tuple((c.kind, c.width if c.kind == "var" else 0)
                for c in cols)
    fn = prog._program_for(sig)
    fixed_lo = tuple(jnp.asarray(c.lo) for c in cols
                     if c.kind == "fixed")
    fixed_hi = tuple(jnp.asarray(c.hi) for c in cols
                     if c.kind == "fixed")
    var_blocks = tuple(jnp.asarray(c.ensure_blocks()) for c in cols
                       if c.kind == "var")
    validities = tuple(None for _ in cols)
    rowmask = jnp.ones(n_rows, dtype=jnp.bool_)
    seeds1 = jnp.asarray(np.array(
        [rowhash._col_seed(c.name, 0) for c in cols], dtype=np.uint32))
    seeds2 = jnp.asarray(np.array(
        [rowhash._col_seed(c.name, 1) for c in cols], dtype=np.uint32))
    nulls1 = jnp.asarray(np.full(len(cols), rowhash._NULL1, np.uint32))
    nulls2 = jnp.asarray(np.full(len(cols), rowhash._NULL2, np.uint32))
    powers1 = tuple(jnp.asarray(rowhash._powers(c.width, int(rowhash._P1)))
                    for c in cols if c.kind == "var")
    powers2 = tuple(jnp.asarray(rowhash._powers(c.width, int(rowhash._P2)))
                    for c in cols if c.kind == "var")

    import functools

    # NOTE: the big arrays ride as ARGUMENTS — captured as closure
    # constants they embed into the program and compilation stalls
    @functools.partial(jax.jit, static_argnums=(0,))
    def loop(iters, flo, fhi, vb, rm, s1, s2, p1, p2):
        def body(i, acc):
            out = fn(flo, fhi, vb, (), (), (), validities, rm,
                     s1 ^ (acc & jnp.uint32(1)), s2,
                     nulls1, nulls2, p1, p2)
            return acc + out[0]

        return jax.lax.fori_loop(0, iters, body, jnp.uint32(0))

    iters = 64
    # ONE compiled shape: the warm call uses the same static iters (a
    # second compile through a cold tunnel could eat the subprocess
    # timeout); value fetch = the only honest sync on this runtime
    int(loop(iters, fixed_lo, fixed_hi, var_blocks, rowmask,
             seeds1, seeds2, powers1, powers2))
    t0 = time.perf_counter()
    int(loop(iters, fixed_lo, fixed_hi, var_blocks, rowmask,
             seeds1, seeds2, powers1, powers2))
    dt = time.perf_counter() - t0
    rps = rows * iters / dt
    return {
        "metric": "device_fingerprint_rows_per_sec",
        "value": round(rps),
        "unit": "rows/sec",
        "vs_baseline": round(rps / 10_000_000, 4),
        "backend": backend,
        "launch_rows": rows,
        "loop_iters": iters,
        "cols": "int64 + 64B var",
        "note": "single-launch fori_loop on resident buffers",
    }


def measure_mesh_1dev(rows: int = 1 << 17) -> Optional[dict]:
    """ShardedFusedProgram on a 1-device mesh on the REAL chip, vs the
    plain fused device program on the same inputs.

    The mesh path's correctness is pinned on the virtual CPU mesh
    (tests + dryrun_multichip); this line gives it hardware execution
    evidence and quantifies the mesh wrapper's overhead at N=1 — the
    delta an operator pays to run the multichip-shaped program before
    adding chips.
    """
    import jax

    if jax.default_backend() == "cpu":
        return None
    from transferia_tpu.ops.fused import FusedMaskFilterProgram
    from transferia_tpu.parallel.fusedmesh import ShardedFusedProgram
    from transferia_tpu.predicate.parser import parse as pred_parse

    rng = np.random.default_rng(21)
    urls = np.char.add("https://example-",
                       rng.integers(0, 997, rows).astype("U4"))
    flat = "".join(urls.tolist()).encode()
    lens = np.array([len(u) for u in urls], dtype=np.int64)
    offsets = np.zeros(rows + 1, dtype=np.int32)
    np.cumsum(lens, out=offsets[1:])
    data = np.frombuffer(flat, dtype=np.uint8)
    region = rng.integers(0, 500, rows).astype(np.int32)
    node = pred_parse("RegionID < 400")
    mask_cols = [(data, offsets)]
    pred_cols = {"RegionID": (region, None)}

    # Tunneled-link methodology: the r04 capture swung 0.3%..18.6%
    # overhead because 3-iteration MEANS absorb every RTT spike of the
    # proxied device.  Interleave plain/mesh iterations (drift hits both
    # alike) and compare MEDIANS; report the spread so a noisy link is
    # visible in the record instead of masquerading as mesh overhead.
    plain = FusedMaskFilterProgram([b"bench-salt"], node)
    sharded = ShardedFusedProgram([b"bench-salt"], node)
    plain.run(mask_cols, pred_cols, rows)    # compile + warm
    out = sharded.run(mask_cols, pred_cols, rows)
    iters = 9
    plain_ts, mesh_ts = [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        plain.run(mask_cols, pred_cols, rows)
        plain_ts.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        out = sharded.run(mask_cols, pred_cols, rows)
        mesh_ts.append(time.perf_counter() - t0)
    import statistics

    plain_s = statistics.median(plain_ts)
    mesh_s = statistics.median(mesh_ts)
    spread_pct = round(100 * (max(mesh_ts) - min(mesh_ts))
                       / max(mesh_s, 1e-9), 1)
    hexes, keep = out
    kept = int(keep.sum()) if keep is not None else rows
    if sharded.last_kept != kept:
        raise AssertionError(
            f"mesh psum kept {sharded.last_kept} != host keep {kept}")
    return {
        "metric": "mesh1_fused_ms_per_batch",
        "unit": "ms",
        "value": round(mesh_s * 1000, 2),
        "plain_device_ms": round(plain_s * 1000, 2),
        "mesh_overhead_pct": round(100 * (mesh_s - plain_s)
                                   / max(plain_s, 1e-9), 1),
        "iter_spread_pct": spread_pct,
        "iters": iters,
        "rows": rows,
        "devices": sharded.n_dev,
        "kept": kept,
        # medians pinned the r04 mystery: the 0.3..18.6% swing was
        # 3-iter means eating tunnel RTT spikes.  The REAL N=1 delta
        # (~30% here) is transfer scheduling: the mesh program ships one
        # monolithic padded block over the tunneled link while the plain
        # program overlaps link-model-sized chunks; on locally-attached
        # multi-chip meshes the shard transfers parallelize instead.
        "note": "overhead=monolithic vs chunked transfer at N=1",
    }


def measure_fingerprint(n_batches: int = 15) -> Optional[dict]:
    """Checksum-fingerprint throughput over the ClickBench batches.

    The checksum task's fingerprint method (tasks/checksum.py,
    ops/rowhash.py): order-independent two-lane digest, backend chosen
    by measurement (device reduction when the link supports it, the C++
    single-pass polyhash otherwise).  Full-table validation speed is a
    first-class metric for a data-transfer framework — this one runs at
    memory-bandwidth-adjacent speed on the host path.
    """
    from transferia_tpu.abstract.schema import TableID
    from transferia_tpu.abstract.table import TableDescription
    from transferia_tpu.factories import new_storage
    from transferia_tpu.ops.rowhash import TableFingerprinter

    transfer = make_transfer(process_count=1)
    storage = new_storage(transfer)
    batches = []

    class _Enough(Exception):
        pass

    def collect(batch):
        batches.append(batch)
        if len(batches) >= n_batches:
            raise _Enough()

    try:
        storage.load_table(
            TableDescription(id=TableID("fs", "hits")), collect)
    except _Enough:
        pass
    if not batches:
        return None
    # warm: let auto decide on real batches AND pay any device compile
    # outside the timed window (the jit cache is module-global, so the
    # timed instance reuses the compiled program)
    warm = TableFingerprinter(backend="auto")
    warm.push(batches[0])
    warm.push(batches[0])
    warm.result()
    decided = warm._decided or "host"
    fp = TableFingerprinter(backend=decided)
    rows = sum(b.n_rows for b in batches)
    t0 = time.perf_counter()
    for b in batches:
        fp.push(b)
    agg = fp.result()
    dt = time.perf_counter() - t0
    return {
        "metric": "checksum_fingerprint_rows_per_sec",
        "value": round(rows / dt),
        "unit": "rows/sec",
        "rows": rows,
        "backend": decided,
        "digest": agg.digest(),
    }


def measure_transform_latency(n_batches: int = 16) -> list:
    """Steady-state single-stream per-batch transform latency (the
    BASELINE kafka2ch config's headline metric shape): one warm chain
    instance, the first (compile-carrying) apply discarded, no competing
    upload threads — unlike the throughput run, where apply windows
    include cross-thread device queueing."""
    from transferia_tpu.abstract.schema import TableID
    from transferia_tpu.abstract.table import TableDescription
    from transferia_tpu.factories import new_storage
    from transferia_tpu.transform.chain import build_chain

    transfer = make_transfer(process_count=1)
    chain = build_chain(transfer.transformation)
    storage = new_storage(transfer)
    batches = []

    class _Enough(Exception):
        pass

    def collect(batch):
        batches.append(batch)
        if len(batches) >= n_batches + 1:
            raise _Enough()

    try:
        storage.load_table(
            TableDescription(id=TableID("fs", "hits")), collect)
    except _Enough:
        pass
    if not batches:
        return []
    # warm: under auto placement the first applies are the strategy
    # probes (host measure, then — link permitting — the device probe
    # whose first launch carries the XLA compile); three warm applies
    # cover host + compile + steady device so the timed loop below is
    # pure steady state for whichever strategy the tuner kept
    for _ in range(3):
        chain.apply(batches[0])
    out = []
    for b in batches[1:]:
        t0 = time.perf_counter()
        chain.apply(b)
        out.append(time.perf_counter() - t0)
    # expose what the auto-tuner decided for this chain (tail diagnostics)
    try:
        from transferia_tpu.transform.fused import DeviceFusedStep

        plan = chain.plan_for(batches[0].table_id, batches[0].schema)
        for step in plan.steps:
            if isinstance(step, DeviceFusedStep):
                global _placement_note
                _placement_note = step.placement_summary()
    except Exception:
        pass
    return out


_placement_note = ""


def measure_kafka2ch(n_partitions: int = 16,
                     msgs_per_partition: int = 1500) -> dict:
    """BASELINE kafka2ch config: fake-Kafka JSON -> parser -> mask+filter
    chain -> ClickHouse sink; returns steady-state replication-path
    transform latency (the chain.apply window inside the sink middleware
    stack) and end-to-end rows/sec.  Uses the in-repo fake wire servers
    (tests/recipes) — the same servers the e2e suite authenticates
    against."""
    import threading

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tests.recipes.fake_clickhouse import FakeCH
    from tests.recipes.fake_kafka import FakeKafka
    from transferia_tpu.coordinator import MemoryCoordinator
    from transferia_tpu.models import Transfer, TransferType
    from transferia_tpu.providers.clickhouse import CHTargetParams
    from transferia_tpu.providers.kafka.client import KafkaClient, Record
    from transferia_tpu.providers.kafka.provider import KafkaSourceParams
    from transferia_tpu.runtime.local import run_replication
    from transferia_tpu.stats import stagetimer

    srv = FakeKafka(n_partitions=n_partitions).start()
    ch = FakeCH().start()
    try:
        seed = KafkaClient([f"127.0.0.1:{srv.port}"])
        srv.create_topic("hits")
        for p in range(n_partitions):
            seed.produce("hits", p, [
                Record(key=b"", value=json.dumps({
                    "id": p * msgs_per_partition + i,
                    "url": f"https://bench.example/{i}",
                    "region": i % 500,
                }).encode())
                for i in range(msgs_per_partition)
            ])
        seed.close()
        cp = MemoryCoordinator()
        t = Transfer(
            id="bench-k2ch", type=TransferType.INCREMENT_ONLY,
            src=KafkaSourceParams(
                brokers=[f"127.0.0.1:{srv.port}"], topic="hits",
                parallelism=4,
                parser={"json": {"schema": [
                    {"name": "id", "type": "int64", "key": True},
                    {"name": "url", "type": "utf8"},
                    {"name": "region", "type": "int32"},
                ], "table": "hits"}},
            ),
            dst=CHTargetParams(host="127.0.0.1", port=ch.port,
                               bufferer=None),
            transformation={"transformers": [
                {"mask_field": {"columns": ["url"], "salt": "bench"}},
                {"filter_rows": {"filter": "region < 400"}},
            ]},
        )
        expected = sum(1 for _ in range(n_partitions)
                       for i in range(msgs_per_partition)
                       if i % 500 < 400)
        stagetimer.collect_samples("transform")
        stagetimer.reset()
        stop = threading.Event()
        th = threading.Thread(
            target=run_replication, args=(t, cp),
            kwargs={"stop_event": stop, "backoff": 0.2}, daemon=True,
        )
        t0 = time.perf_counter()
        th.start()

        def ch_rows():
            return ch.total_rows()

        deadline = time.monotonic() + 120
        while ch_rows() < expected and time.monotonic() < deadline:
            time.sleep(0.05)
        dt = time.perf_counter() - t0
        stop.set()
        th.join(timeout=10)
        rows = ch_rows()
        lat = sorted(stagetimer.samples("transform"))
        out = {
            "metric": "kafka2ch_transform_p99_ms",
            "unit": "ms",
            "rows": rows,
            "rows_per_sec": round(rows / dt) if dt else 0,
        }
        if lat:
            import math

            n = len(lat)
            # drop the first (compile-carrying) sample per part stream
            steady = lat[:max(1, n - 1)] if n > 4 else lat
            out["value"] = round(
                steady[max(0, math.ceil(0.99 * len(steady)) - 1)] * 1000,
                3)
            out["p50_ms"] = round(
                steady[max(0, math.ceil(0.50 * len(steady)) - 1)] * 1000,
                3)
            out["batches"] = n
        return out
    finally:
        srv.stop()
        ch.stop()


_bench_lambda_jit = {}


def bench_lambda(arrays: dict) -> dict:
    """User lambda for the SR fan-in config: a jax.jit columns transform
    (sign-flip ids outside the region window) — the `lambda` transformer
    resolves it by "bench:bench_lambda"."""
    import jax
    import jax.numpy as jnp

    fn = _bench_lambda_jit.get("fn")
    if fn is None:
        fn = jax.jit(lambda ids, region:
                     jnp.where(region < 400, ids, -ids))
        _bench_lambda_jit["fn"] = fn
    return {"id": np.asarray(fn(arrays["id"], arrays["region"]))}


def measure_pg2ch(rows: int = 300_000) -> dict:
    """BASELINE pg2ch config: PG COPY snapshot -> SQL-predicate
    transformer -> ClickHouse sink, through the real activate path
    against the in-repo fake wire servers."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tests.recipes.fake_clickhouse import FakeCH
    from tests.recipes.fake_postgres import FakePG, FakeTable
    from transferia_tpu.coordinator import MemoryCoordinator
    from transferia_tpu.models import Transfer
    from transferia_tpu.providers.clickhouse import CHTargetParams
    from transferia_tpu.providers.postgres import PGSourceParams
    from transferia_tpu.tasks import activate_delivery

    pg = FakePG().start()
    ch = FakeCH().start()
    try:
        pg.add_table(FakeTable(
            "public", "hits",
            [("id", "bigint", True, True),
             ("url", "text", False, False),
             ("region", "integer", False, False),
             ("score", "double precision", False, False)],
            [{"id": str(i), "url": f"https://e.test/{i % 997}",
              "region": str(i % 500), "score": f"{(i % 91) * 1.5}"}
             for i in range(rows)],
        ))
        t = Transfer(
            id="bench-pg2ch",
            src=PGSourceParams(host="127.0.0.1", port=pg.port,
                               database="db", user="u"),
            dst=CHTargetParams(host="127.0.0.1", port=ch.port,
                               bufferer=None),
            transformation={"transformers": [
                {"filter_rows": {
                    "filter": "region < 400 AND score >= 10"}},
            ]},
        )
        t0 = time.perf_counter()
        activate_delivery(t, MemoryCoordinator())
        dt = time.perf_counter() - t0
        got = ch.total_rows()
        expected = sum(1 for i in range(rows)
                       if i % 500 < 400 and (i % 91) * 1.5 >= 10)
        if got != expected:
            raise AssertionError(f"pg2ch row loss: {got} != {expected}")
        return {"metric": "pg2ch_snapshot_rows_per_sec",
                "value": round(rows / dt), "unit": "rows/sec",
                "rows": rows, "sink_rows": got,
                "seconds": round(dt, 2)}
    finally:
        pg.stop()
        ch.stop()


def measure_mysql2kafka(rows: int = 200_000,
                        n_partitions: int = 16) -> dict:
    """BASELINE mysql2kafka config: MySQL snapshot -> PII mask ->
    Debezium-envelope serializer -> partitioned Kafka producer across 16
    partitions (the CDC envelope path at snapshot volume; binlog-tail
    latency is covered by the replication e2e suite)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tests.recipes.fake_kafka import FakeKafka
    from tests.recipes.fake_mysql import FakeMySQL, FakeMyTable
    from transferia_tpu.coordinator import MemoryCoordinator
    from transferia_tpu.models import Transfer
    from transferia_tpu.providers.kafka.provider import KafkaTargetParams
    from transferia_tpu.providers.mysql import MySQLSourceParams
    from transferia_tpu.tasks import activate_delivery

    my = FakeMySQL().start()
    kf = FakeKafka(n_partitions=n_partitions).start()
    try:
        my.add_table(FakeMyTable(
            "db", "users",
            [("id", "bigint", "bigint", True, True),
             ("email", "varchar", "varchar(255)", False, False),
             ("region", "int", "int", False, False)],
            [{"id": i, "email": f"user{i}@example.test",
              "region": i % 500} for i in range(rows)],
        ))
        t = Transfer(
            id="bench-my2kf",
            src=MySQLSourceParams(host="127.0.0.1", port=my.port,
                                  database="db", user="root"),
            dst=KafkaTargetParams(
                brokers=[f"127.0.0.1:{kf.port}"], topic="cdc",
                serializer="debezium"),
            transformation={"transformers": [
                {"mask_field": {"columns": ["email"],
                                "salt": "bench"}},
            ]},
        )
        t0 = time.perf_counter()
        activate_delivery(t, MemoryCoordinator())
        dt = time.perf_counter() - t0
        got = sum(len(p) for p in kf.topics.get("cdc", []))
        if got != rows:
            raise AssertionError(f"mysql2kafka row loss: {got} != {rows}")
        return {"metric": "mysql2kafka_debezium_rows_per_sec",
                "value": round(rows / dt), "unit": "rows/sec",
                "rows": rows, "partitions": n_partitions,
                "seconds": round(dt, 2)}
    finally:
        my.stop()
        kf.stop()


def measure_kafka_sr2ch(n_partitions: int = 64,
                        msgs_per_partition: int = 1200) -> dict:
    """BASELINE Kafka+Confluent-SR -> CH config: 64-partition fan-in of
    confluent-wire AVRO records resolved through the fake schema
    registry, a user jax.jit lambda transformer, ClickHouse sink."""
    import threading

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tests.recipes.fake_clickhouse import FakeCH
    from tests.recipes.fake_kafka import FakeKafka
    from tests.recipes.fake_sr import FakeSchemaRegistry
    from transferia_tpu.coordinator import MemoryCoordinator
    from transferia_tpu.models import Transfer, TransferType
    from transferia_tpu.providers.clickhouse import CHTargetParams
    from transferia_tpu.providers.kafka.client import KafkaClient, Record
    from transferia_tpu.providers.kafka.provider import KafkaSourceParams
    from transferia_tpu.runtime.local import run_replication

    def zz(n: int) -> bytes:
        u = (n << 1) ^ (n >> 63) if n < 0 else (n << 1)
        out = bytearray()
        while True:
            b = u & 0x7F
            u >>= 7
            out.append(b | (0x80 if u else 0))
            if not u:
                return bytes(out)

    schema_json = json.dumps({
        "type": "record", "name": "Hit", "fields": [
            {"name": "id", "type": "long"},
            {"name": "url", "type": "string"},
            {"name": "region", "type": "int"},
        ]})
    sr = FakeSchemaRegistry().start()
    srv = FakeKafka(n_partitions=n_partitions).start()
    ch = FakeCH().start()
    try:
        import urllib.request

        req = urllib.request.Request(
            sr.url + "/subjects/hits-value/versions",
            data=json.dumps({"schema": schema_json}).encode(),
            headers={"Content-Type":
                     "application/vnd.schemaregistry.v1+json"})
        sid = json.loads(urllib.request.urlopen(req,
                                                timeout=10).read())["id"]
        seed = KafkaClient([f"127.0.0.1:{srv.port}"])
        srv.create_topic("hits")
        header = b"\x00" + sid.to_bytes(4, "big")
        for p in range(n_partitions):
            recs = []
            for i in range(msgs_per_partition):
                rid = p * msgs_per_partition + i
                url = f"https://e.test/{rid % 997}".encode()
                body = (zz(rid) + zz(len(url)) + url
                        + zz(rid % 500))
                recs.append(Record(key=b"", value=header + body))
            seed.produce("hits", p, recs)
        seed.close()
        t = Transfer(
            id="bench-sr2ch", type=TransferType.INCREMENT_ONLY,
            src=KafkaSourceParams(
                brokers=[f"127.0.0.1:{srv.port}"], topic="hits",
                parallelism=4,
                parser={"confluent_schema_registry": {
                    "registry_url": sr.url, "table": "hits"}},
            ),
            dst=CHTargetParams(host="127.0.0.1", port=ch.port,
                               bufferer=None),
            transformation={"transformers": [
                # user lambda as a jax.jit program (bench_lambda below)
                {"lambda": {"function": "bench:bench_lambda"}},
            ]},
        )
        expected = n_partitions * msgs_per_partition
        cp = MemoryCoordinator()
        stop = threading.Event()
        th = threading.Thread(
            target=run_replication, args=(t, cp),
            kwargs={"stop_event": stop, "backoff": 0.2}, daemon=True)
        t0 = time.perf_counter()
        th.start()

        def ch_rows():
            return ch.total_rows()

        deadline = time.monotonic() + 180
        while ch_rows() < expected and time.monotonic() < deadline:
            time.sleep(0.05)
        dt = time.perf_counter() - t0
        stop.set()
        th.join(timeout=10)
        got = ch_rows()
        if got != expected:
            raise AssertionError(
                f"kafka-sr2ch row loss: {got} != {expected}")
        return {"metric": "kafka_sr64_2ch_rows_per_sec",
                "value": round(got / dt), "unit": "rows/sec",
                "rows": got, "partitions": n_partitions,
                "seconds": round(dt, 2)}
    finally:
        sr.stop()
        srv.stop()
        ch.stop()


def _trace_out_path() -> str:
    """Timeline artifact control: `--trace[=path]` argv or BENCH_TRACE
    env.  When set, the headline window records pipeline spans
    (stats/trace.py) and writes a Perfetto-loadable trace.json next to
    the usual stderr diagnostics — every benchmark run can then ship a
    timeline artifact alongside its numbers."""
    out = knobs.env_str("BENCH_TRACE", "")
    for a in sys.argv[1:]:
        if a == "--trace":
            out = out or os.path.join(DATA_DIR, "bench_trace.json")
        elif a.startswith("--trace="):
            out = a.split("=", 1)[1]
    return out


# -- regression gate (--against) ---------------------------------------------

# every metric line printed this run (tail diagnostics + headline):
# the --against gate compares THESE against a prior bench artifact
_METRICS_EMITTED: list[dict] = []


def _emit(obj: dict) -> None:
    """One '#'-prefixed stderr metric line, remembered for --against."""
    _METRICS_EMITTED.append(obj)
    print(f"# {json.dumps(obj)}", file=sys.stderr)


# metrics where smaller is the improvement (latencies); everything else
# is a throughput/ratio where bigger is better
_LOWER_IS_BETTER = ("_ms", "_ms_per_batch")

# default band: a candidate may be up to this fraction WORSE than the
# prior before the gate trips
DEFAULT_TOLERANCE = 0.15

# per-metric bands for the known-noisy lines (tunneled-link device
# numbers swing with RTT; the fake-wire configs are scheduling-bound on
# the 1-core bench boxes)
TOLERANCE_OVERRIDES = {
    "device_mask_kernel_rows_per_sec": 0.5,
    "device_decode_rows_per_sec": 0.5,
    "device_fingerprint_rows_per_sec": 0.5,
    "mesh1_fused_ms_per_batch": 0.6,
    "kafka2ch_transform_p99_ms": 0.6,
    "kafka_sr64_2ch_rows_per_sec": 0.4,
    "mysql2kafka_debezium_rows_per_sec": 0.4,
    "pg2ch_snapshot_rows_per_sec": 0.4,
    "fleet_transfers_per_sec": 0.4,
    # merged-histogram dispatch tails (fleet/bench.py via stats/hdr.py):
    # scheduling-bound on the 1-core bench boxes, and the p999 of a
    # ~100-sample window is a single observation — wide bands on
    # purpose; the histogram's merge==concat exactness is pinned by
    # unit tests, not by run-to-run latency stability
    "fleet_dispatch_p50_ms": 0.6,
    "fleet_dispatch_p99_ms": 0.8,
    "fleet_dispatch_p999_ms": 1.0,
    # end-to-end freshness p99 (SLO plane): wall-clock from the sample
    # source's event-time stamp to sink publish — dominated by queue
    # wait on the 1-core boxes, so it swings with scheduling like the
    # dispatch tails above; the SLO verdict math is pinned by
    # tests/unit/test_slo.py, not by run-to-run latency stability
    "replication_lag_p99_ms": 0.8,
    # loopback-gRPC round trips on the 1-core bench boxes are
    # scheduling-bound; the wire-bytes ratio is the stable signal and
    # gates through wire_bytes-derived fields, not rows/s
    "encoded_wire_rows_per_sec": 0.5,
    # multi-stream lane: a loopback put+get per curve point, so the
    # same scheduling noise as encoded_wire applies; the 4-vs-1 ratio
    # divides two such numbers and on the 1-core bench boxes carries
    # NO parallelism signal at all (substream threads timeshare one
    # core) — the lane's contracts gate in-run (pool-once, encoded
    # shrink) and in tests, not through this ratio
    "interchange_multistream_rows_per_sec": 0.5,
    "interchange_stream4_speedup": 1.0,
    # staging-store reads are lexsort-bound and swing with the 1-core
    # boxes' scheduling; the cutover seal is a sub-ms in-memory
    # decision where a single preemption doubles the mean — the
    # correctness half (compaction equivalence, no-flatten pin) gates
    # through the run's own `ok`, not through these bands
    "mvcc_merge_layered_rows_per_sec": 0.4,
    "mvcc_merge_compacted_rows_per_sec": 0.4,
    "mvcc_cutover_ms": 0.8,
    # spill is Arrow-IPC encode + a heap-blob put, rebuild replays the
    # whole manifest through decode + re-land — both wall-clock
    # numbers swing with the 1-core boxes' scheduling; the durability
    # contracts (byte-identical rebuild, no-flatten round trip) gate
    # through the run's own `ok` and the spill conformance tests
    "mvcc_spill_mbs": 0.5,
    "mvcc_rebuild_ms": 0.8,
}


def load_bench_metrics(path: str) -> dict[str, dict]:
    """{metric_name: metric_obj} out of a bench artifact.

    Accepts all three shapes the repo carries: a driver-captured
    BENCH_rNN.json wrapper (`{"tail": "...log lines..."}`), a raw bench
    log (stderr '#' lines + the stdout headline), or a JSON-lines file
    of metric objects.  The LAST occurrence of a metric wins (the
    headline prints early as a crash-safety copy, then final)."""
    with open(path) as fh:
        text = fh.read()
    out: dict[str, dict] = {}

    def take(obj) -> None:
        if isinstance(obj, dict) and isinstance(obj.get("metric"), str):
            out[obj["metric"]] = obj

    lines = text.splitlines()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict):
        take(doc)
        if isinstance(doc.get("tail"), str):
            lines = doc["tail"].splitlines()
        else:
            lines = []
    elif isinstance(doc, list):
        for it in doc:
            take(it)
        lines = []
    for ln in lines:
        ln = ln.strip()
        if ln.startswith("#"):
            ln = ln.lstrip("# ").strip()
        if not ln.startswith("{"):
            continue
        try:
            take(json.loads(ln))
        except ValueError:
            continue
    return out


def compare_against(prior: dict[str, dict], current: dict[str, dict],
                    tolerance: Optional[float] = None
                    ) -> tuple[list[dict], list[str]]:
    """Per-metric comparison with tolerance bands.

    Returns (regressions, report_lines).  Only metrics present in BOTH
    sets with numeric nonzero prior values are gated; the rest are
    reported as skipped so a silently-vanished metric is visible."""
    base_tol = DEFAULT_TOLERANCE if tolerance is None else tolerance
    regressions: list[dict] = []
    lines: list[str] = []
    for name in sorted(prior):
        p = prior[name].get("value")
        c = (current.get(name) or {}).get("value")
        if name not in current:
            lines.append(f"{name}: SKIP (not emitted by this run)")
            continue
        if not isinstance(p, (int, float)) or \
                not isinstance(c, (int, float)) or p <= 0:
            lines.append(f"{name}: SKIP (non-comparable values "
                         f"{p!r} -> {c!r})")
            continue
        tol = max(TOLERANCE_OVERRIDES.get(name, 0.0), base_tol)
        lower_better = name.endswith(_LOWER_IS_BETTER)
        if lower_better and c <= 0:
            # a 0 latency is a broken measurement, not an infinite win
            lines.append(f"{name}: SKIP (non-comparable values "
                         f"{p!r} -> {c!r})")
            continue
        ratio = (p / c) if lower_better else (c / p)
        verdict = "OK" if ratio >= 1.0 - tol else "REGRESSION"
        lines.append(
            f"{name}: {p} -> {c} "
            f"({'x' if not lower_better else '/'}{ratio:.3f} vs "
            f"floor {1.0 - tol:.2f}) {verdict}")
        if verdict == "REGRESSION":
            regressions.append({
                "metric": name, "prior": p, "current": c,
                "ratio": round(ratio, 4), "tolerance": tol,
                "lower_is_better": lower_better,
            })
    for name in sorted(set(current) - set(prior)):
        lines.append(f"{name}: NEW (no prior value)")
    return regressions, lines


def run_regression_gate(against_path: str,
                        current: dict[str, dict],
                        tolerance: Optional[float] = None) -> int:
    try:
        prior = load_bench_metrics(against_path)
    except (OSError, UnicodeDecodeError) as e:
        print(f"# against: unreadable artifact {against_path}: {e}",
              file=sys.stderr)
        return 2
    if not prior:
        print(f"# against: no metric lines found in {against_path}",
              file=sys.stderr)
        return 2
    regressions, lines = compare_against(prior, current, tolerance)
    for ln in lines:
        print(f"# against: {ln}", file=sys.stderr)
    verdict = {"metric": "bench_regression_gate",
               "ok": not regressions,
               "against": os.path.basename(against_path),
               "compared": sum(1 for ln in lines if "SKIP" not in ln
                               and "NEW" not in ln),
               "regressions": regressions}
    print(f"# {json.dumps(verdict)}", file=sys.stderr)
    return 1 if regressions else 0


def _against_args() -> tuple[Optional[str], Optional[str],
                             Optional[float]]:
    """(--against PATH, --candidate PATH, --tolerance F) off argv.
    With --candidate the gate compares two artifacts and never runs a
    benchmark (the verify-skill smoke); without it the gate runs after
    whatever bench stage argv selected, over the metrics it emitted."""
    against = candidate = None
    tolerance = None
    argv = sys.argv[1:]
    for i, a in enumerate(argv):
        if a == "--against" and i + 1 < len(argv):
            against = argv[i + 1]
        elif a.startswith("--against="):
            against = a.split("=", 1)[1]
        elif a == "--candidate" and i + 1 < len(argv):
            candidate = argv[i + 1]
        elif a.startswith("--candidate="):
            candidate = a.split("=", 1)[1]
        elif a == "--tolerance" and i + 1 < len(argv):
            tolerance = _parse_tolerance(argv[i + 1])
        elif a.startswith("--tolerance="):
            tolerance = _parse_tolerance(a.split("=", 1)[1])
    return against, candidate, tolerance


def _parse_tolerance(raw: str) -> float:
    """Bad input exits 2 (unusable input), NOT 1 — a CI wrapper keying
    on the gate's exit codes must never read a flag typo as a perf
    regression."""
    try:
        tol = float(raw)
    except ValueError:
        print(f"# against: invalid --tolerance {raw!r} "
              f"(want a fraction like 0.15)", file=sys.stderr)
        raise SystemExit(2)
    if tol < 0:
        print(f"# against: --tolerance must be >= 0, got {tol}",
              file=sys.stderr)
        raise SystemExit(2)
    return tol


def measure_dispatch() -> dict:
    """`--dispatch`: the compressed dispatch plane's micro-bench —
    identical dict-heavy mask+filter batches (the clickbench URL shape:
    low-cardinality string column + int filter column) dispatched
    through the fused device program with the encoding forced RAW vs
    AUTO (ops/dispatch.py).  Reports rows/s per mode plus the
    encoded-vs-raw-equivalent H2D compression ratio; the acceptance bar
    is >=5x on the dict-heavy shape."""
    from transferia_tpu.abstract import TableID
    from transferia_tpu.abstract.schema import new_table_schema
    from transferia_tpu.columnar.batch import (
        Column,
        ColumnBatch,
        DictEnc,
        DictPool,
        _offsets_from_lengths,
    )
    from transferia_tpu.ops import dispatch as dsp
    from transferia_tpu.stats.trace import TELEMETRY
    from transferia_tpu.transform import build_chain
    from transferia_tpu.transform.fused import (
        set_device_fusion,
        set_placement,
    )

    rows = knobs.env_int("BENCH_DISPATCH_ROWS", 131_072)
    n_batches = max(1, knobs.env_int("BENCH_DISPATCH_BATCHES", 4))
    uniques = 4096
    tid = TableID("bench", "dispatch")
    schema = new_table_schema([("URL", "utf8"), ("RegionID", "int32")])
    rng = np.random.default_rng(11)
    vals = [f"https://bench{i}.example/path/{i % 97}/{i}"
            for i in range(uniques)]
    bufs = [v.encode() for v in vals]
    pool_data = np.frombuffer(b"".join(bufs), dtype=np.uint8).copy()
    pool_off = _offsets_from_lengths([len(b) for b in bufs] + [0])

    # identical data for both modes: draw once, rebind per-mode pools
    batch_data = [
        (rng.integers(0, uniques, rows).astype(np.int32),
         rng.integers(0, 500, rows).astype(np.int32))
        for _ in range(n_batches)
    ]

    def batches(pool):
        out = []
        for codes, regions in batch_data:
            url = Column("URL", schema.find("URL").data_type,
                         dict_enc=DictEnc(codes, pool=pool))
            region = Column(
                "RegionID", schema.find("RegionID").data_type, regions)
            out.append(ColumnBatch(tid, schema,
                                   {"URL": url, "RegionID": region}))
        return out

    cfg = {"transformers": [
        {"mask_field": {"columns": ["URL"], "salt": "bench-salt"}},
        {"filter_rows": {"filter": "RegionID < 400"}},
    ]}

    def run_mode(mode: str) -> tuple[float, dict]:
        # fresh pool per mode so neither rides the other's memo
        pool = DictPool(pool_data, pool_off, null_code=uniques)
        data = batches(pool)
        dsp.set_dispatch_encoding(mode)
        set_device_fusion(True)
        set_placement("device")
        try:
            chain = build_chain(cfg)
            chain.apply(data[0])  # warm: compiles + pool upload
            TELEMETRY.reset()
            t0 = time.perf_counter()
            total = 0
            for b in data:
                out = chain.apply(b)
                total += out.n_rows
            dt = time.perf_counter() - t0
            assert total > 0
            return (n_batches * rows) / max(dt, 1e-9), \
                TELEMETRY.snapshot()
        finally:
            set_device_fusion(None)
            set_placement(None)
            dsp.set_dispatch_encoding(None)

    raw_rps, raw_snap = run_mode("raw")
    enc_rps, enc_snap = run_mode("auto")
    ratio = (enc_snap["h2d_raw_equiv_bytes"]
             / max(enc_snap["h2d_encoded_bytes"], 1))
    return {
        "metric": "dispatch_encoded_rows_per_sec",
        "unit": "rows/sec",
        "value": round(enc_rps),
        "raw_rows_per_sec": round(raw_rps),
        "speedup_vs_raw": round(enc_rps / max(raw_rps, 1e-9), 2),
        "compression_ratio": round(ratio, 1),
        "h2d_encoded_bytes": enc_snap["h2d_encoded_bytes"],
        "h2d_raw_equiv_bytes": enc_snap["h2d_raw_equiv_bytes"],
        "h2d_raw_mode_bytes": raw_snap["h2d_bytes"],
        "dict_pool_hits": enc_snap["dict_pool_hits"],
        "rows_per_batch": rows,
        "batches": n_batches,
    }


def measure_checksum_dict() -> dict:
    """`--checksum-dict`: the dict-native reduction plane's A/B — the
    SAME dict-heavy batches (clickbench URL shape: one low-cardinality
    string column + one int64 id) fingerprinted flat (pre-materialized
    buffers, the pre-PR wire) vs code-native (DictEnc columns, pool
    accumulators + code gather).  Digest equality is asserted; the
    acceptance bar is >=3x rows/s on this shape with ZERO flat
    materializations on the dict run."""
    from transferia_tpu.abstract import TableID
    from transferia_tpu.abstract.schema import new_table_schema
    from transferia_tpu.columnar.batch import (
        Column,
        ColumnBatch,
        DictEnc,
        DictPool,
        _offsets_from_lengths,
    )
    from transferia_tpu.ops.rowhash import TableFingerprinter
    from transferia_tpu.stats.trace import TELEMETRY

    rows = knobs.env_int("BENCH_CHECKSUM_DICT_ROWS", 262_144)
    n_batches = max(1, knobs.env_int("BENCH_CHECKSUM_DICT_BATCHES", 8))
    uniques = 4096
    tid = TableID("bench", "checksum_dict")
    # the ClickBench `hits` character: one wide id plus several
    # low-cardinality string columns riding parquet dictionaries
    dict_cols = ("URL", "Referer", "SearchPhrase")
    schema = new_table_schema(
        [("id", "int64", True)] + [(c, "utf8") for c in dict_cols])
    rng = np.random.default_rng(13)
    pools = {}
    for ci, cname in enumerate(dict_cols):
        vals = [f"https://bench{ci}-{i}.example/path/{i % 97}/{i}"
                for i in range(uniques)]
        bufs = [v.encode() for v in vals]
        pool_data = np.frombuffer(b"".join(bufs), dtype=np.uint8).copy()
        pool_off = _offsets_from_lengths([len(b) for b in bufs] + [0])
        pools[cname] = DictPool(pool_data, pool_off, null_code=uniques)

    batch_data = [
        (np.arange(i * rows, (i + 1) * rows, dtype=np.int64),
         {c: rng.integers(0, uniques, rows).astype(np.int32)
          for c in dict_cols})
        for i in range(n_batches)
    ]
    id_t = schema.find("id").data_type

    def mk_batches(flat: bool):
        out = []
        for ids, codes in batch_data:
            cols = {"id": Column("id", id_t, ids)}
            for c in dict_cols:
                enc = DictEnc(codes[c], pool=pools[c])
                ct = schema.find(c).data_type
                cols[c] = (Column(c, ct, *enc.materialize()) if flat
                           else Column(c, ct, dict_enc=enc))
            out.append(ColumnBatch(tid, schema, cols))
        return out

    dict_batches = mk_batches(flat=False)
    flat_batches = mk_batches(flat=True)

    def run(batches) -> tuple[float, str]:
        fp = TableFingerprinter(backend="host")
        fp.push(batches[0])  # warm: native lib load, acc memo
        fp = TableFingerprinter(backend="host")
        t0 = time.perf_counter()
        for b in batches:
            fp.push(b)
        agg = fp.result()
        dt = time.perf_counter() - t0
        return (n_batches * rows) / max(dt, 1e-9), agg.digest()

    flat_rps, flat_digest = run(flat_batches)
    TELEMETRY.reset()
    dict_rps, dict_digest = run(dict_batches)
    snap = TELEMETRY.snapshot()
    if dict_digest != flat_digest:
        raise AssertionError(
            f"dict-native digest {dict_digest} != flat {flat_digest}")
    return {
        "metric": "checksum_dict_fingerprint_rows_per_sec",
        "unit": "rows/sec",
        "value": round(dict_rps),
        "flat_rows_per_sec": round(flat_rps),
        "speedup_vs_flat": round(dict_rps / max(flat_rps, 1e-9), 2),
        "digest": dict_digest,
        "digest_match": True,
        "dict_flat_materializations":
            snap["dict_flat_materializations"],
        "lazy_dict_preserved": snap["lazy_dict_preserved"],
        "rows_per_batch": rows,
        "batches": n_batches,
        "pool_values": uniques,
    }


def measure_encoded_wire() -> dict:
    """`--encoded-wire`: the pool-once encoded Flight wire's A/B —
    identical dict-heavy batches (clickbench URL shape) streamed
    through a loopback Flight server with the encoded wire forced OFF
    (dict columns materialize flat per batch — the pre-PR wire) vs ON
    (one Arrow dictionary batch per stream, then codes-only record
    batches).  The run asserts the pool-once telemetry (each DictPool
    ships at most once per stream) and reports rows/s per mode plus
    the bytes-on-wire ratio; the acceptance bar is encoded wire bytes
    < 0.5x flat on this shape."""
    from transferia_tpu.abstract.schema import (
        CanonicalType,
        TableID,
        new_table_schema,
    )
    from transferia_tpu.columnar.batch import (
        Column,
        ColumnBatch,
        DictEnc,
        DictPool,
        _offsets_from_lengths,
    )
    from transferia_tpu.interchange import convert
    from transferia_tpu.interchange.flight import (
        FlightShardClient,
        ShardFlightServer,
    )
    from transferia_tpu.interchange.telemetry import TELEMETRY as ITEL

    rows = knobs.env_int("BENCH_ENCODED_WIRE_ROWS", 65_536)
    n_batches = max(1, knobs.env_int("BENCH_ENCODED_WIRE_BATCHES", 4))
    uniques = 4096
    tid = TableID("bench", "encoded_wire")
    schema = new_table_schema([("URL", "utf8"), ("RegionID", "int32")])
    vals = [f"https://bench{i}.example/path/{i % 97}/{i}"
            for i in range(uniques)]
    bufs = [v.encode() for v in vals]
    pool_data = np.frombuffer(b"".join(bufs), dtype=np.uint8).copy()
    pool_off = _offsets_from_lengths([len(b) for b in bufs] + [0])
    rng = np.random.default_rng(17)
    batch_data = [
        (rng.integers(0, uniques, rows).astype(np.int32),
         rng.integers(0, 500, rows).astype(np.int32))
        for _ in range(n_batches)
    ]

    def batches(pool):
        out = []
        for codes, regions in batch_data:
            out.append(ColumnBatch(tid, schema, {
                "URL": Column("URL", CanonicalType.UTF8,
                              dict_enc=DictEnc(codes, pool=pool)),
                "RegionID": Column("RegionID", CanonicalType.INT32,
                                   regions),
            }))
        return out

    def run_mode(encoded: bool, server, client,
                 key: str) -> tuple[float, int]:
        pool = DictPool(pool_data, pool_off, null_code=uniques)
        data = batches(pool)
        convert.set_encoded_wire(encoded)
        try:
            # warm the FULL round trip: the first dictionary-bearing
            # stream pays one-time arrow/grpc code-path setup (~0.6s)
            # that must not land in the timed window
            client.put_part(key, data)
            client.get_part(key)
            ITEL.reset()
            t0 = time.perf_counter()
            client.put_part(key, data)
            got = client.get_part(key)
            dt = time.perf_counter() - t0
            n_out = sum(b.n_rows for b in got)
            assert n_out == n_batches * rows, \
                f"row mismatch {n_out} != {n_batches * rows}"
            snap = ITEL.snapshot()
            if encoded and snap["pools_shipped"] > 1:
                raise AssertionError(
                    f"pool shipped {snap['pools_shipped']}x on one "
                    f"stream (pool-once contract broken)")
            return (n_batches * rows) / max(dt, 1e-9), snap["bytes_in"]
        finally:
            convert.set_encoded_wire(None)

    with ShardFlightServer(enable_shm=False) as server:
        with FlightShardClient(server.location,
                               allow_shm=False) as client:
            flat_rps, flat_bytes = run_mode(False, server, client,
                                            "bench.wire/flat")
            enc_rps, enc_bytes = run_mode(True, server, client,
                                          "bench.wire/enc")
    return {
        "metric": "encoded_wire_rows_per_sec",
        "unit": "rows/sec",
        "value": round(enc_rps),
        "flat_rows_per_sec": round(flat_rps),
        "speedup_vs_flat": round(enc_rps / max(flat_rps, 1e-9), 2),
        "wire_bytes_encoded": enc_bytes,
        "wire_bytes_flat": flat_bytes,
        "wire_bytes_ratio": round(enc_bytes / max(flat_bytes, 1), 3),
        "pool_once": True,
        "rows_per_batch": rows,
        "batches": n_batches,
        "pool_values": uniques,
    }


def measure_interchange() -> dict:
    """`--interchange`: the Arrow interchange plane's shard-handoff
    stage — identical sample batches moved via the row-pivot baseline
    (ChangeItems out and back), the Arrow IPC stream, the shared-memory
    segment, and loopback Flight; reports rows/s per path plus the
    zero-copy buffer ratio (interchange/bench.py).  The acceptance bar
    is the IPC-or-shm path beating the pivot baseline by >= 2x."""
    from transferia_tpu.interchange.bench import run_interchange_bench

    rows = knobs.env_int("BENCH_INTERCHANGE_ROWS", 500_000)
    return run_interchange_bench(rows=rows, batch_rows=65_536)


def _emit_multistream(report: dict) -> None:
    """The multi-stream lane's own gate lines out of an interchange
    report: rows/s at 4 substreams on the dict-heavy shape, and the
    4-vs-1 scaling ratio.  Both carry TOLERANCE_OVERRIDES bands — on a
    1-core bench box the ratio is pure scheduling noise (substream
    threads timeshare the core), so the band is wide on purpose."""
    curve = report.get("stream_curve") or {}
    four = curve.get("4") or {}
    if four.get("rows_per_sec"):
        _emit({"metric": "interchange_multistream_rows_per_sec",
               "unit": "rows/sec", "value": four["rows_per_sec"],
               "wire_mb": four.get("wire_mb"),
               "encoded_wire_ratio": four.get("encoded_wire_ratio")})
    if report.get("stream4_speedup"):
        _emit({"metric": "interchange_stream4_speedup", "unit": "x",
               "value": report["stream4_speedup"]})


def measure_fleet() -> dict:
    """`--fleet`: the fleet control plane's scheduler bench — 100+
    concurrent sample→memory transfers through admission control +
    weighted fair-share dispatch (fleet/bench.py).  Tracked metrics:
    p50/p99 scheduler dispatch latency and the Jain fairness index
    under the 10:1 tenant skew (acceptance bar >= 0.9), with the
    delivery audit (no transfer lost or double-admitted) folded into
    `ok`."""
    from transferia_tpu.fleet.bench import run_fleet_bench

    return run_fleet_bench(
        transfers=knobs.env_int("BENCH_FLEET_TRANSFERS", 120),
        workers=knobs.env_int("BENCH_FLEET_WORKERS", 8),
        rows=knobs.env_int("BENCH_FLEET_ROWS", 256),
    )


def measure_mvcc() -> dict:
    """`--mvcc`: the MVCC staging store's two read shapes — layered
    merge-on-read vs the compacted base — plus the cutover seal
    latency floor (mvcc/bench.py).  The run self-checks compaction
    row-equivalence and the zero-flat-materializations pin; both fold
    into `ok`."""
    from transferia_tpu.mvcc.bench import run_mvcc_bench

    return run_mvcc_bench(
        rows=knobs.env_int("BENCH_MVCC_ROWS", 200_000),
        layers=knobs.env_int("BENCH_MVCC_LAYERS", 12),
    )


def main() -> int:
    from transferia_tpu.stats import stagetimer

    against, candidate, tolerance = _against_args()
    if against and candidate:
        # pure compare mode: two artifacts, no benchmark run — the
        # verify-skill smoke and ad-hoc "did rNN regress vs rMM" checks
        try:
            cand = load_bench_metrics(candidate)
        except (OSError, UnicodeDecodeError) as e:
            print(f"# against: unreadable artifact {candidate}: {e}",
                  file=sys.stderr)
            return 2
        if not cand:
            # a truncated/empty candidate would turn every prior
            # metric into a SKIP and pass the gate — a run that
            # emitted nothing is unusable input, not a clean bill
            print(f"# against: no metric lines found in {candidate}",
                  file=sys.stderr)
            return 2
        return run_regression_gate(against, cand, tolerance)

    def gated(rc: int = 0) -> int:
        if against:
            grc = run_regression_gate(
                against, {m["metric"]: m for m in _METRICS_EMITTED},
                tolerance)
            return rc or grc
        return rc

    if "--fleet" in sys.argv[1:]:
        # standalone stage: scheduler latency + fairness (one JSON
        # line).  --trace[=path]/BENCH_TRACE wraps the whole fleet run
        # in a capture: with causal propagation on, one transfer's
        # admission → queue-wait → dispatch → parts → device work
        # exports as a single linked timeline
        from transferia_tpu.fleet.bench import format_report as _fmt_fleet

        trace_out = _trace_out_path()
        if trace_out:
            from transferia_tpu.stats import trace as _trace

            _trace.reset()
            _trace.enable(True)
        try:
            report = measure_fleet()
        finally:
            if trace_out:
                from transferia_tpu.stats import trace as _trace

                _trace.enable(False)
                n_events = _trace.write_chrome_trace(trace_out)
                print(f"# trace: {n_events} events -> {trace_out}",
                      file=sys.stderr)
        for line in _fmt_fleet(report).splitlines():
            print(f"# {line}", file=sys.stderr)
        _METRICS_EMITTED.append(report)
        # the merged-histogram dispatch tail rides the --against gate
        # as its own metric lines (latency direction: *_ms suffix)
        for q in ("p50", "p99", "p999"):
            _emit({"metric": f"fleet_dispatch_{q}_ms", "unit": "ms",
                   "value": report[f"dispatch_hdr_{q}_ms"]})
        # end-to-end freshness tail (SLO plane): event-time → publish
        # lag over the run window, latency direction like any *_ms
        if report.get("replication_lag_count"):
            _emit({"metric": "replication_lag_p99_ms", "unit": "ms",
                   "value": report["replication_lag_p99_ms"]})
        print(json.dumps(report))
        return gated(0 if report["ok"] else 1)

    if "--mvcc" in sys.argv[1:]:
        # standalone stage: layered vs compacted staging-store reads +
        # cutover seal latency (one JSON line); the run self-checks
        # compaction equivalence and the no-flatten pin
        from transferia_tpu.mvcc.bench import format_report as _fmt_mvcc

        report = measure_mvcc()
        for line in _fmt_mvcc(report).splitlines():
            print(f"# {line}", file=sys.stderr)
        _METRICS_EMITTED.append(report)
        _emit({"metric": "mvcc_merge_compacted_rows_per_sec",
               "unit": "rows/sec",
               "value": report["compacted_rows_per_sec"]})
        _emit({"metric": "mvcc_cutover_ms", "unit": "ms",
               "value": report["cutover_ms"]})
        _emit({"metric": "mvcc_spill_mbs", "unit": "MB/s",
               "value": report["spill_mbs"]})
        _emit({"metric": "mvcc_rebuild_ms", "unit": "ms",
               "value": report["rebuild_ms"]})
        print(json.dumps(report))
        return gated(0 if report["ok"] else 1)

    if "--interchange" in sys.argv[1:]:
        # standalone stage: one stdout JSON line, diagnostics on stderr
        from transferia_tpu.interchange.bench import format_report

        report = measure_interchange()
        for line in format_report(report).splitlines():
            print(f"# {line}", file=sys.stderr)
        _METRICS_EMITTED.append(report)
        _emit_multistream(report)
        print(json.dumps(report))
        return gated()

    if "--checksum-dict" in sys.argv[1:]:
        # standalone stage: flat vs code-native fingerprint (one JSON
        # line, printed next to checksum_fingerprint_rows_per_sec's
        # shape so the two headline checksum rates read together)
        report = measure_checksum_dict()
        print(f"# checksum-dict: code-native {report['value']} rows/s "
              f"vs flat {report['flat_rows_per_sec']} rows/s "
              f"({report['speedup_vs_flat']}x), "
              f"flat_materializations="
              f"{report['dict_flat_materializations']}", file=sys.stderr)
        _METRICS_EMITTED.append(report)
        print(json.dumps(report))
        return gated()

    if "--encoded-wire" in sys.argv[1:]:
        # standalone stage: pool-once Flight wire vs flat (one JSON
        # line); the run itself asserts the pool-once telemetry
        report = measure_encoded_wire()
        print(f"# encoded-wire: {report['value']} rows/s vs flat "
              f"{report['flat_rows_per_sec']} rows/s "
              f"({report['speedup_vs_flat']}x), wire bytes "
              f"{report['wire_bytes_encoded']} vs "
              f"{report['wire_bytes_flat']} "
              f"({report['wire_bytes_ratio']}x)", file=sys.stderr)
        _METRICS_EMITTED.append(report)
        print(json.dumps(report))
        return gated()

    if "--dispatch" in sys.argv[1:]:
        # standalone stage: encoded vs raw H2D dispatch (one JSON line)
        report = measure_dispatch()
        print(f"# dispatch: encoded {report['value']} rows/s vs raw "
              f"{report['raw_rows_per_sec']} rows/s "
              f"({report['speedup_vs_raw']}x), compression "
              f"{report['compression_ratio']}x", file=sys.stderr)
        _METRICS_EMITTED.append(report)
        print(json.dumps(report))
        return gated()

    fallback = None
    if not _device_available():
        fallback = "cpu-backend"
        if not _force_cpu_backend():
            # a live (wedged) backend can't be flipped: report honestly
            # rather than hanging without ever printing the JSON
            print(json.dumps({
                "metric": "clickbench_snapshot_rows_per_sec",
                "value": 0,
                "unit": "rows/sec",
                "vs_baseline": 0.0,
                "fallback": "none-backend-wedged",
            }))
            print("# jax backend already initialized and TPU wedged; "
                  "cannot fall back in-process", file=sys.stderr)
            # 2, not 1: the regression gate reserves 1 for a real perf
            # regression; a dead runtime is unusable environment
            return 2
        print("# TPU runtime unavailable after retries; measuring on the "
              "host pipeline (CPU) as a labeled diagnostic fallback",
              file=sys.stderr)
    t_gen = time.perf_counter()
    generate_dataset()
    generate_wide_dataset()
    gen_s = time.perf_counter() - t_gen

    # warmup: compile the hash/filter programs on the first batches
    # (also the once-per-process runtime warm — cold device init happens
    # here, outside the timed window)
    warm_rows, warm_s = run_pipeline(limit_rows=BATCH_ROWS * 2,
                                     parquet=WIDE_PARQUET)

    # headline: the ClickBench-shaped wide dataset (~70 cols) — the shape
    # the 10M rows/s target is defined on (reference docs/benchmarks.md)
    from transferia_tpu.stats.profiler import profile as cpu_profile

    from transferia_tpu.providers import parquet_native, readahead

    parquet_native.reset_fallback_stats()
    readahead.reset_stats()
    trace_out = _trace_out_path()
    if trace_out:
        from transferia_tpu.stats import trace as _trace

        _trace.reset()
        _trace.enable(True)
    stagetimer.enable(True)
    stagetimer.reset()
    with cpu_profile() as prof:
        rows, dt = run_pipeline(parquet=WIDE_PARQUET, total_rows=WIDE_ROWS)
    stage_note = stagetimer.format_breakdown(dt)
    ra = readahead.snapshot_stats()
    if ra["prefetched_groups"]:
        # queue-depth evidence that decode overlapped downstream work —
        # rides the stages string so BENCH_*.json captures it
        stage_note += (
            f" readahead_groups={ra['prefetched_groups']}"
            f" readahead_depth_avg={ra['avg_depth']}"
            f" readahead_depth_max={ra['max_depth']}"
            f" readahead_inflight_mb_max="
            f"{ra['max_inflight_bytes'] / 1e6:.0f}")
    if trace_out:
        from transferia_tpu.stats import trace as _trace

        _trace.enable(False)
        n_events = _trace.write_chrome_trace(trace_out)
        print(f"# trace: {n_events} events -> {trace_out}",
              file=sys.stderr)
        for line in _trace.format_summary(dt).splitlines():
            print(f"# trace: {line}", file=sys.stderr)
    native_fallbacks = parquet_native.fallback_stats()
    rps = rows / dt
    # continuity line: the r01-r03 10-col dataset (own warmup so its
    # differently-shaped programs never compile inside the timed window)
    stagetimer.enable(False)
    run_pipeline(limit_rows=BATCH_ROWS * 2)
    rows10, dt10 = run_pipeline()
    latencies = measure_transform_latency()
    import resource

    peak_rss_mb = round(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024)
    result = {
        "metric": "clickbench_snapshot_rows_per_sec",
        "value": round(rps),
        "unit": "rows/sec",
        "vs_baseline": round(rps / 10_000_000, 4),
        "cpu_count": _effective_cpus(),
        "dataset": {"rows": rows, "cols": _dataset_cols(WIDE_PARQUET)},
        "native_fallback_cols": len(native_fallbacks),
        "peak_rss_mb": peak_rss_mb,
        "stages": stage_note or None,
    }
    if WIDE_ROWS >= 100_000_000:
        # scale-proof marker (BENCH_WIDE_ROWS=100000000): dict pools and
        # the 2GiB offset guards under ~100M rows — the evidence lives in
        # the existing dataset/peak_rss_mb/native_fallback_cols fields
        result["scale_proof"] = True
    if native_fallbacks:
        result["native_fallbacks"] = native_fallbacks
    if fallback:
        result["fallback"] = fallback
    # crash-safety copy: the official line prints LAST (the driver tails
    # the output), but an OOM in an aux bench must not erase the headline
    print(f"# headline(early): {json.dumps(result)}", file=sys.stderr)
    lat_note = ""
    if latencies:
        import math

        lat = sorted(latencies)
        n = len(lat)
        p50 = lat[max(0, math.ceil(0.50 * n) - 1)] * 1000
        p99 = lat[max(0, math.ceil(0.99 * n) - 1)] * 1000  # nearest rank
        lat_note = (f" transform_latency_ms=p50:{p50:.2f}/p99:{p99:.2f}"
                    f" ({n} single-stream batches, steady state)")
    print(
        f"# rows={rows} time={dt:.2f}s warmup={warm_s:.1f}s "
        f"gen={gen_s:.1f}s batch={BATCH_ROWS} "
        f"process_count={_auto_process_count()} "
        f"backend={'cpu-fallback' if fallback else 'device'}"
        f"{lat_note} dataset={WIDE_PARQUET}",
        file=sys.stderr,
    )
    _emit({'metric': 'clickbench10_snapshot_rows_per_sec',
           'value': round(rows10 / dt10), 'unit': 'rows/sec',
           'rows': rows10,
           'note': 'r01-r03 continuity dataset (10 cols)'})
    if stage_note:
        print(f"# stages: {stage_note}", file=sys.stderr)
    if prof.report is not None and prof.report.samples:
        for line in prof.report.format(10).splitlines():
            print(f"# profile: {line}", file=sys.stderr)
    try:
        from transferia_tpu.ops.linkprobe import probe_link

        link_note = probe_link().describe()
    except Exception as e:
        link_note = f"probe failed: {type(e).__name__}"
    from transferia_tpu.stats.trace import TELEMETRY as _tel

    _snap = _tel.snapshot()
    if _snap["h2d_encoded_bytes"]:
        link_note += (
            f" dispatch_ratio={_snap['dispatch_compression_ratio']}"
            f" dict_pool_hits={_snap['dict_pool_hits']}")
    print(f"# link: {link_note}"
          + (f" {_placement_note}" if _placement_note else ""),
          file=sys.stderr)
    if not fallback:
        try:
            kern = measure_device_kernel()
            if kern:
                _emit(kern)
        except Exception as e:
            print(f"# device kernel bench failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
        try:
            dk = measure_device_decode()
            if dk:
                _emit(dk)
        except Exception as e:
            print(f"# device decode bench failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
        try:
            # subprocess-isolated with a hard timeout: a wedged tunneled
            # runtime can HANG a compile, and no aux metric is allowed
            # to stall the bench tail
            dfp = _run_isolated("measure_device_fingerprint",
                                timeout=300)
            if dfp:
                _emit(dfp)
        except Exception as e:
            print(f"# device fingerprint bench failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
        try:
            mesh1 = measure_mesh_1dev()
            if mesh1:
                _emit(mesh1)
        except Exception as e:
            print(f"# mesh 1-dev bench failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
    try:
        fprint = measure_fingerprint()
        if fprint:
            if fallback:
                fprint["fallback"] = fallback
            _emit(fprint)
    except Exception as e:
        print(f"# fingerprint bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    if knobs.env_str("BENCH_SKIP_CHECKSUM_DICT", "") != "1":
        try:
            cdict = measure_checksum_dict()
            if fallback:
                cdict["fallback"] = fallback
            _emit(cdict)
        except Exception as e:
            print(f"# checksum-dict bench failed: {type(e).__name__}: "
                  f"{e}", file=sys.stderr)
    if knobs.env_str("BENCH_SKIP_INTERCHANGE", "") != "1":
        try:
            ichg = measure_interchange()
            _emit(ichg)
            _emit_multistream(ichg)
        except Exception as e:
            print(f"# interchange bench failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
    if knobs.env_str("BENCH_SKIP_ENCODED_WIRE", "") != "1":
        try:
            ew = measure_encoded_wire()
            _emit(ew)
        except Exception as e:
            print(f"# encoded-wire bench failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
    if knobs.env_str("BENCH_SKIP_DISPATCH", "") != "1":
        try:
            disp = measure_dispatch()
            if fallback:
                disp["fallback"] = fallback
            _emit(disp)
        except Exception as e:
            print(f"# dispatch bench failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
    # remaining BASELINE configs (each prints one tail line; failures
    # never mask the headline, which already printed)
    if knobs.env_str("BENCH_SKIP_KAFKA2CH", "") != "1":
        try:
            k2ch = measure_kafka2ch()
            if fallback:
                k2ch["fallback"] = fallback
            _emit(k2ch)
        except Exception as e:
            print(f"# kafka2ch bench failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
    if knobs.env_str("BENCH_SKIP_CONFIGS", "") != "1":
        for name, fn in (("pg2ch", measure_pg2ch),
                         ("mysql2kafka", measure_mysql2kafka),
                         ("kafka_sr64", measure_kafka_sr2ch)):
            try:
                out = fn()
                if fallback:
                    out["fallback"] = fallback
                _emit(out)
            except Exception as e:
                print(f"# {name} bench failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
    # the ONE stdout JSON line, last so tail-capture always records it
    _METRICS_EMITTED.append(result)
    print(json.dumps(result))
    return gated()


def _effective_cpus() -> float:
    """Cores this process can actually use (affinity ∩ cgroup quota) —
    shared with the fs provider's decode auto-knobs."""
    from transferia_tpu.runtime.limits import effective_cpus

    return effective_cpus()


def _dataset_cols(path: str) -> Optional[int]:
    try:
        import pyarrow.parquet as pq

        return pq.ParquetFile(path).metadata.num_columns
    except Exception:
        return None


if __name__ == "__main__":
    sys.exit(main() or 0)
