"""Table work units: descriptions, parts, filters.

Reference parity: pkg/abstract (TableDescription), operation_table_part.go:8-21
(OperationTablePart — the unit of sharded-snapshot work assignment).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Optional

from transferia_tpu.abstract.schema import TableID


@dataclass
class TableDescription:
    """A table (or a slice of one) to snapshot."""

    id: TableID
    filter: str = ""       # WHERE-like predicate (pkg/predicate syntax)
    offset: int = 0
    eta_rows: int = 0      # estimated rows (for big-first scheduling)

    def fqtn(self) -> str:
        return self.id.fqtn()

    def part_key(self) -> str:
        return f"{self.id}|{self.filter}|{self.offset}"


@dataclass
class OperationTablePart:
    """Sharded-snapshot work unit (operation_table_part.go:8-21).

    Created by the main worker's table splitter, published through the
    coordinator, pulled by secondary workers via AssignOperationTablePart.
    """

    operation_id: str = ""
    table_id: TableID = field(default_factory=lambda: TableID("", ""))
    filter: str = ""
    offset: int = 0
    part_index: int = 0
    parts_count: int = 1
    eta_rows: int = 0
    completed_rows: int = 0
    read_bytes: int = 0
    completed: bool = False
    worker_index: Optional[int] = None  # assignee
    # Lease plane (coordinator-owned): a part claim is a lease, not a
    # permanent grant.  `assignment_epoch` bumps on every (re)assignment
    # and fences stale completions (a zombie worker whose lease expired
    # carries the old epoch and is rejected by update_operation_parts);
    # `lease_expires_at` is a wall-clock deadline renewed by the worker
    # heartbeat (0 = no lease: legacy claims never expire); `stolen_from`
    # records the previous holder when an expired lease is reclaimed.
    assignment_epoch: int = 0
    lease_expires_at: float = 0.0
    stolen_from: Optional[int] = None
    # staged two-phase commit (abstract/commit.py): the assignment
    # epoch under which the coordinator granted this part's publish
    # (Coordinator.commit_part); None = never granted.  Audit trail —
    # the grant itself is fenced against assignment_epoch at grant time.
    commit_epoch: Optional[int] = None
    # inline-validation digest of this part's post-transform rows
    # (FingerprintAggregate.digest(); merged per table at read time —
    # per-part writes keep the coordinator update race-free)
    fingerprint: str = ""

    def key(self) -> str:
        return f"{self.operation_id}/{self.table_id}/{self.part_index}"

    def part_id(self) -> str:
        """PartID stamped on control events and rows of this part."""
        return f"{self.table_id}_{self.part_index}_{self.parts_count}"

    def to_description(self) -> TableDescription:
        return TableDescription(
            id=self.table_id,
            filter=self.filter,
            offset=self.offset,
            eta_rows=self.eta_rows,
        )

    def to_json(self) -> dict[str, Any]:
        return {
            "operation_id": self.operation_id,
            "schema": self.table_id.namespace,
            "table": self.table_id.name,
            "filter": self.filter,
            "offset": self.offset,
            "part_index": self.part_index,
            "parts_count": self.parts_count,
            "eta_rows": self.eta_rows,
            "completed_rows": self.completed_rows,
            "read_bytes": self.read_bytes,
            "completed": self.completed,
            "worker_index": self.worker_index,
            "assignment_epoch": self.assignment_epoch,
            "lease_expires_at": self.lease_expires_at,
            "stolen_from": self.stolen_from,
            "commit_epoch": self.commit_epoch,
            "fingerprint": self.fingerprint,
        }

    @staticmethod
    def from_json(d: dict[str, Any]) -> "OperationTablePart":
        return OperationTablePart(
            operation_id=d.get("operation_id", ""),
            table_id=TableID(d.get("schema", ""), d.get("table", "")),
            filter=d.get("filter", ""),
            offset=d.get("offset", 0),
            part_index=d.get("part_index", 0),
            parts_count=d.get("parts_count", 1),
            eta_rows=d.get("eta_rows", 0),
            completed_rows=d.get("completed_rows", 0),
            read_bytes=d.get("read_bytes", 0),
            completed=d.get("completed", False),
            worker_index=d.get("worker_index"),
            assignment_epoch=d.get("assignment_epoch", 0),
            lease_expires_at=d.get("lease_expires_at", 0.0),
            stolen_from=d.get("stolen_from"),
            commit_epoch=d.get("commit_epoch"),
            fingerprint=d.get("fingerprint", ""),
        )

    @staticmethod
    def from_description(op_id: str, td: TableDescription,
                         part_index: int = 0, parts_count: int = 1) -> "OperationTablePart":
        return OperationTablePart(
            operation_id=op_id,
            table_id=td.id,
            filter=td.filter,
            offset=td.offset,
            part_index=part_index,
            parts_count=parts_count,
            eta_rows=td.eta_rows,
        )
