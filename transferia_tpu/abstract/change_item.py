"""Row-level change event.

Reference parity: pkg/abstract/changeitem/change_item.go:27-80 (ChangeItem),
change_item_collapse.go (Collapse), utils.go (SplitByID/SplitByTableID).

In this framework `ChangeItem` is the *row view* used by CDC sources, control
events, and API compatibility; bulk data (snapshots, parsed queue batches)
lives in `transferia_tpu.columnar.ColumnBatch` from birth and is only
materialized into ChangeItems at the row-oriented edges (e.g. Debezium
emission, row-based sinks).  Both views share TableSchema.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace
from typing import Any, Optional, Sequence

from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.abstract.schema import TableID, TableSchema


@dataclass(frozen=True)
class OldKeys:
    """Pre-update/delete key values (changeitem change_item.go OldKeys)."""

    key_names: tuple[str, ...] = ()
    key_values: tuple[Any, ...] = ()

    def as_dict(self) -> dict[str, Any]:
        return dict(zip(self.key_names, self.key_values))


@dataclass(frozen=True)
class ChangeItem:
    """Universal row event.

    Parallel arrays ``column_names``/``column_values`` mirror the reference
    layout; ``table_schema`` is shared across items of a batch (never copied
    per row).  ``lsn`` is the provider-specific monotonic position;
    ``commit_time_ns`` is the transaction commit time in epoch nanoseconds.
    """

    kind: Kind
    schema: str = ""          # namespace (db schema)
    table: str = ""
    column_names: tuple[str, ...] = ()
    column_values: tuple[Any, ...] = ()
    table_schema: Optional[TableSchema] = None
    old_keys: OldKeys = field(default_factory=OldKeys)
    lsn: int = 0
    commit_time_ns: int = 0
    txn_id: str = ""
    counter: int = 0
    part_id: str = ""         # sharded-load part id (changeitem PartID)
    size_bytes: int = 0       # EventSize: read bytes attributed to this item
    queue_meta: Optional[dict] = None  # topic/partition/offset for mirror mode

    # -- identity -----------------------------------------------------------
    @property
    def table_id(self) -> TableID:
        return TableID(self.schema, self.table)

    def is_row_event(self) -> bool:
        return self.kind.is_row

    def is_system(self) -> bool:
        return self.kind.is_system

    # -- values -------------------------------------------------------------
    def as_dict(self) -> dict[str, Any]:
        return dict(zip(self.column_names, self.column_values))

    def value(self, column: str) -> Any:
        try:
            return self.column_values[self.column_names.index(column)]
        except ValueError:
            return None

    def key_values(self) -> tuple[Any, ...]:
        """Current primary-key values according to table_schema."""
        if self.table_schema is None:
            return ()
        keys = []
        vals = self.as_dict()
        for c in self.table_schema.key_columns():
            keys.append(vals.get(c.name))
        return tuple(keys)

    def effective_key(self) -> tuple[Any, ...]:
        """Key identifying the row *before* this event (for collapse order).

        For updates/deletes with old_keys present, the old key wins —
        matches the reference's collapse semantics
        (change_item_collapse.go).
        """
        if self.kind in (Kind.UPDATE, Kind.DELETE) and self.old_keys.key_names:
            if self.table_schema is not None:
                ok = self.old_keys.as_dict()
                return tuple(
                    ok.get(c.name) for c in self.table_schema.key_columns()
                )
            return tuple(self.old_keys.key_values)
        return self.key_values()

    def keys_changed(self) -> bool:
        if self.kind != Kind.UPDATE or not self.old_keys.key_names:
            return False
        return self.effective_key() != self.key_values()

    # -- functional updates -------------------------------------------------
    def with_values(self, names: Sequence[str], values: Sequence[Any]) -> "ChangeItem":
        return replace(
            self, column_names=tuple(names), column_values=tuple(values)
        )

    def remove_columns(self, names: Sequence[str]) -> "ChangeItem":
        """changeitem change_item.go:693 RemoveColumns."""
        drop = set(names)
        keep = [
            (n, v)
            for n, v in zip(self.column_names, self.column_values)
            if n not in drop
        ]
        schema = (
            self.table_schema.drop(drop) if self.table_schema is not None else None
        )
        return replace(
            self,
            column_names=tuple(n for n, _ in keep),
            column_values=tuple(v for _, v in keep),
            table_schema=schema,
        )

    def to_json(self) -> dict[str, Any]:
        out = {
            "kind": self.kind.value,
            "schema": self.schema,
            "table": self.table,
            "columnnames": list(self.column_names),
            "columnvalues": list(self.column_values),
            "lsn": self.lsn,
            "commit_time": self.commit_time_ns,
            "id": self.counter,
            "txn_id": self.txn_id,
        }
        if self.old_keys.key_names:
            out["oldkeys"] = {
                "keynames": list(self.old_keys.key_names),
                "keyvalues": list(self.old_keys.key_values),
            }
        if self.table_schema is not None:
            out["table_schema"] = self.table_schema.to_json()
        return out

    @staticmethod
    def from_json(d: dict[str, Any]) -> "ChangeItem":
        ok = d.get("oldkeys") or {}
        ts = d.get("table_schema")
        return ChangeItem(
            kind=Kind(d["kind"]),
            schema=d.get("schema", ""),
            table=d.get("table", ""),
            column_names=tuple(d.get("columnnames", ())),
            column_values=tuple(d.get("columnvalues", ())),
            table_schema=TableSchema.from_json(ts) if ts else None,
            old_keys=OldKeys(
                tuple(ok.get("keynames", ())), tuple(ok.get("keyvalues", ()))
            ),
            lsn=d.get("lsn", 0),
            commit_time_ns=d.get("commit_time", 0),
            txn_id=d.get("txn_id", ""),
            counter=d.get("id", 0),
        )


# ---------------------------------------------------------------------------
# Control-event constructors (kind.go system kinds)
# ---------------------------------------------------------------------------

def _control(kind: Kind, table_id: TableID, schema: Optional[TableSchema],
             part_id: str = "") -> ChangeItem:
    return ChangeItem(
        kind=kind,
        schema=table_id.namespace,
        table=table_id.name,
        table_schema=schema,
        part_id=part_id,
        commit_time_ns=time.time_ns(),
    )


def init_table_load(table_id: TableID, schema: Optional[TableSchema] = None,
                    part_id: str = "") -> ChangeItem:
    return _control(Kind.INIT_TABLE_LOAD, table_id, schema, part_id)


def done_table_load(table_id: TableID, schema: Optional[TableSchema] = None,
                    part_id: str = "") -> ChangeItem:
    return _control(Kind.DONE_TABLE_LOAD, table_id, schema, part_id)


def init_sharded_table_load(table_id: TableID,
                            schema: Optional[TableSchema] = None) -> ChangeItem:
    return _control(Kind.INIT_SHARDED_TABLE_LOAD, table_id, schema)


def done_sharded_table_load(table_id: TableID,
                            schema: Optional[TableSchema] = None) -> ChangeItem:
    return _control(Kind.DONE_SHARDED_TABLE_LOAD, table_id, schema)


def synchronize_event(table_id: TableID = TableID("", "")) -> ChangeItem:
    return _control(Kind.SYNCHRONIZE, table_id, None)


# ---------------------------------------------------------------------------
# Batch utilities (changeitem/utils.go, change_item_collapse.go)
# ---------------------------------------------------------------------------

def split_by_table_id(items: Sequence[ChangeItem]) -> dict[TableID, list[ChangeItem]]:
    out: dict[TableID, list[ChangeItem]] = {}
    for it in items:
        out.setdefault(it.table_id, []).append(it)
    return out


def split_by_id(items: Sequence[ChangeItem]) -> list[list[ChangeItem]]:
    """Group consecutive items by transaction id (utils.go SplitByID)."""
    out: list[list[ChangeItem]] = []
    cur_id: Optional[tuple] = None
    for it in items:
        key = (it.txn_id, it.lsn)
        if cur_id is None or key != cur_id:
            out.append([])
            cur_id = key
        out[-1].append(it)
    return out


def collapse(items: Sequence[ChangeItem]) -> list[ChangeItem]:
    """Collapse multiple events per primary key into at most one.

    Reference: changeitem/change_item_collapse.go — within one push batch,
    insert+update chains fold into a single insert/update carrying the final
    values; a trailing delete folds to a single delete (or nothing if the row
    was inserted inside the batch).  Items without schema/keys pass through
    untouched in order.  Updates that change the primary key are *not*
    collapsed across the key change.
    """
    # Pass-through when any item lacks key info — safety first.
    for it in items:
        if not it.is_row_event():
            return list(items)
        if it.table_schema is None or not it.table_schema.has_primary_key():
            return list(items)
        if it.keys_changed():
            return list(items)

    order: list[tuple] = []
    state: dict[tuple, Optional[ChangeItem]] = {}
    # True only while the key's entire in-batch history is a fresh insert
    # chain (insert [+updates]); then insert+delete folds to nothing.  A key
    # first seen via update/delete may pre-exist in the target, so a trailing
    # delete must survive (delete->insert->delete collapses to delete).
    fresh_insert: dict[tuple, bool] = {}

    for it in items:
        key = (it.table_id, it.effective_key())
        if key not in state:
            order.append(key)
            state[key] = None
            fresh_insert[key] = it.kind == Kind.INSERT
        prev = state[key]
        if it.kind == Kind.INSERT:
            state[key] = it
        elif it.kind == Kind.UPDATE:
            if prev is not None and prev.kind in (Kind.INSERT, Kind.UPDATE):
                # merge columns: later values win
                merged = dict(zip(prev.column_names, prev.column_values))
                merged.update(zip(it.column_names, it.column_values))
                names = tuple(merged.keys())
                state[key] = replace(
                    prev if prev.kind == Kind.INSERT else it,
                    column_names=names,
                    column_values=tuple(merged[n] for n in names),
                    lsn=it.lsn,
                    commit_time_ns=it.commit_time_ns,
                )
            else:
                state[key] = it
        elif it.kind == Kind.DELETE:
            state[key] = None if fresh_insert[key] else it

    return [state[k] for k in order if state[k] is not None]
