"""Shared MVCC staging-store fence semantics (dict form).

The MVCC staging store (transferia_tpu/mvcc/) keeps its columnar layer
DATA in process memory; what must survive crashes and arbitrate races
is the CONTROL state — which delta layers were admitted and whether the
snapshot→replication cutover has been sealed.  That state is one JSON
document per scope stored through the coordinator, exactly like fleet
tickets and obs segments, so all three backends (memory dict / flock'd
file / S3 conditional writes) implement byte-identical semantics around
their own atomicity primitive.

Document shape::

    {"layers": [ {worker, seq, table, lsn_min, lsn_max, rows,
                  content_key, admitted_at,
                  locator, offsets}, ... ],              # admission order
     "bases": {"<table>/<part>": {table, part, epoch, rows,
                                  content_key, locator,
                                  recorded_at}, ...},    # spill manifest
     "cutover": null | {"watermark": W, "epoch": E, "sealed_at": ts,
                        "offsets": {"topic:partition": O, ...}}}

Rules (mirroring abstract/ticket.py's in-place helpers):

* Layer admission is idempotent under the obs-segment ``(worker, seq)``
  replace convention: re-admitting the same key REPLACES the stored
  metadata in place (same admission position — merge order is stable
  across a worker's retry of a faulted admission RPC).
* The cutover is a single first-wins fence: the first seal wins
  atomically; an identical retry (same watermark AND epoch) is granted
  idempotently; anything else is fenced and handed the sealed decision.
* After the seal, NEW layer admissions are fenced — a zombie snapshot
  worker that wakes up and publishes after the cutover cannot slip rows
  into a decision that already happened.  Re-admitting an
  already-admitted key stays an idempotent ack (the data it refers to
  was part of the decision).
* The SPILL MANIFEST rides the same doc: a layer record's ``locator``
  names the coordinator-addressable blob its encoded batches spilled
  to (mvcc/spill.py), ``offsets`` the per-source-partition high
  offsets its rows covered, and ``bases`` maps each landed base
  version to its blob under the put_base epoch fence (an older-epoch
  re-record is a zombie and is fenced).  A restarted worker rebuilds
  the whole scope byte-identically from nothing but this doc plus the
  blobs it names.
* The replication SOURCE OFFSET commits inside the cutover decision:
  the seal stores the per-partition offsets the delta layers covered,
  and every response (grant, idempotent retry, fence) hands them back
  — a zombie pump adopts the sealed offsets instead of re-deciding,
  so it can neither double-deliver nor skip a window.
"""

from __future__ import annotations

import time
from typing import Any, Optional

# admission statuses (mvcc_admit_layer result["status"])
ADMITTED = "admitted"      # new (worker, seq) appended pre-cutover
REPLACED = "replaced"      # same (worker, seq) re-put pre-cutover
DUPLICATE = "duplicate"    # same (worker, seq) re-put post-cutover: ack,
#                            no mutation — the layer was in the decision
FENCED = "fenced"          # new (worker, seq) post-cutover: rejected

# base-record statuses (mvcc_record_base result["status"])
RECORDED = "recorded"      # new (table, part) manifest entry
#                            (REPLACED = equal/newer epoch re-record,
#                             FENCED = older-epoch zombie re-record)


def new_mvcc_doc() -> dict:
    return {"layers": [], "bases": {}, "cutover": None}


def layer_key(layer: dict) -> tuple[str, int]:
    """Identity of a delta layer: the obs-segment (worker, seq) pair."""
    return (str(layer.get("worker", "")), int(layer.get("seq", -1)))


def normalize_layer(layer: dict,
                    now: Optional[float] = None) -> dict:
    """JSON-plain metadata record for one admitted layer.  Only control
    fields cross the coordinator — columnar data stays in process (or
    in the spilled blob the ``locator`` names)."""
    rec = {
        "worker": str(layer.get("worker", "")),
        "seq": int(layer.get("seq", -1)),
        "table": str(layer.get("table", "")),
        "lsn_min": int(layer.get("lsn_min", 0)),
        "lsn_max": int(layer.get("lsn_max", 0)),
        "rows": int(layer.get("rows", 0)),
        "content_key": str(layer.get("content_key", "")),
        "admitted_at": (time.time() if now is None else now),
    }
    # spill manifest fields (absent pre-spill / in unspilled mode)
    if layer.get("locator"):
        rec["locator"] = str(layer["locator"])
    if layer.get("offsets"):
        rec["offsets"] = {str(k): int(v)
                          for k, v in dict(layer["offsets"]).items()}
    return rec


def admit_layer_in_place(doc: dict, layer: dict,
                         now: Optional[float] = None) -> dict:
    """Mutate the scope doc with one layer admission; returns the
    decision dict the backends hand back verbatim."""
    key = layer_key(layer)
    layers = doc.setdefault("layers", [])
    idx = next((i for i, d in enumerate(layers)
                if layer_key(d) == key), None)
    sealed = doc.get("cutover")
    if sealed is not None:
        if idx is not None:
            return {"status": DUPLICATE, "cutover": dict(sealed)}
        return {"status": FENCED, "cutover": dict(sealed)}
    rec = normalize_layer(layer, now)
    if idx is not None:
        layers[idx] = rec
        return {"status": REPLACED, "layers": len(layers)}
    layers.append(rec)
    return {"status": ADMITTED, "layers": len(layers)}


def base_key(base: dict) -> str:
    """Identity of a base version in the spill manifest."""
    return f"{base.get('table', '')}/{base.get('part', '')}"


def record_base_in_place(doc: dict, base: dict,
                         now: Optional[float] = None) -> dict:
    """Record one spilled base version in the scope's manifest, under
    the same epoch rule as the store's in-process fence: an older
    epoch than the recorded one is a zombie re-put and is fenced; an
    equal/newer epoch replaces (idempotent part retry).

    A base with ``exclusive: true`` (the compaction fold — one
    compacted base that supersedes EVERY part of its table) also
    EVICTS the table's other manifest records; the decision returns
    their blob locators under ``evicted`` so the caller can GC the
    blobs.  Without the eviction a rebuild would re-land the
    pre-compaction parts next to the compacted image and resurrect
    rows the folded delete layers removed."""
    bases = doc.setdefault("bases", {})
    rec = dict(base)
    exclusive = bool(rec.pop("exclusive", False))
    key = base_key(rec)
    prev = bases.get(key)
    epoch = int(rec.get("epoch", 1))
    if prev is not None and epoch < int(prev.get("epoch", 1)):
        return {"status": FENCED, "epoch": int(prev.get("epoch", 1))}
    bases[key] = {
        "table": str(rec.get("table", "")),
        "part": str(rec.get("part", "")),
        "epoch": epoch,
        "rows": int(rec.get("rows", 0)),
        "content_key": str(rec.get("content_key", "")),
        "locator": str(rec.get("locator", "")),
        "recorded_at": (time.time() if now is None else now),
    }
    res = {"status": REPLACED if prev is not None else RECORDED,
           "epoch": epoch}
    if exclusive:
        evicted = []
        for k in [k for k in bases if k != key
                  and bases[k].get("table") == rec.get("table")]:
            loc = bases[k].get("locator")
            if loc:
                evicted.append(str(loc))
            del bases[k]
        res["evicted"] = evicted
    return res


def cutover_in_place(doc: dict, watermark: int, epoch: int,
                     now: Optional[float] = None,
                     offsets: Optional[dict] = None) -> dict:
    """Seal (or re-acknowledge, or fence) the cutover decision.  The
    seal stores `offsets` — the per-source-partition high offsets the
    admitted layers covered — and every response carries the SEALED
    offsets back: the replication pump commits exactly those to its
    source, inside this fence's decision, never its own local view."""
    sealed = doc.get("cutover")
    if sealed is None:
        doc["cutover"] = {"watermark": int(watermark),
                          "epoch": int(epoch),
                          "sealed_at": (time.time() if now is None
                                        else now),
                          "offsets": {str(k): int(v) for k, v
                                      in (offsets or {}).items()}}
        return {"granted": True, "first": True,
                "watermark": int(watermark), "epoch": int(epoch),
                "offsets": dict(doc["cutover"]["offsets"])}
    same = (int(sealed.get("watermark", -1)) == int(watermark)
            and int(sealed.get("epoch", -1)) == int(epoch))
    return {"granted": same, "first": False,
            "watermark": int(sealed.get("watermark", -1)),
            "epoch": int(sealed.get("epoch", -1)),
            "offsets": dict(sealed.get("offsets") or {})}


def prune_layers_in_place(doc: dict, keys: list) -> int:
    """Drop layer records by (worker, seq) key — compaction folded them
    into a new base version.  Idempotent: missing keys prune nothing."""
    want = {(str(k[0]), int(k[1])) for k in keys}
    layers = doc.setdefault("layers", [])
    kept = [d for d in layers if layer_key(d) not in want]
    pruned = len(layers) - len(kept)
    doc["layers"] = kept
    return pruned


def doc_watermark(doc: dict) -> int:
    """Delta LSN high-watermark over every admitted layer (-1 = none).
    The cutover driver seals THIS value: the highest LSN any admitted
    layer carries is exactly where the replication lane must resume."""
    layers = doc.get("layers") or []
    if not layers:
        return -1
    return max(int(d.get("lsn_max", 0)) for d in layers)


def state_view(doc: Optional[dict]) -> dict:
    """Read-only JSON-plain snapshot of a scope doc (missing = empty)."""
    if not doc:
        doc = new_mvcc_doc()
    return {
        "layers": [dict(d) for d in (doc.get("layers") or [])],
        "bases": {k: dict(v)
                  for k, v in (doc.get("bases") or {}).items()},
        "cutover": (dict(doc["cutover"])
                    if doc.get("cutover") else None),
        "watermark": doc_watermark(doc),
    }


def doc_offsets(doc: Optional[dict]) -> dict:
    """Per-source-partition high offsets over every admitted layer —
    what the cutover seals, and where a resuming pump's positions
    start.  Max-merged across layers: workers chunk one partition's
    feed into many layers."""
    out: dict[str, int] = {}
    for d in ((doc or {}).get("layers") or []):
        for part, off in (d.get("offsets") or {}).items():
            cur = out.get(str(part))
            if cur is None or int(off) > cur:
                out[str(part)] = int(off)
    return out
