"""Shared MVCC staging-store fence semantics (dict form).

The MVCC staging store (transferia_tpu/mvcc/) keeps its columnar layer
DATA in process memory; what must survive crashes and arbitrate races
is the CONTROL state — which delta layers were admitted and whether the
snapshot→replication cutover has been sealed.  That state is one JSON
document per scope stored through the coordinator, exactly like fleet
tickets and obs segments, so all three backends (memory dict / flock'd
file / S3 conditional writes) implement byte-identical semantics around
their own atomicity primitive.

Document shape::

    {"layers": [ {worker, seq, table, lsn_min, lsn_max, rows,
                  content_key, admitted_at}, ... ],      # admission order
     "cutover": null | {"watermark": W, "epoch": E, "sealed_at": ts}}

Rules (mirroring abstract/ticket.py's in-place helpers):

* Layer admission is idempotent under the obs-segment ``(worker, seq)``
  replace convention: re-admitting the same key REPLACES the stored
  metadata in place (same admission position — merge order is stable
  across a worker's retry of a faulted admission RPC).
* The cutover is a single first-wins fence: the first seal wins
  atomically; an identical retry (same watermark AND epoch) is granted
  idempotently; anything else is fenced and handed the sealed decision.
* After the seal, NEW layer admissions are fenced — a zombie snapshot
  worker that wakes up and publishes after the cutover cannot slip rows
  into a decision that already happened.  Re-admitting an
  already-admitted key stays an idempotent ack (the data it refers to
  was part of the decision).
"""

from __future__ import annotations

import time
from typing import Any, Optional

# admission statuses (mvcc_admit_layer result["status"])
ADMITTED = "admitted"      # new (worker, seq) appended pre-cutover
REPLACED = "replaced"      # same (worker, seq) re-put pre-cutover
DUPLICATE = "duplicate"    # same (worker, seq) re-put post-cutover: ack,
#                            no mutation — the layer was in the decision
FENCED = "fenced"          # new (worker, seq) post-cutover: rejected


def new_mvcc_doc() -> dict:
    return {"layers": [], "cutover": None}


def layer_key(layer: dict) -> tuple[str, int]:
    """Identity of a delta layer: the obs-segment (worker, seq) pair."""
    return (str(layer.get("worker", "")), int(layer.get("seq", -1)))


def normalize_layer(layer: dict,
                    now: Optional[float] = None) -> dict:
    """JSON-plain metadata record for one admitted layer.  Only control
    fields cross the coordinator — columnar data stays in process."""
    return {
        "worker": str(layer.get("worker", "")),
        "seq": int(layer.get("seq", -1)),
        "table": str(layer.get("table", "")),
        "lsn_min": int(layer.get("lsn_min", 0)),
        "lsn_max": int(layer.get("lsn_max", 0)),
        "rows": int(layer.get("rows", 0)),
        "content_key": str(layer.get("content_key", "")),
        "admitted_at": (time.time() if now is None else now),
    }


def admit_layer_in_place(doc: dict, layer: dict,
                         now: Optional[float] = None) -> dict:
    """Mutate the scope doc with one layer admission; returns the
    decision dict the backends hand back verbatim."""
    key = layer_key(layer)
    layers = doc.setdefault("layers", [])
    idx = next((i for i, d in enumerate(layers)
                if layer_key(d) == key), None)
    sealed = doc.get("cutover")
    if sealed is not None:
        if idx is not None:
            return {"status": DUPLICATE, "cutover": dict(sealed)}
        return {"status": FENCED, "cutover": dict(sealed)}
    rec = normalize_layer(layer, now)
    if idx is not None:
        layers[idx] = rec
        return {"status": REPLACED, "layers": len(layers)}
    layers.append(rec)
    return {"status": ADMITTED, "layers": len(layers)}


def cutover_in_place(doc: dict, watermark: int, epoch: int,
                     now: Optional[float] = None) -> dict:
    """Seal (or re-acknowledge, or fence) the cutover decision."""
    sealed = doc.get("cutover")
    if sealed is None:
        doc["cutover"] = {"watermark": int(watermark),
                          "epoch": int(epoch),
                          "sealed_at": (time.time() if now is None
                                        else now)}
        return {"granted": True, "first": True,
                "watermark": int(watermark), "epoch": int(epoch)}
    same = (int(sealed.get("watermark", -1)) == int(watermark)
            and int(sealed.get("epoch", -1)) == int(epoch))
    return {"granted": same, "first": False,
            "watermark": int(sealed.get("watermark", -1)),
            "epoch": int(sealed.get("epoch", -1))}


def prune_layers_in_place(doc: dict, keys: list) -> int:
    """Drop layer records by (worker, seq) key — compaction folded them
    into a new base version.  Idempotent: missing keys prune nothing."""
    want = {(str(k[0]), int(k[1])) for k in keys}
    layers = doc.setdefault("layers", [])
    kept = [d for d in layers if layer_key(d) not in want]
    pruned = len(layers) - len(kept)
    doc["layers"] = kept
    return pruned


def doc_watermark(doc: dict) -> int:
    """Delta LSN high-watermark over every admitted layer (-1 = none).
    The cutover driver seals THIS value: the highest LSN any admitted
    layer carries is exactly where the replication lane must resume."""
    layers = doc.get("layers") or []
    if not layers:
        return -1
    return max(int(d.get("lsn_max", 0)) for d in layers)


def state_view(doc: Optional[dict]) -> dict:
    """Read-only JSON-plain snapshot of a scope doc (missing = empty)."""
    if not doc:
        doc = new_mvcc_doc()
    return {
        "layers": [dict(d) for d in (doc.get("layers") or [])],
        "cutover": (dict(doc["cutover"])
                    if doc.get("cutover") else None),
        "watermark": doc_watermark(doc),
    }
