"""Error taxonomy.

Reference parity: pkg/abstract/errors.go (fatal markers), pkg/errors/
(categorized + coded errors).  Fatal errors terminate replication instead of
being retried (runtime/local/replication.go:120-131); coded errors carry a
stable machine-readable code for operators.
"""

from __future__ import annotations

from typing import Optional


class TransferError(Exception):
    """Base class for framework errors."""


class FatalError(TransferError):
    """Non-retriable: replication must stop and the transfer be failed."""


class AbortTransferError(FatalError):
    """Operator-visible abort (bad config, incompatible schema)."""


class WorkerKilledError(TransferError):
    """The worker thread/process is dying (pod eviction, OOM-kill, chaos
    `worker_crash` trials).  Deliberately NOT retriable: the part must be
    left mid-flight with its lease intact so a surviving worker reclaims
    it after expiry — retrying locally would mask the death."""


class TransferPreemptedError(TransferError):
    """The fleet revoked this worker's ticket lease (a higher-priority
    arrival needed the lane) and the snapshot loader yielded at a part
    boundary.  Deliberately NOT retriable locally: the completed parts
    are committed, the ticket is already requeued, and the transfer
    resumes from those parts when it is next claimed — retrying here
    would keep occupying the lane the preemption exists to free."""


class StaleEpochPublishError(TransferError):
    """A staged-commit publish carried an assignment epoch older than
    the sink's last accepted publish for the part: a zombie worker woke
    after its lease expired, the part was reclaimed, and the new owner
    already published.  Deliberately NOT retriable — retrying would
    re-offer the same dead epoch; the engine drops the stale result the
    same way it drops an epoch-fenced coordinator update."""

    def __init__(self, key: str, epoch: int, published_epoch: int):
        super().__init__(
            f"stale publish of {key!r}: epoch {epoch} <= already "
            f"published epoch {published_epoch}")
        self.key = key
        self.epoch = epoch
        self.published_epoch = published_epoch


class CodedError(TransferError):
    """Error with a stable code (pkg/errors/coded)."""

    def __init__(self, code: str, message: str, fatal: bool = False):
        super().__init__(f"[{code}] {message}")
        self.code = code
        self.fatal = fatal


# Stable codes (pkg/errors/codes) — extend as providers land.
class Codes:
    GENERIC_NO_PKEY = "generic.no_primary_key"
    MAIN_WORKER_RESTART = "runtime.main_worker_restart"
    UNPARSEABLE = "parser.unparseable"
    MISSING_DATA_TRANSFORMATION = "transformer.missing_data"
    DIAL_TIMEOUT = "network.dial_timeout"
    DROP_NOT_ALLOWED = "target.drop_not_allowed"
    TABLE_SPLIT_FAILED = "storage.table_split_failed"
    SNAPSHOT_PARTS_ORPHANED = "snapshot.parts_orphaned"


class TableUploadError(TransferError):
    """Per-part upload failure; retried with backoff by the snapshot loader."""

    def __init__(self, message: str, cause: Optional[BaseException] = None):
        super().__init__(message)
        self.cause = cause


class CategorizedError(TransferError):
    """Error attributed to source / target / internal (pkg/errors/categories)."""

    SOURCE = "source"
    TARGET = "target"
    INTERNAL = "internal"

    def __init__(self, category: str, message: str,
                 cause: Optional[BaseException] = None):
        super().__init__(f"({category}) {message}")
        self.category = category
        self.cause = cause


def cause_chain(err: BaseException):
    """Iterate an error and its causes (``__cause__`` or a ``cause``
    attribute, cycle-safe) — THE walk every classification predicate
    below shares, so `is_fatal`/`is_worker_kill`/`is_preemption`/
    `is_retriable` can never disagree about what "anywhere in the
    chain" means."""
    seen = set()
    cur: Optional[BaseException] = err
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        yield cur
        cur = cur.__cause__ or getattr(cur, "cause", None)


def is_fatal(err: BaseException) -> bool:
    """abstract.IsFatal — walks the cause chain."""
    return any(isinstance(cur, FatalError)
               or (isinstance(cur, CodedError) and cur.fatal)
               for cur in cause_chain(err))


# Programming/schema errors: retrying re-executes the identical code on
# the identical input — burning the whole backoff schedule to fail with
# the same traceback.  Walked through the cause chain like is_fatal, so
# a TableUploadError wrapping a TypeError fails fast too.
_NON_RETRIABLE_TYPES = (TypeError, AttributeError, NameError, KeyError,
                        IndexError, AssertionError, WorkerKilledError,
                        StaleEpochPublishError, TransferPreemptedError)


def is_worker_kill(err: BaseException) -> bool:
    """True when a WorkerKilledError sits anywhere in the cause chain
    (the snapshot loader wraps part failures in TableUploadError)."""
    return any(isinstance(cur, WorkerKilledError)
               for cur in cause_chain(err))


def is_preemption(err: BaseException) -> bool:
    """True when a TransferPreemptedError sits anywhere in the cause
    chain (same walk as is_worker_kill — wrappers preserve the chain)."""
    return any(isinstance(cur, TransferPreemptedError)
               for cur in cause_chain(err))


def is_retriable(err: BaseException) -> bool:
    """The single retry predicate: fatal errors (is_fatal semantics) and
    programming/schema errors anywhere in the cause chain fail fast;
    everything else gets the backoff schedule."""
    if is_fatal(err):
        return False
    return not any(isinstance(cur, _NON_RETRIABLE_TYPES)
                   for cur in cause_chain(err))
