"""Core dataplane contracts.

Reference parity: pkg/abstract/source.go:3 (Source), sink.go:14 (Sinker),
async_sink.go:12 (AsyncSink), storage.go:286-420 (Storage + optional
capability interfaces), slot_monitor.go.

The unit flowing through pushers/sinks is a **batch**: either a list of
row-view ChangeItems or a columnar `ColumnBatch` (the TPU currency).  Sinks
that only understand rows call `batch_to_items`; columnar-native sinks (CH,
parquet) consume blocks zero-copy.  Control events always travel as
ChangeItem lists so ordering relative to data blocks is preserved by the
single serialized push path.
"""

from __future__ import annotations

import abc
import concurrent.futures
from typing import Any, Callable, Iterable, Optional, Sequence, Union, TYPE_CHECKING

from transferia_tpu.abstract.change_item import ChangeItem
from transferia_tpu.abstract.schema import TableID, TableSchema
from transferia_tpu.abstract.table import TableDescription

if TYPE_CHECKING:  # avoid import cycle; columnar imports schema only
    from transferia_tpu.columnar.batch import ColumnBatch

# A push unit: row items or one columnar block.
Batch = Union[Sequence[ChangeItem], "ColumnBatch"]

# Synchronous pusher: raises on error (reference: func(items) error).
Pusher = Callable[[Batch], None]


def is_columnar(batch: Batch) -> bool:
    return hasattr(batch, "columns") and hasattr(batch, "n_rows")


class Source(abc.ABC):
    """Replication source (source.go:3): runs until stop() or fatal error."""

    @abc.abstractmethod
    def run(self, sink: "AsyncSink") -> None:
        """Block, pushing batches into sink until stop() is called."""

    @abc.abstractmethod
    def stop(self) -> None:
        ...


class Sinker(abc.ABC):
    """Synchronous, non-concurrent sink (sink.go:10-14)."""

    @abc.abstractmethod
    def push(self, batch: Batch) -> None:
        ...

    def close(self) -> None:
        ...


class AsyncSink(abc.ABC):
    """Asynchronous sink (async_sink.go:12).

    async_push returns a Future resolved when the batch is durably delivered;
    callers ack upstream (e.g. commit Kafka offsets) only after resolution —
    the at-least-once discipline (kafka/source.go:251).
    """

    @abc.abstractmethod
    def async_push(self, batch: Batch) -> "concurrent.futures.Future[None]":
        ...

    def close(self) -> None:
        ...


class SyncAsAsyncSink(AsyncSink):
    """Adapter: wrap a synchronous Sinker as an AsyncSink (resolved inline)."""

    def __init__(self, sinker: Sinker):
        self._sinker = sinker

    def async_push(self, batch: Batch) -> "concurrent.futures.Future[None]":
        fut: concurrent.futures.Future[None] = concurrent.futures.Future()
        try:
            self._sinker.push(batch)
            fut.set_result(None)
        except BaseException as e:  # propagate through the future
            fut.set_exception(e)
        return fut

    def close(self) -> None:
        self._sinker.close()


def resolve_all(futures: Iterable["concurrent.futures.Future[None]"]) -> None:
    """Wait for pushes; re-raise the first error."""
    for f in futures:
        f.result()


class TableInfo:
    """Table listing entry (storage.go TableInfo)."""

    __slots__ = ("eta_rows", "is_view", "schema")

    def __init__(self, eta_rows: int = 0, is_view: bool = False,
                 schema: Optional[TableSchema] = None):
        self.eta_rows = eta_rows
        self.is_view = is_view
        self.schema = schema


class Storage(abc.ABC):
    """Snapshot source (storage.go:286)."""

    @abc.abstractmethod
    def table_list(self, include: Optional[list[TableID]] = None
                   ) -> dict[TableID, TableInfo]:
        ...

    @abc.abstractmethod
    def table_schema(self, table: TableID) -> TableSchema:
        ...

    @abc.abstractmethod
    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        """Stream the table (or slice) into the pusher as batches."""

    def exact_table_rows_count(self, table: TableID) -> int:
        return self.estimate_table_rows_count(table)

    def estimate_table_rows_count(self, table: TableID) -> int:
        return 0

    def table_exists(self, table: TableID) -> bool:
        return table in self.table_list(include=[table])

    def table_size_in_bytes(self, table: TableID) -> int:
        """On-disk size estimate; 0 = unknown (storage.go SizeableStorage)."""
        return 0

    def ping(self) -> None:
        ...

    def close(self) -> None:
        ...


# -- optional storage capabilities (storage.go:300-420) ----------------------

class PositionalStorage(abc.ABC):
    """Exposes the log position at snapshot start (storage.go:300)."""

    @abc.abstractmethod
    def position(self) -> dict[str, Any]:
        ...


class ShardingStorage(abc.ABC):
    """Splits one table into parallel-loadable parts (storage.go:339)."""

    @abc.abstractmethod
    def shard_table(self, table: TableDescription) -> list[TableDescription]:
        ...


class AsyncPartDiscovery(abc.ABC):
    """Streams a table's parts while upload is already running — huge
    object listings must not serialize activation
    (table_part_provider/tpp_setter_async.go, storage.go:379-399)."""

    @abc.abstractmethod
    def iter_table_parts(self, table: TableDescription):
        """Yield TableDescription parts lazily."""


class ShardedStateStorage(abc.ABC):
    """Consistent-point handoff from the main worker's storage to the
    secondaries' (load_snapshot.go:607-671 SetShardedStateToSource)."""

    @abc.abstractmethod
    def sharded_state(self) -> dict:
        ...

    @abc.abstractmethod
    def set_sharded_state(self, state: dict) -> None:
        ...


class SnapshotableStorage(abc.ABC):
    """Transactionally consistent snapshot bracket (storage.go:359)."""

    def begin_snapshot(self) -> None:
        ...

    def end_snapshot(self) -> None:
        ...


class IncrementalStorage(abc.ABC):
    """Cursor-based incremental snapshots (storage.go:354).

    Flow (load_snapshot_incremental.go): before a snapshot the loader reads
    the persisted cursor state, asks for filtered TableDescriptions
    (rows past each cursor), snapshots the slices, and on success persists
    `next_increment_state` — captured BEFORE loading so rows written during
    the snapshot are re-read next time rather than skipped.
    """

    @abc.abstractmethod
    def get_increment_state(self, tables: list["IncrementalTable"],
                            state: dict[str, Any]
                            ) -> list[TableDescription]:
        """Table descriptions filtered to rows past each stored cursor."""

    @abc.abstractmethod
    def next_increment_state(self, tables: list["IncrementalTable"]
                             ) -> dict[str, Any]:
        """Cursor values (str(table_id) -> value) to persist on success."""


class IncrementalTable:
    """RegularSnapshot incremental table spec (model/endpoint IncrementalTable)."""

    __slots__ = ("table", "cursor_field", "initial_state")

    def __init__(self, table: TableID, cursor_field: str, initial_state: str = ""):
        self.table = table
        self.cursor_field = cursor_field
        self.initial_state = initial_state


class ScanPredicateStorage(abc.ABC):
    """Scan-predicate pushdown capability.

    A storage accepting a predicate pre-filters rows during the scan —
    in whatever form is native to it (arrow compute on record batches
    for the fs/S3 readers, a WHERE clause for SQL sources).  Pushdown is
    advisory: the transformer chain re-applies the same predicate, so a
    storage may filter partially or not at all and the output is still
    correct; what it saves is pivot/transform work on rows that were
    going to be dropped anyway.  (The reference's TableDescription
    carries a WHERE-style Filter the SQL storages inline; this is the
    capability-level generalization driven by the chain planner.)
    """

    @abc.abstractmethod
    def set_scan_predicate(self, table: "TableID", node) -> bool:
        """Install a predicate AST (predicate/ast.py) for scans of the
        table; returns True when the storage will use it."""


class SampleableStorage(abc.ABC):
    """Checksum sampling (storage.go:322-337)."""

    @abc.abstractmethod
    def load_random_sample(self, table: TableDescription, pusher: Pusher) -> None:
        ...

    @abc.abstractmethod
    def load_top_bottom_sample(self, table: TableDescription, pusher: Pusher) -> None:
        ...

    @abc.abstractmethod
    def load_sample_by_set(self, table: TableDescription,
                           key_set: Sequence[dict], pusher: Pusher) -> None:
        """Load exactly the rows whose primary keys appear in key_set
        (each entry maps key column name -> value; storage.go:335)."""

    def table_accessible(self, table: TableDescription) -> bool:
        return True
