"""Staged two-phase sink commit contract (exactly-once delivery).

The at-least-once contract (bounded duplication under retries, proved
by the chaos auditor) upgrades to exactly-once for sinks that can
stage: batches land in a **staging area** keyed by the part's
`(operation, part, assignment_epoch)` and become visible only after a
single coordinator-fenced `commit_part` decision grants the publish.

Lifecycle (driven by the snapshot engine, tasks/snapshot.py):

    begin_part(key, epoch)      # open/replace the part's staging area
    push(...)*                  # batches stage (dedup window applied)
    -- coordinator.commit_part(operation, part) --   epoch-fenced
    publish_part(key, epoch)    # granted: staged data becomes visible
    abort_part(key)             # fenced/failed: staged data discarded

Invariants every implementation must uphold:

- **stage replaces**: `begin_part` for a key discards anything
  previously staged under that key — a retried part restages from
  scratch and can never append duplicates into staging;
- **publish replaces**: publishing a part key REPLACES any previously
  published data for that key (the Flight shard server's
  replace-on-reput semantics generalized) — an idempotent republish of
  the same `(part, epoch)` is a no-op-equivalent, and a newer epoch's
  publish supersedes an older one;
- **publish fences**: a publish whose epoch is OLDER than the last
  accepted publish for the key raises
  `abstract.errors.StaleEpochPublishError` — a zombie that somehow got
  past the coordinator fence (grant raced a steal) still cannot
  clobber the survivor's published data;
- **staged data is invisible**: nothing staged may be observable
  through the sink's read/storage surface before `publish_part`.

Sinks without the capability keep the existing at-least-once path with
its bounded-duplication guarantee unchanged — `begin_part` is simply
never called on them.
"""

from __future__ import annotations

import abc
from typing import Optional

from transferia_tpu.abstract.interfaces import Sinker


class StagedSinker(abc.ABC):
    """Capability mixin for sinks that support the staged two-phase
    commit.  A sink both inherits this AND answers
    `staged_commit_available()` (some modes of a sink cannot stage,
    e.g. a single-shot pipe target)."""

    supports_staged_commit = True

    # rows the dedup window dropped during the most recent
    # `publish_part` (replayed torn-write prefixes suppressed before
    # visibility); implementations set it as they publish and the
    # engine folds it into CommitStats
    last_dedup_dropped: int = 0

    def staged_commit_available(self) -> bool:
        """True when THIS instance/configuration can stage (default).
        Checked by the engine before `begin_part`."""
        return True

    @abc.abstractmethod
    def begin_part(self, key: str, epoch: int) -> None:
        """Open the staging area for a part under an assignment epoch,
        replacing anything previously staged for `key`."""

    @abc.abstractmethod
    def publish_part(self, key: str, epoch: int) -> int:
        """Make the staged data visible, replacing any previously
        published data for `key`.  Returns rows published.  Raises
        StaleEpochPublishError when `epoch` is older than the last
        accepted publish for `key`."""

    @abc.abstractmethod
    def abort_part(self, key: str) -> None:
        """Discard the staging area for `key` (fenced or failed part).
        Idempotent; unknown keys are a no-op."""

    def note_push_retry(self) -> None:
        """Called by the sink Retrier right before it re-pushes a
        FAILED batch: arms the open stage's dedup window so a replayed
        torn-write prefix is recognized (the window only ever drops
        when armed — an unarmed push can never be a replay).  No open
        stage = no-op."""


# wrapper attributes the middleware/async layers use to hold the next
# sink down; walked in order by find_staged_sink
_INNER_ATTRS = ("inner", "_sinker", "sinker", "_inner")


def find_staged_sink(sink) -> Optional[StagedSinker]:
    """Walk a middleware/async sink chain down to the raw sink and
    return it when it is a staging-capable StagedSinker (and its
    current configuration can stage), else None.

    The stage/publish lifecycle is a property of the RAW sink (the
    staging area lives in the target), so the engine needs the bottom
    of the chain; middlewares transparently forward pushes and never
    interpose on the commit protocol."""
    seen = set()
    cur = sink
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        if isinstance(cur, StagedSinker):
            return cur if cur.staged_commit_available() else None
        nxt = None
        for attr in _INNER_ATTRS:
            cand = getattr(cur, attr, None)
            if cand is not None and (isinstance(cand, (Sinker, StagedSinker))
                                     or hasattr(cand, "async_push")):
                nxt = cand
                break
        cur = nxt
    return None
