"""Canonical type lattice and table schemas.

Reference parity: pkg/abstract/changeitem/table_schema.go, col_schema.go and
pkg/abstract/typesystem/schema.go:48-68 (the canonical lattice is the YT
schema type set there; we keep the same names minus the `yt` prefix).

TPU-first notes: every canonical type carries a fixed-width device dtype.
Variable-length types (STRING/UTF8/ANY) are represented on device as a
byte tensor + int32 offsets (Arrow-style); DECIMAL travels as scaled int64
pairs or utf8 depending on provider rules.  The schema fingerprint
(`TableSchema.fingerprint`) keys the per-table transformer plan cache and the
XLA compilation cache, mirroring the reference's schema-hash keyed plan cache
(pkg/transformer/transformation.go:47-60).
"""

from __future__ import annotations

import enum
import hashlib
import json
from dataclasses import dataclass, field, replace
from typing import Any, Iterable, Optional

import numpy as np


class CanonicalType(str, enum.Enum):
    """Canonical (provider-independent) column types.

    Mirrors the reference's canonical lattice (typesystem/schema.go:48-68).
    """

    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    UINT16 = "uint16"
    UINT32 = "uint32"
    UINT64 = "uint64"
    FLOAT = "float"      # float32
    DOUBLE = "double"    # float64
    BOOLEAN = "boolean"
    STRING = "string"    # arbitrary bytes
    UTF8 = "utf8"        # validated text
    DATE = "date"        # days since epoch (int32)
    DATETIME = "datetime"    # seconds since epoch (int64)
    TIMESTAMP = "timestamp"  # microseconds since epoch (int64)
    INTERVAL = "interval"    # microseconds (int64)
    DECIMAL = "decimal"      # exact numeric; utf8 on the wire by default
    ANY = "any"          # JSON-ish variant

    @property
    def is_integer(self) -> bool:
        return self in _INTS

    @property
    def is_float(self) -> bool:
        return self in (CanonicalType.FLOAT, CanonicalType.DOUBLE)

    @property
    def is_numeric(self) -> bool:
        return self.is_integer or self.is_float

    @property
    def is_variable_width(self) -> bool:
        """True for types stored as bytes+offsets on device."""
        return self in (
            CanonicalType.STRING,
            CanonicalType.UTF8,
            CanonicalType.ANY,
            CanonicalType.DECIMAL,
        )

    @property
    def np_dtype(self) -> np.dtype:
        """Fixed-width numpy dtype of the device representation."""
        return _NP_DTYPES[self]


_INTS = frozenset(
    {
        CanonicalType.INT8,
        CanonicalType.INT16,
        CanonicalType.INT32,
        CanonicalType.INT64,
        CanonicalType.UINT8,
        CanonicalType.UINT16,
        CanonicalType.UINT32,
        CanonicalType.UINT64,
    }
)

_NP_DTYPES = {
    CanonicalType.INT8: np.dtype(np.int8),
    CanonicalType.INT16: np.dtype(np.int16),
    CanonicalType.INT32: np.dtype(np.int32),
    CanonicalType.INT64: np.dtype(np.int64),
    CanonicalType.UINT8: np.dtype(np.uint8),
    CanonicalType.UINT16: np.dtype(np.uint16),
    CanonicalType.UINT32: np.dtype(np.uint32),
    CanonicalType.UINT64: np.dtype(np.uint64),
    CanonicalType.FLOAT: np.dtype(np.float32),
    CanonicalType.DOUBLE: np.dtype(np.float64),
    CanonicalType.BOOLEAN: np.dtype(np.bool_),
    CanonicalType.DATE: np.dtype(np.int32),
    CanonicalType.DATETIME: np.dtype(np.int64),
    CanonicalType.TIMESTAMP: np.dtype(np.int64),
    CanonicalType.INTERVAL: np.dtype(np.int64),
    # Variable-width: dtype of the *byte buffer*
    CanonicalType.STRING: np.dtype(np.uint8),
    CanonicalType.UTF8: np.dtype(np.uint8),
    CanonicalType.ANY: np.dtype(np.uint8),
    CanonicalType.DECIMAL: np.dtype(np.uint8),
}


@dataclass(frozen=True, order=True)
class TableID:
    """Qualified table identity (changeitem TableID: namespace + name)."""

    namespace: str
    name: str

    def fqtn(self) -> str:
        return f'"{self.namespace}"."{self.name}"' if self.namespace else f'"{self.name}"'

    def __str__(self) -> str:  # pragma: no cover - repr sugar
        return f"{self.namespace}.{self.name}" if self.namespace else self.name

    @staticmethod
    def parse(s: str) -> "TableID":
        if "." in s:
            ns, name = s.split(".", 1)
            return TableID(ns, name)
        return TableID("", s)

    def include_matches(self, pattern: "TableID") -> bool:
        """Wildcard match: pattern parts of '*' or '' match anything."""
        ns_ok = pattern.namespace in ("", "*") or pattern.namespace == self.namespace
        name_ok = pattern.name in ("", "*") or pattern.name == self.name
        return ns_ok and name_ok


@dataclass(frozen=True)
class ColSchema:
    """Column schema (changeitem/col_schema.go).

    `original_type` preserves the provider-native type string (e.g.
    ``pg:bigint``, ``ch:DateTime64(3)``) for target-side DDL fidelity and for
    the versioned fallback machinery.
    """

    name: str
    data_type: CanonicalType
    primary_key: bool = False
    required: bool = False
    original_type: str = ""
    expression: str = ""
    path: str = ""  # nested-source path (parsers)
    properties: tuple = ()

    def with_type(self, t: CanonicalType) -> "ColSchema":
        return replace(self, data_type=t)


class TableSchema:
    """Ordered column collection with a fast name index and a fingerprint.

    Reference: changeitem/table_schema.go.  Immutable by convention; all
    mutators return new TableSchema instances so the fingerprint can be
    safely used as an XLA/plan cache key.
    """

    __slots__ = ("columns", "_index", "_fingerprint")

    def __init__(self, columns: Iterable[ColSchema]):
        self.columns: tuple[ColSchema, ...] = tuple(columns)
        self._index = {c.name: i for i, c in enumerate(self.columns)}
        self._fingerprint: Optional[str] = None

    def __iter__(self):
        return iter(self.columns)

    def __len__(self) -> int:
        return len(self.columns)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, TableSchema) and self.columns == other.columns

    def __hash__(self) -> int:
        return hash(self.columns)

    def __repr__(self) -> str:  # pragma: no cover
        return f"TableSchema({[c.name for c in self.columns]})"

    def names(self) -> list[str]:
        return [c.name for c in self.columns]

    def find(self, name: str) -> Optional[ColSchema]:
        i = self._index.get(name)
        return self.columns[i] if i is not None else None

    def index_of(self, name: str) -> int:
        return self._index.get(name, -1)

    def key_columns(self) -> list[ColSchema]:
        return [c for c in self.columns if c.primary_key]

    def has_primary_key(self) -> bool:
        return any(c.primary_key for c in self.columns)

    def fingerprint(self) -> str:
        """Stable hash of the full schema — plan/compile cache key.

        Mirrors the reference's schema hash used to invalidate transformer
        plans (pkg/transformer/transformation.go:47-60).
        """
        if self._fingerprint is None:
            payload = json.dumps(
                [
                    (c.name, c.data_type.value, c.primary_key, c.required,
                     c.original_type, c.expression, c.path, list(c.properties))
                    for c in self.columns
                ],
                separators=(",", ":"),
                default=str,
            ).encode()
            self._fingerprint = hashlib.sha256(payload).hexdigest()[:16]
        return self._fingerprint

    # -- functional mutators ------------------------------------------------
    def project(self, names: Iterable[str]) -> "TableSchema":
        keep = [n for n in names if n in self._index]
        return TableSchema(self.columns[self._index[n]] for n in keep)

    def drop(self, names: Iterable[str]) -> "TableSchema":
        dropset = set(names)
        return TableSchema(c for c in self.columns if c.name not in dropset)

    def rename(self, mapping: dict[str, str]) -> "TableSchema":
        return TableSchema(
            replace(c, name=mapping.get(c.name, c.name)) for c in self.columns
        )

    def append(self, *cols: ColSchema) -> "TableSchema":
        return TableSchema(self.columns + tuple(cols))

    def with_types(self, mapping: dict[str, CanonicalType]) -> "TableSchema":
        return TableSchema(
            c.with_type(mapping[c.name]) if c.name in mapping else c
            for c in self.columns
        )

    def to_json(self) -> list[dict[str, Any]]:
        return [
            {
                "name": c.name,
                "type": c.data_type.value,
                "key": c.primary_key,
                "required": c.required,
                "original_type": c.original_type,
                "expression": c.expression,
                "path": c.path,
            }
            for c in self.columns
        ]

    @staticmethod
    def from_json(items: list[dict[str, Any]]) -> "TableSchema":
        return TableSchema(
            ColSchema(
                name=i["name"],
                data_type=CanonicalType(i["type"]),
                primary_key=i.get("key", False),
                required=i.get("required", False),
                original_type=i.get("original_type", ""),
                expression=i.get("expression", ""),
                path=i.get("path", ""),
            )
            for i in items
        )


def new_table_schema(cols: list[tuple], **kw) -> TableSchema:
    """Convenience constructor: list of (name, type[, primary_key]) tuples."""
    out = []
    for spec in cols:
        name, ctype = spec[0], spec[1]
        pk = bool(spec[2]) if len(spec) > 2 else False
        if isinstance(ctype, str):
            ctype = CanonicalType(ctype)
        out.append(ColSchema(name=name, data_type=ctype, primary_key=pk, **kw))
    return TableSchema(out)
