"""Core data model (reference: pkg/abstract/, pkg/abstract/changeitem/)."""

from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.abstract.schema import (
    CanonicalType,
    ColSchema,
    TableID,
    TableSchema,
)
from transferia_tpu.abstract.change_item import (
    ChangeItem,
    OldKeys,
    collapse,
    split_by_id,
    split_by_table_id,
)
from transferia_tpu.abstract.table import (
    TableDescription,
    OperationTablePart,
)
from transferia_tpu.abstract.errors import (
    FatalError,
    AbortTransferError,
    TableUploadError,
    CodedError,
    is_fatal,
)
from transferia_tpu.abstract.interfaces import (
    AsyncSink,
    IncrementalStorage,
    SampleableStorage,
    ShardingStorage,
    Sinker,
    Source,
    Storage,
    Pusher,
)

__all__ = [
    "Kind",
    "CanonicalType",
    "ColSchema",
    "TableID",
    "TableSchema",
    "ChangeItem",
    "OldKeys",
    "collapse",
    "split_by_id",
    "split_by_table_id",
    "TableDescription",
    "OperationTablePart",
    "FatalError",
    "AbortTransferError",
    "TableUploadError",
    "CodedError",
    "is_fatal",
    "AsyncSink",
    "Sinker",
    "Source",
    "Storage",
    "Pusher",
    "ShardingStorage",
    "IncrementalStorage",
    "SampleableStorage",
]
