"""Row-event kinds and control events.

Reference parity: pkg/abstract/changeitem/kind.go and const.go — row kinds
(insert/update/delete), DDL-ish kinds, and the system/control kinds that
bracket snapshot table loads (InitTableLoad/DoneTableLoad/
InitShardedTableLoad/DoneShardedTableLoad) plus the Synchronize barrier.

Control events are first-class here because the TPU pipeline processes row
data in columnar blocks: control events must never be reordered relative to
the blocks of the same table part, so they travel as standalone items through
the same serialized push path (see middlewares/ and parsequeue/).
"""

from __future__ import annotations

import enum


class Kind(str, enum.Enum):
    # Row kinds
    INSERT = "insert"
    UPDATE = "update"
    DELETE = "delete"

    # Schema/DDL kinds
    DDL = "ddl"
    PG_DDL = "pg:DDL"
    MONGO_CREATE = "mongo:create"
    MONGO_DROP = "mongo:drop"
    MONGO_RENAME = "mongo:rename"
    MONGO_DROP_DATABASE = "mongo:dropDatabase"
    MONGO_NOOP = "mongo:noop"
    TRUNCATE = "truncate"
    DROP = "drop"

    # Snapshot control kinds (kind.go: InitTableLoad et al.)
    INIT_TABLE_LOAD = "init_load_table"
    DONE_TABLE_LOAD = "done_load_table"
    INIT_SHARDED_TABLE_LOAD = "init_sharded_table_load"
    DONE_SHARDED_TABLE_LOAD = "done_sharded_table_load"

    # Barrier used by async sinks to force a flush and confirm delivery
    SYNCHRONIZE = "synchronize"

    @property
    def is_row(self) -> bool:
        return self in _ROW_KINDS

    @property
    def is_control(self) -> bool:
        return self in _CONTROL_KINDS

    @property
    def is_system(self) -> bool:
        """Non-row kinds: control events plus DDL-ish events."""
        return self not in _ROW_KINDS


_ROW_KINDS = frozenset({Kind.INSERT, Kind.UPDATE, Kind.DELETE})
_CONTROL_KINDS = frozenset(
    {
        Kind.INIT_TABLE_LOAD,
        Kind.DONE_TABLE_LOAD,
        Kind.INIT_SHARDED_TABLE_LOAD,
        Kind.DONE_SHARDED_TABLE_LOAD,
        Kind.SYNCHRONIZE,
    }
)

# Stable int8 codes for the columnar representation (ColumnBatch.kinds).
# Only row kinds appear inside columnar blocks; control events are standalone.
KIND_CODES = {Kind.INSERT: 0, Kind.UPDATE: 1, Kind.DELETE: 2}
CODE_KINDS = {v: k for k, v in KIND_CODES.items()}
