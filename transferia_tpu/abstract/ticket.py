"""FleetTicket: one admission-queue entry of the distributed fleet.

The in-process fleet (fleet/scheduler.py) keeps its queue in Python
deques — a scheduler crash loses every queued transfer.  The
distributed fleet (fleet/distributed.py) keeps the queue in the
COORDINATOR instead: tickets are JSON documents the backends store
durably (memory dicts / flock'd files / S3 conditional writes), so N
scheduler replicas share one queue, a restart resumes exactly where
the dead scheduler stopped, and worker PROCESSES claim work without
ever talking to the scheduler.

Claims reuse the part-lease design (coordinator/interface.py) verbatim:
a claim is a lease the holding worker renews from its heartbeat;
`claim_epoch` bumps on every (re)claim and revocation, and any
completion/release carrying a stale epoch is fenced — a zombie worker
that wakes after its ticket was reclaimed (crash) or revoked
(preemption) cannot mark the reassigned ticket done.

State machine (see ARCHITECTURE.md "Distributed fleet"):

    queued --claim--> claimed --complete--> done | failed
      ^                  |
      |                  +-- release (drain / transient fault / yield)
      +--- revoke (preemption) / lease expiry (crash reclaim)

Shared helpers (`ticket_claimable`, `claim_in_place`, ...) mutate the
JSON dict form in place so the three backends implement byte-identical
semantics around their own atomicity primitive (lock / flock / CAS).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Optional

# fixed priority order shared with the fleet QoS classes
# (fleet/scheduler.py QosClass values); lower rank = more latency
# sensitive = preempts, never preempted by a higher rank
QOS_RANK = {"interactive": 0, "batch": 1, "scavenger": 2}

TICKET_STATES = ("queued", "claimed", "done", "failed")


@dataclass
class FleetTicket:
    """One schedulable transfer in a durable fleet queue."""

    ticket_id: str
    transfer_id: str = ""
    tenant: str = "default"
    qos: str = "batch"                  # interactive | batch | scavenger
    cost: int = 1                       # deficit units (~parts weight)
    # what to run: a payload the worker's runner registry resolves
    # (fleet/worker.py) — callables can't cross a process boundary
    payload: dict = field(default_factory=dict)
    # -- queue bookkeeping (owned by the coordinator backends) ------------
    seq: int = -1                       # durable admission order
    state: str = "queued"
    claimed_by: str = ""                # worker id ("" = unclaimed)
    claim_epoch: int = 0                # bumps on claim/reclaim/revoke
    lease_expires_at: float = 0.0
    attempts: int = 0                   # claims granted so far
    failures: int = 0                   # failed RUN attempts (a claim
    #                                     after a preemption/drain yield
    #                                     is not a failure — yields must
    #                                     not burn the retry budget)
    stolen_from: str = ""               # prev holder on a crash reclaim
    preempted_from: str = ""            # prev holder on the last revoke
    preemptions: int = 0
    error: str = ""
    enqueued_at: float = 0.0
    completed_at: float = 0.0           # wall clock of the terminal
    #                                     transition (retention GC key)

    def key(self) -> str:
        return self.ticket_id

    @property
    def qos_rank(self) -> int:
        return QOS_RANK.get(self.qos, QOS_RANK["batch"])

    @property
    def terminal(self) -> bool:
        return self.state in ("done", "failed")

    def to_json(self) -> dict:
        return {
            "ticket_id": self.ticket_id,
            "transfer_id": self.transfer_id,
            "tenant": self.tenant,
            "qos": self.qos,
            "cost": self.cost,
            "payload": dict(self.payload),
            "seq": self.seq,
            "state": self.state,
            "claimed_by": self.claimed_by,
            "claim_epoch": self.claim_epoch,
            "lease_expires_at": self.lease_expires_at,
            "attempts": self.attempts,
            "failures": self.failures,
            "stolen_from": self.stolen_from,
            "preempted_from": self.preempted_from,
            "preemptions": self.preemptions,
            "error": self.error,
            "enqueued_at": self.enqueued_at,
            "completed_at": self.completed_at,
        }

    @classmethod
    def from_json(cls, d: dict) -> "FleetTicket":
        return cls(
            ticket_id=d["ticket_id"],
            transfer_id=d.get("transfer_id", ""),
            tenant=d.get("tenant", "default"),
            qos=d.get("qos", "batch"),
            cost=int(d.get("cost", 1)),
            payload=dict(d.get("payload") or {}),
            seq=int(d.get("seq", -1)),
            state=d.get("state", "queued"),
            claimed_by=d.get("claimed_by", ""),
            claim_epoch=int(d.get("claim_epoch", 0)),
            lease_expires_at=float(d.get("lease_expires_at", 0.0)),
            attempts=int(d.get("attempts", 0)),
            failures=int(d.get("failures", 0)),
            stolen_from=d.get("stolen_from", ""),
            preempted_from=d.get("preempted_from", ""),
            preemptions=int(d.get("preemptions", 0)),
            error=d.get("error", ""),
            enqueued_at=float(d.get("enqueued_at", 0.0)),
            completed_at=float(d.get("completed_at", 0.0)),
        )


# -- shared dict-form semantics (one implementation, three backends) ---------

def ticket_lease_expired(d: dict, now: Optional[float] = None) -> bool:
    """Same rule as part leases: 0 = no lease, never expires.  Wall
    clock — tickets cross process/host boundaries."""
    exp = float(d.get("lease_expires_at") or 0.0)
    if exp <= 0:
        return False
    return exp < (time.time() if now is None else now)


def ticket_claimable(d: dict, now: Optional[float] = None) -> bool:
    """Claimable = queued, OR claimed with an expired lease (the holder
    is presumed dead: crash reclaim)."""
    state = d.get("state", "queued")
    if state == "queued":
        return True
    return state == "claimed" and ticket_lease_expired(d, now)


def claim_in_place(d: dict, worker_id: str, lease_seconds: float,
                   now: Optional[float] = None) -> None:
    """Mutate a claimable ticket dict into this worker's claim: bump
    the epoch (fencing), stamp a fresh lease, record a steal when the
    previous holder's lease expired."""
    now = time.time() if now is None else now
    stolen = d.get("state") == "claimed"
    d["stolen_from"] = d.get("claimed_by", "") if stolen else ""
    d["state"] = "claimed"
    d["claimed_by"] = worker_id
    d["claim_epoch"] = int(d.get("claim_epoch", 0)) + 1
    d["attempts"] = int(d.get("attempts", 0)) + 1
    d["lease_expires_at"] = (now + lease_seconds
                             if lease_seconds > 0 else 0.0)


def fence_matches(d: dict, ticket: "FleetTicket") -> bool:
    """The single ticket fence: a completion/release is accepted only
    from the holder of the CURRENT claim epoch."""
    return (d.get("state") == "claimed"
            and d.get("claimed_by") == ticket.claimed_by
            and int(d.get("claim_epoch", 0)) == ticket.claim_epoch)


def complete_is_duplicate(d: dict, ticket: "FleetTicket") -> bool:
    """True when the stored ticket is already TERMINAL under this same
    claim (epoch + holder match): the completion RPC applied but its
    response was lost, and the worker is retrying.  Completion is
    idempotent under one epoch — the retry must be acknowledged, not
    misreported as a zombie fence (complete_in_place keeps claimed_by
    exactly so this check can tell a retry from a reclaim)."""
    return (d.get("state") in ("done", "failed")
            and d.get("claimed_by") == ticket.claimed_by
            and int(d.get("claim_epoch", 0)) == ticket.claim_epoch)


def complete_in_place(d: dict, error: str = "") -> None:
    d["state"] = "failed" if error else "done"
    d["error"] = error
    d["lease_expires_at"] = 0.0
    d["completed_at"] = time.time()


def ticket_expired(d: dict, retention_seconds: float,
                   now: Optional[float] = None) -> bool:
    """Retention rule shared by the three backends' GC: only TERMINAL
    tickets age out, `retention_seconds` after their terminal
    transition (tickets from before the completed_at field fall back
    to enqueued_at — old terminal records, prunable either way)."""
    if d.get("state") not in ("done", "failed"):
        return False
    ts = float(d.get("completed_at") or d.get("enqueued_at") or 0.0)
    return ts + retention_seconds < (time.time() if now is None
                                     else now)


def release_in_place(d: dict, failed: bool = False) -> None:
    """Return a claimed ticket to the queue (graceful drain, transient
    fault, preemption yield).  The attempt stays counted; the epoch is
    NOT bumped here — the next claim bumps it.  `failed=True` records
    a failed RUN attempt: only these count against the retry budget —
    a preemption or drain yield is scheduler-initiated and must not
    walk the ticket toward permanent failure."""
    d["state"] = "queued"
    d["claimed_by"] = ""
    d["lease_expires_at"] = 0.0
    if failed:
        d["failures"] = int(d.get("failures", 0)) + 1


def revoke_in_place(d: dict) -> None:
    """Preemption: force a claimed ticket back to the queue and bump
    the epoch NOW, so the (still running) old holder's completion or
    release is fenced the moment the revoke lands — it yields at its
    next part boundary and the transfer resumes elsewhere from its
    committed parts."""
    d["preempted_from"] = d.get("claimed_by", "")
    d["preemptions"] = int(d.get("preemptions", 0)) + 1
    d["claim_epoch"] = int(d.get("claim_epoch", 0)) + 1
    d["state"] = "queued"
    d["claimed_by"] = ""
    d["lease_expires_at"] = 0.0


def sort_key(d: dict) -> tuple:
    """Stable queue order: QoS rank first, then durable admission seq
    — the deterministic tie-break every picker shares."""
    return (QOS_RANK.get(d.get("qos", "batch"), 1),
            int(d.get("seq", -1)))
