"""Parser contracts (pkg/parsers/abstract.go:9-71, utils.go:145)."""

from __future__ import annotations

import abc
import time
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

from transferia_tpu.abstract.schema import (
    CanonicalType,
    ColSchema,
    TableID,
    TableSchema,
)
from transferia_tpu.columnar.batch import ColumnBatch

# System table receiving unparseable rows (parsers/utils.go:145 _unparsed).
UNPARSED_TABLE = TableID("", "_unparsed")

UNPARSED_SCHEMA = TableSchema([
    ColSchema("_timestamp", CanonicalType.TIMESTAMP),
    ColSchema("_partition", CanonicalType.UTF8, primary_key=True),
    ColSchema("_offset", CanonicalType.UINT64, primary_key=True),
    ColSchema("_idx", CanonicalType.UINT32, primary_key=True),
    ColSchema("unparsed_row", CanonicalType.STRING),
    ColSchema("reason", CanonicalType.UTF8),
])


@dataclass(frozen=True)
class Message:
    """One queue message (parsers/abstract.go Message)."""

    value: bytes
    key: bytes = b""
    topic: str = ""
    partition: int = 0
    offset: int = 0
    write_time_ns: int = 0
    headers: tuple = ()


@dataclass
class ParseResult:
    """DoBatch output: parsed columnar blocks + unparsed leftovers."""

    batches: list[ColumnBatch] = field(default_factory=list)
    unparsed: Optional[ColumnBatch] = None

    def row_count(self) -> int:
        return sum(b.n_rows for b in self.batches)


class Parser(abc.ABC):
    """Payload decoder (abstract.go:35-38).

    do_batch is the hot path: one vectorized decode per message batch.
    """

    TYPE = ""

    @abc.abstractmethod
    def do_batch(self, messages: Sequence[Message]) -> ParseResult:
        ...

    def do(self, message: Message) -> ParseResult:
        return self.do_batch([message])

    def result_schema(self) -> Optional[TableSchema]:
        """Declared output schema, when static."""
        return None


def unparsed_batch(messages: Sequence[Message], reasons: Sequence[str],
                   topic_table: str = "") -> ColumnBatch:
    """Build the `_unparsed` block for failed messages."""
    n = len(messages)
    now = time.time_ns() // 1000
    return ColumnBatch.from_pydict(
        UNPARSED_TABLE, UNPARSED_SCHEMA, {
            "_timestamp": [
                (m.write_time_ns // 1000) if m.write_time_ns else now
                for m in messages
            ],
            "_partition": [
                f"{m.topic}:{m.partition}" for m in messages
            ],
            "_offset": [m.offset for m in messages],
            "_idx": list(range(n)),
            "unparsed_row": [m.value for m in messages],
            "reason": list(reasons),
        }
    )
