"""Parser registry (pkg/parsers/registry.go:25-38).

Config shape (endpoint `parser_config` capability, model Parseable):

    parser:
      json: {schema: [...], table: "t", add_system_cols: true}
    # or: tskv / debezium / blank / cloudevents / protobuf / ...
"""

from __future__ import annotations

from typing import Any, Callable

from transferia_tpu.parsers.base import Parser

_REGISTRY: dict[str, Callable[[dict], Parser]] = {}


def register_parser(type_name: str):
    def deco(cls_or_factory):
        if isinstance(cls_or_factory, type):
            cls_or_factory.TYPE = type_name
            _REGISTRY[type_name] = lambda cfg: cls_or_factory(**(cfg or {}))
        else:
            _REGISTRY[type_name] = cls_or_factory
        return cls_or_factory

    return deco


def make_parser(config: Any) -> Parser:
    """Build from {type_name: cfg} one-of map or (type_name, cfg)."""
    if isinstance(config, dict):
        if len(config) != 1:
            raise ValueError(
                f"parser config must be a single-key map, got {config!r}"
            )
        (type_name, cfg), = config.items()
    else:
        type_name, cfg = config
    factory = _REGISTRY.get(type_name)
    if factory is None:
        raise KeyError(
            f"unknown parser {type_name!r}; known: {sorted(_REGISTRY)}"
        )
    p = factory(cfg or {})
    p.TYPE = type_name
    return p


def registered_parsers() -> list[str]:
    return sorted(_REGISTRY)
