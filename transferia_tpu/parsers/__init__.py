"""Parsers: queue payloads -> columnar batches / ChangeItems.

Reference parity: pkg/parsers/ (abstract.go Message/MessageBatch,
Parser.Do/DoBatch, registry.go, the _unparsed policy in utils.go:145) and
pkg/parsers/registry/ plugins.

TPU-first difference: DoBatch is the primary API and returns ColumnBatches
(whole message batches decode into columnar buffers at once — pyarrow's
vectorized JSON/CSV readers on host today, device byte-tensor kernels where
it pays); per-message Do exists for CDC edges.  Rows that fail to parse are
routed to the `_unparsed` system table, never dropped.
"""

from transferia_tpu.parsers.base import (
    Message,
    ParseResult,
    Parser,
    UNPARSED_TABLE,
    unparsed_batch,
)
from transferia_tpu.parsers.registry import (
    make_parser,
    register_parser,
    registered_parsers,
)

import transferia_tpu.parsers.plugins  # noqa: F401  (self-registration)

__all__ = [
    "Message",
    "ParseResult",
    "Parser",
    "UNPARSED_TABLE",
    "unparsed_batch",
    "make_parser",
    "register_parser",
    "registered_parsers",
]
