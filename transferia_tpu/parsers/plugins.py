"""Parser registry plugins beyond the generic JSON/TSKV pair.

Reference parity: pkg/parsers/registry/ — audittrailsv1, blank, cloudevents,
cloudlogging, confluentschemaregistry, debezium, json, logfeller, native,
protobuf, raw_to_table, tskv.  json/tskv live in generic.py; logfeller is
Yandex-internal and intentionally out of scope.
"""

from __future__ import annotations

import json
import logging
from typing import Optional, Sequence

logger = logging.getLogger(__name__)

from transferia_tpu.abstract.change_item import ChangeItem
from transferia_tpu.abstract.schema import (
    CanonicalType,
    ColSchema,
    TableID,
    TableSchema,
)
from transferia_tpu.columnar.batch import Column, ColumnBatch
from transferia_tpu.parsers.base import (
    Message,
    ParseResult,
    Parser,
    unparsed_batch,
)
from transferia_tpu.parsers.generic import GenericJsonParser
from transferia_tpu.parsers.registry import register_parser

import transferia_tpu.parsers.generic  # noqa: F401  (registers json/tskv)


# Raw queue-mirror schema (changeitem/mirror.go: topic/partition/offset/
# write time + raw data as the row).
RAW_SCHEMA = TableSchema([
    ColSchema("topic", CanonicalType.UTF8, primary_key=True),
    ColSchema("partition", CanonicalType.UINT32, primary_key=True),
    ColSchema("offset", CanonicalType.UINT64, primary_key=True),
    ColSchema("timestamp", CanonicalType.TIMESTAMP),
    ColSchema("key", CanonicalType.STRING),
    ColSchema("data", CanonicalType.STRING),
])


@register_parser("blank")
@register_parser("raw_to_table")
class BlankParser(Parser):
    """Messages pass through as raw rows (registry/blank, raw_to_table)."""

    def __init__(self, table: str = "", namespace: str = ""):
        self.table = table
        self.namespace = namespace

    def do_batch(self, messages: Sequence[Message]) -> ParseResult:
        if not messages:
            return ParseResult()
        table = TableID(self.namespace,
                        self.table or messages[0].topic or "data")
        batch = ColumnBatch.from_pydict(table, RAW_SCHEMA, {
            "topic": [m.topic for m in messages],
            "partition": [m.partition for m in messages],
            "offset": [m.offset for m in messages],
            "timestamp": [m.write_time_ns // 1000 for m in messages],
            "key": [m.key for m in messages],
            "data": [m.value for m in messages],
        })
        return ParseResult(batches=[batch])

    def result_schema(self) -> TableSchema:
        return RAW_SCHEMA


@register_parser("debezium")
class DebeziumParser(Parser):
    """Debezium envelopes -> ChangeItems -> columnar blocks
    (registry/debezium + engine)."""

    def __init__(self, schema_registry_url: str = "",
                 schema_registry_user: str = "",
                 schema_registry_password: str = "", **kw):
        from transferia_tpu.debezium import DebeziumReceiver

        unpacker = None
        if schema_registry_url:
            # Confluent wire-format messages (0x00 + schema id frame)
            from transferia_tpu.debezium.packer import Unpacker
            from transferia_tpu.schemaregistry import SchemaRegistryClient

            unpacker = Unpacker(SchemaRegistryClient(
                schema_registry_url, user=schema_registry_user,
                password=schema_registry_password))
        self.receiver = DebeziumReceiver(unpacker=unpacker)

    def do_batch(self, messages: Sequence[Message]) -> ParseResult:
        items: list[ChangeItem] = []
        bad: list[Message] = []
        reasons: list[str] = []
        for m in messages:
            try:
                it = self.receiver.receive(m.value, m.key or None)
                if it is not None:
                    items.append(it)
            except (ValueError, KeyError, TypeError) as e:
                bad.append(m)
                reasons.append(f"debezium: {e}")
        result = ParseResult()
        # group consecutive same-(table, schema) runs into columnar blocks
        run: list[ChangeItem] = []

        def flush():
            if run:
                result.batches.append(ColumnBatch.from_rows(run))
                run.clear()

        for it in items:
            if run and (it.table_id != run[0].table_id
                        or it.table_schema != run[0].table_schema):
                flush()
            run.append(it)
        flush()
        if bad:
            result.unparsed = unparsed_batch(bad, reasons)
        return result


@register_parser("cloudevents")
class CloudEventsParser(Parser):
    """CloudEvents 1.0 structured-JSON messages (registry/cloudevents)."""

    SCHEMA = TableSchema([
        ColSchema("id", CanonicalType.UTF8, primary_key=True),
        ColSchema("source", CanonicalType.UTF8, primary_key=True),
        ColSchema("specversion", CanonicalType.UTF8),
        ColSchema("type", CanonicalType.UTF8),
        ColSchema("subject", CanonicalType.UTF8),
        ColSchema("time", CanonicalType.UTF8),
        ColSchema("datacontenttype", CanonicalType.UTF8),
        ColSchema("data", CanonicalType.ANY),
    ])

    def __init__(self, table: str = "cloudevents", namespace: str = ""):
        self.table = TableID(namespace, table)

    def do_batch(self, messages: Sequence[Message]) -> ParseResult:
        rows, bad, reasons = [], [], []
        for m in messages:
            try:
                obj = json.loads(m.value)
                if not isinstance(obj, dict) or "id" not in obj \
                        or "source" not in obj:
                    raise ValueError("missing required id/source")
                rows.append(obj)
            except ValueError as e:
                bad.append(m)
                reasons.append(f"cloudevents: {e}")
        result = ParseResult()
        if rows:
            result.batches.append(ColumnBatch.from_pydict(
                self.table, self.SCHEMA, {
                    c.name: [r.get(c.name) for r in rows]
                    for c in self.SCHEMA
                }
            ))
        if bad:
            result.unparsed = unparsed_batch(bad, reasons)
        return result


@register_parser("native")
class NativeParser(Parser):
    """Framework-native ChangeItem JSON lines (registry/native)."""

    def do_batch(self, messages: Sequence[Message]) -> ParseResult:
        items, bad, reasons = [], [], []
        for m in messages:
            for line in m.value.split(b"\n"):
                if not line.strip():
                    continue
                try:
                    items.append(ChangeItem.from_json(json.loads(line)))
                except (ValueError, KeyError) as e:
                    bad.append(Message(value=line, topic=m.topic,
                                       partition=m.partition,
                                       offset=m.offset))
                    reasons.append(f"native: {e}")
        result = ParseResult()
        run: list[ChangeItem] = []
        for it in items:
            if run and (it.table_id != run[0].table_id
                        or it.table_schema != run[0].table_schema):
                result.batches.append(ColumnBatch.from_rows(run))
                run = []
            run.append(it)
        if run:
            result.batches.append(ColumnBatch.from_rows(run))
        if bad:
            result.unparsed = unparsed_batch(bad, reasons)
        return result


@register_parser("audittrailsv1")
def _audittrails(cfg: dict) -> Parser:
    """Audit-trails preset of the generic parser (registry/audittrailsv1)."""
    return GenericJsonParser(
        schema=[
            {"name": "event_id", "type": "utf8", "key": True},
            {"name": "event_source", "type": "utf8"},
            {"name": "event_type", "type": "utf8"},
            {"name": "event_time", "type": "utf8"},
            {"name": "authentication", "type": "any"},
            {"name": "authorization", "type": "any"},
            {"name": "resource_metadata", "type": "any"},
            {"name": "request_metadata", "type": "any"},
            {"name": "event_status", "type": "utf8"},
            {"name": "details", "type": "any"},
        ],
        table=cfg.get("table", "audit_trails"),
        add_system_cols=False,
    )


@register_parser("cloudlogging")
def _cloudlogging(cfg: dict) -> Parser:
    """Cloud-logging preset (registry/cloudlogging)."""
    return GenericJsonParser(
        schema=[
            {"name": "uid", "type": "utf8", "key": True},
            {"name": "resource", "type": "any"},
            {"name": "timestamp", "type": "utf8"},
            {"name": "ingested_at", "type": "utf8"},
            {"name": "saved_at", "type": "utf8"},
            {"name": "level", "type": "utf8"},
            {"name": "message", "type": "utf8"},
            {"name": "json_payload", "type": "any"},
            {"name": "stream_name", "type": "utf8"},
        ],
        table=cfg.get("table", "cloud_logging"),
        add_system_cols=False,
    )


@register_parser("protobuf")
class ProtobufParser(Parser):
    """Protobuf messages via a compiled message class
    (registry/protobuf; lazy per-field decode is a later optimization).

    config: message: "package.module:MessageClass", table, namespace.
    """

    def __init__(self, message: str, table: str = "data",
                 namespace: str = ""):
        import importlib

        mod, cls = message.split(":", 1)
        self.msg_cls = getattr(importlib.import_module(mod), cls)
        self.table = TableID(namespace, table)

    def do_batch(self, messages: Sequence[Message]) -> ParseResult:
        rows, bad, reasons = [], [], []
        from google.protobuf.json_format import MessageToDict

        for m in messages:
            try:
                pb = self.msg_cls()
                pb.ParseFromString(m.value)
                rows.append(MessageToDict(pb, preserving_proto_field_name=True))
            except Exception as e:  # protobuf raises DecodeError etc.
                bad.append(m)
                reasons.append(f"protobuf: {e}")
        result = ParseResult()
        if rows:
            seen: dict[str, CanonicalType] = {}
            for r in rows[:100]:
                for k, v in r.items():
                    from transferia_tpu.parsers.generic import _infer_type

                    seen.setdefault(k, _infer_type(v))
            schema = TableSchema([ColSchema(k, t) for k, t in seen.items()])
            result.batches.append(ColumnBatch.from_pydict(
                self.table, schema,
                {k: [r.get(k) for r in rows] for k in seen}
            ))
        if bad:
            result.unparsed = unparsed_batch(bad, reasons)
        return result


@register_parser("confluent_schema_registry")
class ConfluentSRParser(Parser):
    """Confluent wire format (magic byte 0 + 4-byte schema id + payload).

    Resolves schemas through a pluggable resolver (pkg/schemaregistry
    equivalent).  JSON-schema payloads decode via the generic parser;
    AVRO payloads decode with the in-repo schema-driven binary decoder
    (schemaregistry/avro.py) using the registered writer schema.
    """

    def __init__(self, table: str = "data", namespace: str = "",
                 resolver: Optional[object] = None,
                 registry_url: str = "", registry_user: str = "",
                 registry_password: str = ""):
        self.table = table
        self.namespace = namespace
        # resolver: callable(schema_id) -> field-spec list (the generic
        # parser's `schema` config) or None; a registry_url builds one over
        # the Confluent REST API (pkg/schemaregistry equivalent); absent
        # falls back to schema inference
        if resolver is None and registry_url:
            from transferia_tpu.schemaregistry import sr_resolver

            resolver = sr_resolver(registry_url, user=registry_user,
                                   password=registry_password)
        self.resolver = resolver
        self.registry_url = registry_url
        self.registry_user = registry_user
        self.registry_password = registry_password
        self._parsers: dict[int, GenericJsonParser] = {}
        self._avro: dict[int, object] = {}
        self._client = None

    def _sr_client(self):
        if self._client is None:
            # reuse the resolver's client when it exposes one (sr_resolver
            # does) — one connection/config/cache, not two
            self._client = getattr(self.resolver, "client", None)
        if self._client is None and self.registry_url:
            from transferia_tpu.schemaregistry import SchemaRegistryClient

            self._client = SchemaRegistryClient(
                self.registry_url, user=self.registry_user,
                password=self.registry_password)
        return self._client

    def _avro_for(self, schema_id: int):
        """AvroSchema for a registered AVRO entry; None when the registry
        says the id is NOT Avro (cached).  Transient registry failures
        RAISE: dead-lettering valid data on an outage would consume the
        offsets forever — the parse failure propagates so the runtime
        retries the batch without committing (at-least-once)."""
        if schema_id in self._avro:
            return self._avro[schema_id]
        client = self._sr_client()
        avro = None
        if client is not None:
            try:
                entry = client.schema_by_id(schema_id)
            except Exception as e:
                if "404" in str(e):
                    # PERMANENTLY absent id (deleted / foreign registry):
                    # cache the miss so the message dead-letters instead
                    # of poisoning the partition with endless retries
                    logger.warning("schema id %d not registered (404)",
                                   schema_id)
                    self._avro[schema_id] = None
                    return None
                raise  # transient outage: abort the batch for retry
            if entry.get("schemaType", "AVRO") == "AVRO":
                from transferia_tpu.schemaregistry.avro import AvroSchema

                try:
                    avro = AvroSchema(entry["schema"])
                except Exception as e:
                    logger.warning("schema id %d: bad avro schema (%s)",
                                   schema_id, e)
                    avro = None  # permanently undecodable: cacheable
        self._avro[schema_id] = avro
        return avro

    @staticmethod
    def _avro_col_type(node) -> CanonicalType:
        prim = {
            "int": CanonicalType.INT32, "long": CanonicalType.INT64,
            "float": CanonicalType.FLOAT, "double": CanonicalType.DOUBLE,
            "boolean": CanonicalType.BOOLEAN,
            "string": CanonicalType.UTF8, "bytes": CanonicalType.STRING,
        }
        if isinstance(node, str):
            return prim.get(node, CanonicalType.ANY)
        if node[0] == "union":
            # only the nullable-field idiom has a single concrete type;
            # multi-branch unions can carry any branch's value
            concrete = [b for b in node[1] if b != "null"]
            if len(concrete) == 1:
                return ConfluentSRParser._avro_col_type(concrete[0])
            return CanonicalType.ANY
        if node[0] == "enum":
            return CanonicalType.UTF8
        if node[0] == "fixed":
            return CanonicalType.STRING
        return CanonicalType.ANY

    # avro primitive -> (C type code, canonical type) for the flat-record
    # native fast path (hostops.cpp avro_decode_flat)
    _AVRO_C_TYPES = {
        "boolean": (1, CanonicalType.BOOLEAN),
        "int": (2, CanonicalType.INT32),
        "long": (2, CanonicalType.INT64),
        "float": (3, CanonicalType.FLOAT),
        "double": (4, CanonicalType.DOUBLE),
        "string": (5, CanonicalType.UTF8),
        "bytes": (5, CanonicalType.STRING),
    }

    def _flat_spec(self, avro):
        """(name, c_code, ctype, nullable, null_branch) per field when the
        schema is a flat record of primitives (None = out of envelope);
        cached per AvroSchema instance."""
        # cached ON the schema object: an id()-keyed dict would serve a
        # stale spec if a freed AvroSchema's address got reused
        spec = getattr(avro, "_flat_spec_cache", False)
        if spec is not False:
            return spec
        spec = None
        root = avro.root
        if isinstance(root, list) and root[0] == "record":
            out = []
            for name, t in root[2]:
                nullable, null_branch = False, 0
                node = t
                if isinstance(node, list) and node[0] == "union" \
                        and len(node[1]) == 2 and "null" in node[1]:
                    nullable = True
                    null_branch = node[1].index("null")
                    node = node[1][1 - null_branch]
                if not isinstance(node, str) \
                        or node not in self._AVRO_C_TYPES:
                    out = None
                    break
                code, ctype = self._AVRO_C_TYPES[node]
                out.append((name, code, ctype, nullable, null_branch))
            spec = out or None
        try:
            avro._flat_spec_cache = spec
        except AttributeError:  # slotted schema object: just recompute
            pass
        return spec

    def _avro_batch_native(self, avro, msgs: list[Message]):
        """Columnar decode of a flat-record run via the C decoder; None
        defers to the exact per-row path (out of envelope, native lib
        absent, or any malformed message in the run)."""
        from transferia_tpu.native import lib as native_lib

        cdll = native_lib()
        if cdll is None or not hasattr(cdll, "avro_decode_flat"):
            return None
        spec = self._flat_spec(avro)
        if spec is None:
            return None
        import numpy as np

        n = len(msgs)
        payloads = [m.value for m in msgs]
        data = np.frombuffer(b"".join(payloads), dtype=np.uint8)
        offs = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(p) for p in payloads], out=offs[1:])
        if int(offs[-1]) > 0x7FFF0000:
            # var-width offsets are int32 in the C decoder
            return None
        ftypes = np.array([c for _, c, _, _, _ in spec], dtype=np.uint8)
        fnull = np.array([1 if nl else 0 for *_, nl, _ in spec],
                         dtype=np.uint8)
        fbr = np.array([br for *_, br in spec], dtype=np.uint8)
        tasks = np.zeros((len(spec), 6), dtype=np.int64)
        holds = []
        for i, (name, code, ctype, nullable, _br) in enumerate(spec):
            validity = np.empty(n, dtype=np.uint8) if nullable else None
            if code == 5:
                cap = int(offs[-1])
                vdata = np.empty(max(cap, 1), dtype=np.uint8)
                voffs = np.empty(n + 1, dtype=np.int32)
                tasks[i, 1] = vdata.ctypes.data
                tasks[i, 2] = voffs.ctypes.data
                tasks[i, 3] = cap
                holds.append((vdata, voffs, validity))
            else:
                dt = {1: np.uint8, 2: np.int64, 3: np.float32,
                      4: np.float64}[code]
                out = np.empty(n, dtype=dt)
                tasks[i, 0] = out.ctypes.data
                holds.append((out, validity))
            if validity is not None:
                tasks[i, 4] = validity.ctypes.data
        rc = cdll.avro_decode_flat(
            data if data.size else np.zeros(1, dtype=np.uint8),
            offs, n, ftypes, fnull, fbr, len(spec), tasks.reshape(-1))
        if rc != n:
            return None
        cols = {}
        for i, (name, code, ctype, nullable, _br) in enumerate(spec):
            h = holds[i]
            validity = h[-1]
            v = None
            if validity is not None and not validity.all():
                v = validity.astype(np.bool_)
            if code == 5:
                vdata, voffs = h[0], h[1]
                flat = vdata[:int(voffs[n])]
                if ctype == CanonicalType.UTF8:
                    # the exact path DECODES strings (and dead-letters
                    # rows with invalid utf-8); one bulk validation over
                    # the flat buffer keeps the classification identical
                    try:
                        flat.tobytes().decode("utf-8")
                    except UnicodeDecodeError:
                        return None
                cols[name] = Column(name, ctype, flat, voffs, v)
            else:
                vals = h[0]
                if ctype == CanonicalType.INT32:
                    vals = vals.astype(np.int32)
                elif ctype == CanonicalType.BOOLEAN:
                    vals = vals.view(np.bool_)
                cols[name] = Column(name, ctype, vals, None, v)
        schema = TableSchema([
            ColSchema(name, ctype) for name, _, ctype, _, _ in spec])
        result = ParseResult()
        result.batches.append(ColumnBatch(
            TableID(self.namespace, self.table), schema, cols))
        return result

    def _avro_batch(self, avro, msgs: list[Message]) -> ParseResult:
        fast = None
        try:
            fast = self._avro_batch_native(avro, msgs)
        except Exception:  # any surprise: the exact path decides
            logger.debug("native avro fast path failed", exc_info=True)
        if fast is not None:
            return fast
        result = ParseResult()
        rows, bad, reasons = [], [], []
        for m in msgs:
            try:
                rows.append(avro.decode(m.value))
            except Exception as e:
                bad.append(m)
                reasons.append(f"avro: {e}")
        if rows:
            root = avro.root
            if isinstance(root, list) and root[0] == "record":
                cols = [(name, self._avro_col_type(t))
                        for name, t in root[2]]
            else:  # non-record root: single value column
                cols = [("value", self._avro_col_type(root))]
                rows = [{"value": r} for r in rows]
            schema = TableSchema([ColSchema(n, t) for n, t in cols])
            result.batches.append(ColumnBatch.from_pydict(
                TableID(self.namespace, self.table), schema,
                {n: [r.get(n) for r in rows] for n, _ in cols},
            ))
        if bad:
            result.unparsed = unparsed_batch(bad, reasons)
        return result

    def _parser_for(self, schema_id: int) -> GenericJsonParser:
        p = self._parsers.get(schema_id)
        if p is None:
            fields = None
            resolver_ok = True
            if self.resolver is not None:
                try:
                    fields = self.resolver(schema_id)
                except Exception as e:
                    # transient registry outage: fall back to inference for
                    # this batch but do NOT cache, so the id retries later
                    logger.warning(
                        "schema registry lookup for id %d failed (%s); "
                        "falling back to inference", schema_id, e,
                    )
                    resolver_ok = False
            p = GenericJsonParser(schema=fields, table=self.table,
                                  namespace=self.namespace)
            if resolver_ok:
                self._parsers[schema_id] = p
        return p

    def do_batch(self, messages: Sequence[Message]) -> ParseResult:
        import struct

        # contiguous runs per schema id: offset order within the batch must
        # survive schema evolution (CDC consumers replay in emit order)
        runs: list[tuple[int, list[Message]]] = []
        bad, reasons = [], []
        for m in messages:
            v = m.value
            if len(v) >= 5 and v[0] == 0:
                schema_id = struct.unpack(">I", v[1:5])[0]
                payload = v[5:]
                stripped = Message(
                    value=payload, key=m.key, topic=m.topic,
                    partition=m.partition, offset=m.offset,
                    write_time_ns=m.write_time_ns,
                )
                # the registry's schemaType is authoritative: an Avro
                # payload may begin with 0x7b ('{') by coincidence (e.g.
                # a long field encoding -62), so byte-sniffing only
                # decides when the id has no registered Avro schema
                if self._avro_for(schema_id) is not None:
                    kind = "avro"
                elif payload[:1] in (b"{", b"["):
                    kind = "json"
                else:
                    bad.append(m)
                    reasons.append(
                        "confluent-sr: binary payload and no AVRO schema "
                        "registered for this id"
                    )
                    continue
                if runs and runs[-1][0] == (schema_id, kind):
                    runs[-1][1].append(stripped)
                else:
                    runs.append(((schema_id, kind), [stripped]))
            else:
                bad.append(m)
                reasons.append("confluent-sr: missing magic byte")
        result = ParseResult()
        for (schema_id, kind), msgs in runs:
            if kind == "avro":
                sub = self._avro_batch(self._avro_for(schema_id), msgs)
            else:
                sub = self._parser_for(schema_id).do_batch(msgs)
            result.batches.extend(sub.batches)
            if sub.unparsed is not None:
                result.unparsed = sub.unparsed \
                    if result.unparsed is None else \
                    ColumnBatch.concat([result.unparsed, sub.unparsed])
        if bad:
            ub = unparsed_batch(bad, reasons)
            result.unparsed = ub if result.unparsed is None else \
                ColumnBatch.concat([result.unparsed, ub])
        return result
