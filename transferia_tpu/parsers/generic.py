"""Generic schema-driven JSON/TSKV parser.

Reference parity: pkg/parsers/generic/generic_parser.go (the ~2.3 KLoC CPU
hot loop of the reference) + lookup.go field tables.  Re-designed columnar:
the whole message batch decodes in one vectorized pass (pyarrow's JSON block
reader into arrow columns -> ColumnBatch, no per-row Go/Python loop), with
per-row error localization by recursive bisection — a failed block splits in
halves until bad rows are isolated (O(log n) vectorized parses when errors
are rare), which solves SURVEY.md §7 hard-part (d) without giving up batch
decode.  Failed rows go to `_unparsed` (utils.go:145 policy).

System columns (_timestamp/_partition/_offset/_idx) become the primary key
like the reference's generic parser output schema.
"""

from __future__ import annotations

import json
from typing import Any, Optional, Sequence

import numpy as np

from transferia_tpu.abstract.schema import (
    CanonicalType,
    ColSchema,
    TableID,
    TableSchema,
)
from transferia_tpu.columnar.batch import ColumnBatch, Column
from transferia_tpu.parsers.base import (
    Message,
    ParseResult,
    Parser,
    unparsed_batch,
)
from transferia_tpu.parsers.registry import register_parser

_SYSTEM_COLS = [
    ColSchema("_timestamp", CanonicalType.TIMESTAMP, primary_key=True),
    ColSchema("_partition", CanonicalType.UTF8, primary_key=True),
    ColSchema("_offset", CanonicalType.UINT64, primary_key=True),
    ColSchema("_idx", CanonicalType.UINT32, primary_key=True),
]


def _field_to_colschema(f: dict) -> ColSchema:
    return ColSchema(
        name=f["name"],
        data_type=CanonicalType(f.get("type", "any")),
        primary_key=bool(f.get("key", False)),
        required=bool(f.get("required", False)),
        path=f.get("path", ""),
    )


class _Lines:
    """Flattened (message, line) view of a batch."""

    __slots__ = ("values", "msg_index", "line_index", "arrow_failed_full")

    def __init__(self, messages: Sequence[Message]):
        self.values: list[bytes] = []
        self.msg_index: list[int] = []
        self.line_index: list[int] = []
        self.arrow_failed_full = False
        for mi, m in enumerate(messages):
            for li, line in enumerate(m.value.split(b"\n")):
                if line.strip():
                    self.values.append(line)
                    self.msg_index.append(mi)
                    self.line_index.append(li)


@register_parser("json")
@register_parser("generic")
class GenericJsonParser(Parser):
    """config: schema: [{name,type,key?,path?,required?}] (None = infer),
    table, namespace, add_system_cols, unescape_string_values."""

    def __init__(self, schema: Optional[list[dict]] = None,
                 table: str = "data", namespace: str = "",
                 add_system_cols: bool = True,
                 null_keys_allowed: bool = False):
        self.fields = [_field_to_colschema(f) for f in (schema or [])]
        self.table = TableID(namespace, table)
        self.add_system_cols = add_system_cols
        self.null_keys_allowed = null_keys_allowed
        self._schema: Optional[TableSchema] = None
        if self.fields:
            self._schema = self._build_schema(self.fields)

    def _build_schema(self, fields: list[ColSchema]) -> TableSchema:
        cols = list(fields)
        if self.add_system_cols:
            has_user_key = any(c.primary_key for c in cols)
            sys_cols = [
                ColSchema(c.name, c.data_type,
                          primary_key=not has_user_key,
                          required=c.required)
                for c in _SYSTEM_COLS
            ]
            cols = sys_cols + cols
        return TableSchema(cols)

    def result_schema(self) -> Optional[TableSchema]:
        return self._schema

    # -- decoding -----------------------------------------------------------
    def _decode_rows(self, values: list[bytes],
                     skip_full_arrow: bool = False) -> list[Optional[dict]]:
        """Vectorized decode with bisecting error isolation.

        Returns one dict per line (None = unparseable).  The fast path
        decodes the whole block in one C++ pass (pyarrow's JSON reader for
        large batches, a single stdlib json.loads for small ones); only
        blocks containing a bad row pay the recursive split.
        """
        out: list[Optional[dict]] = [None] * len(values)

        def block_decode(lo: int, hi: int) -> Optional[list[dict]]:
            blob = b"[" + b",".join(values[lo:hi]) + b"]"
            try:
                rows = json.loads(blob)
            except ValueError:
                return None
            if len(rows) != hi - lo or \
                    not all(isinstance(r, dict) for r in rows):
                return None
            return rows

        def block_decode_arrow(lo: int, hi: int) -> Optional[list[dict]]:
            """One vectorized pass over newline-joined rows (arrow's C++
            block reader) — ~5-10x json.loads on wide batches.  Used only
            with an explicit scalar schema so arrow can't reinterpret
            values (e.g. date-like strings) differently from json.loads;
            any mismatch falls back to the bisecting stdlib path."""
            import io

            try:
                import pyarrow as pa
                import pyarrow.json as pajson
            except ImportError:
                return block_decode(lo, hi)
            schema = self._arrow_schema()
            if schema is None:
                return block_decode(lo, hi)
            blob = b"\n".join(values[lo:hi])
            try:
                tbl = pajson.read_json(
                    io.BytesIO(blob),
                    parse_options=pajson.ParseOptions(
                        newlines_in_values=False,
                        explicit_schema=schema,
                        unexpected_field_behavior="ignore",
                    ),
                )
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                return None
            if tbl.num_rows != hi - lo:
                return None
            return tbl.to_pylist()

        def attempt(lo: int, hi: int, skip_arrow: bool = False) -> None:
            use_arrow = hi - lo >= 256 and not skip_arrow
            rows = (block_decode_arrow(lo, hi) if use_arrow
                    else block_decode(lo, hi))
            if rows is not None:
                out[lo:hi] = rows
                return
            if hi - lo == 1:
                return  # isolated bad row stays None
            mid = (lo + hi) // 2
            attempt(lo, mid)
            attempt(mid, hi)

        if values:
            # skip_full_arrow: the caller already ran (and failed) the
            # full-range arrow parse — don't pay it twice
            attempt(0, len(values), skip_arrow=skip_full_arrow)
        return out

    def _arrow_schema(self):
        """Explicit arrow schema for the C++ fast path, or None when the
        declared fields need features arrow can't mirror (nested paths,
        ANY variants, inference) or pyarrow is absent."""
        if not self.fields:
            return None
        try:
            import pyarrow as pa
        except ImportError:
            return None

        scalar = {
            CanonicalType.INT8: pa.int64(), CanonicalType.INT16: pa.int64(),
            CanonicalType.INT32: pa.int64(),
            CanonicalType.INT64: pa.int64(),
            CanonicalType.FLOAT: pa.float64(),
            CanonicalType.DOUBLE: pa.float64(),
            CanonicalType.BOOLEAN: pa.bool_(),
            CanonicalType.UTF8: pa.string(),
            CanonicalType.STRING: pa.string(),
        }
        out = []
        for cs in self.fields:
            if cs.path or cs.data_type not in scalar:
                return None
            out.append(pa.field(cs.name, scalar[cs.data_type]))
        return pa.schema(out)

    def _extract(self, rows: list[dict], cs: ColSchema) -> list[Any]:
        if cs.path:
            parts = cs.path.split(".")

            def get(r):
                cur: Any = r
                for p in parts:
                    if not isinstance(cur, dict) or p not in cur:
                        return None
                    cur = cur[p]
                return cur

            return [get(r) for r in rows]
        return [r.get(cs.name) for r in rows]

    def _fast_columnar(self, messages: Sequence[Message],
                       lines: "_Lines") -> Optional[ParseResult]:
        """Whole-batch columnar shortcut: arrow-decode straight into the
        ColumnBatch with vectorized system columns — no per-row dicts.
        Returns None when anything (bad rows, null keys, exotic schema)
        needs the general path."""
        if type(self) is not GenericJsonParser or not self.fields:
            return None
        if len(lines.values) < 256:
            return None
        import io

        import numpy as np

        try:
            import pyarrow as pa
            import pyarrow.json as pajson
        except ImportError:  # minimal install: general path only
            return None
        schema = self._arrow_schema()
        if schema is None:
            return None
        try:
            tbl = pajson.read_json(
                io.BytesIO(b"\n".join(lines.values)),
                parse_options=pajson.ParseOptions(
                    newlines_in_values=False,
                    explicit_schema=schema,
                    unexpected_field_behavior="ignore",
                ),
            )
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
            # tell the general path the full-range arrow parse is a known
            # failure so it goes straight to bisection
            lines.arrow_failed_full = True
            return None
        if tbl.num_rows != len(lines.values):
            lines.arrow_failed_full = True
            return None
        keep = np.ones(tbl.num_rows, dtype=bool)
        if not self.null_keys_allowed:
            # null-key offenders route to _unparsed without abandoning the
            # already-done C++ parse
            for cs in self.fields:
                if cs.primary_key and tbl.column(cs.name).null_count:
                    keep &= np.asarray(
                        tbl.column(cs.name).combine_chunks().is_valid()
                    )
        kept_pos = np.nonzero(keep)[0]
        if len(kept_pos) != tbl.num_rows:
            tbl = tbl.take(pa.array(kept_pos))
        out_schema = self._schema or self._build_schema(self.fields)
        batch = ColumnBatch.from_arrow(
            tbl.combine_chunks().to_batches()[0], self.table,
            out_schema.project([c.name for c in self.fields]),
        ) if tbl.num_rows else None
        cols = dict(batch.columns) if batch is not None else {}
        if self.add_system_cols and batch is not None:
            midx = np.asarray(lines.msg_index)[kept_pos]
            write_ns = np.array(
                [m.write_time_ns for m in messages], dtype=np.int64
            )
            offsets_arr = np.array(
                [m.offset for m in messages], dtype=np.uint64
            )
            parts = [f"{m.topic}:{m.partition}" for m in messages]
            cols["_timestamp"] = Column(
                "_timestamp", CanonicalType.TIMESTAMP,
                (write_ns // 1000)[midx],
            )
            cols["_partition"] = Column.from_pylist(
                "_partition", CanonicalType.UTF8,
                [parts[i] for i in midx],
            )
            cols["_offset"] = Column("_offset", CanonicalType.UINT64,
                                     offsets_arr[midx])
            cols["_idx"] = Column(
                "_idx", CanonicalType.UINT32,
                np.asarray(lines.line_index,
                           dtype=np.uint32)[kept_pos],
            )
        result = ParseResult()
        if batch is not None:
            ordered = {
                c.name: cols[c.name] for c in out_schema if c.name in cols
            }
            result.batches.append(
                ColumnBatch(self.table, out_schema, ordered)
            )
        bad_pos = np.nonzero(~keep)[0]
        if len(bad_pos):
            bad_msgs = [
                Message(
                    value=lines.values[i],
                    topic=messages[lines.msg_index[i]].topic,
                    partition=messages[lines.msg_index[i]].partition,
                    offset=messages[lines.msg_index[i]].offset,
                    write_time_ns=messages[lines.msg_index[i]]
                    .write_time_ns,
                )
                for i in bad_pos
            ]
            result.unparsed = unparsed_batch(
                bad_msgs, ["null value in key column"] * len(bad_pos)
            )
        return result

    def do_batch(self, messages: Sequence[Message]) -> ParseResult:
        lines = _Lines(messages)
        fast = self._fast_columnar(messages, lines)
        if fast is not None:
            return fast
        decoded = self._decode_rows(
            lines.values, skip_full_arrow=lines.arrow_failed_full
        )

        # line index -> failure reason; grows as validation rejects rows
        bad: dict[int, str] = {
            i: "invalid " + ("JSON" if type(self) is GenericJsonParser
                             else self.TYPE)
            for i, d in enumerate(decoded) if d is None
        }
        good_idx = [i for i in range(len(decoded)) if i not in bad]

        fields = self.fields
        if not fields and good_idx:
            # schema inference from the first good rows
            seen: dict[str, CanonicalType] = {}
            for i in good_idx[:100]:
                for k, v in decoded[i].items():
                    seen.setdefault(k, _infer_type(v))
            fields = [ColSchema(k, t) for k, t in seen.items()]

        schema = self._schema or self._build_schema(fields)
        rows = [decoded[i] for i in good_idx]
        data: dict[str, list] = {}
        for cs in fields:
            data[cs.name] = self._extract(rows, cs)
        # null-key validation — offenders move to _unparsed
        if not self.null_keys_allowed:
            for kn in (c.name for c in fields if c.primary_key):
                for j, v in enumerate(data[kn]):
                    if v is None and good_idx[j] not in bad:
                        bad[good_idx[j]] = f"null value in key column {kn}"
        if len(bad) and rows:
            keep = [j for j, i in enumerate(good_idx) if i not in bad]
            data = {k: [v[j] for j in keep] for k, v in data.items()}
            good_idx = [good_idx[j] for j in keep]

        if self.add_system_cols:
            metas = [messages[lines.msg_index[i]] for i in good_idx]
            data["_timestamp"] = [m.write_time_ns // 1000 for m in metas]
            data["_partition"] = [
                f"{m.topic}:{m.partition}" for m in metas
            ]
            data["_offset"] = [m.offset for m in metas]
            data["_idx"] = [lines.line_index[i] for i in good_idx]

        result = ParseResult()
        if good_idx:
            coerced = _coerce(data, schema)
            result.batches.append(
                ColumnBatch.from_pydict(self.table, schema, coerced)
            )
        if bad:
            order = sorted(bad)
            bad_msgs = [
                Message(
                    value=lines.values[i],
                    topic=messages[lines.msg_index[i]].topic,
                    partition=messages[lines.msg_index[i]].partition,
                    offset=messages[lines.msg_index[i]].offset,
                    write_time_ns=messages[lines.msg_index[i]].write_time_ns,
                )
                for i in order
            ]
            result.unparsed = unparsed_batch(
                bad_msgs, [bad[i] for i in order]
            )
        return result


def _infer_type(v: Any) -> CanonicalType:
    if isinstance(v, bool):
        return CanonicalType.BOOLEAN
    if isinstance(v, int):
        return CanonicalType.INT64
    if isinstance(v, float):
        return CanonicalType.DOUBLE
    if isinstance(v, str):
        return CanonicalType.UTF8
    return CanonicalType.ANY


def _coerce(data: dict[str, list], schema: TableSchema) -> dict[str, list]:
    """Best-effort scalar coercion to the declared types."""
    out = {}
    for name, values in data.items():
        cs = schema.find(name)
        if cs is None:
            continue
        t = cs.data_type
        if t.is_numeric or t in (CanonicalType.DATETIME,
                                 CanonicalType.TIMESTAMP,
                                 CanonicalType.DATE):
            def conv(v):
                if v is None or isinstance(v, (int, float)):
                    return v
                try:
                    return float(v) if t.is_float else int(v)
                except (TypeError, ValueError):
                    return None
            out[name] = [conv(v) for v in values]
        elif t == CanonicalType.BOOLEAN:
            out[name] = [
                None if v is None else
                (v if isinstance(v, bool) else str(v).lower() == "true")
                for v in values
            ]
        else:
            out[name] = values
    return out


@register_parser("tskv")
class TskvParser(GenericJsonParser):
    """TSKV (tab-separated key=value) lines -> same output contract."""

    def _decode_rows(self, values: list[bytes],
                     skip_full_arrow: bool = False) -> list[Optional[dict]]:
        out: list[Optional[dict]] = []
        for line in values:
            try:
                text = line.decode("utf-8")
                if text.startswith("tskv\t"):
                    text = text[5:]
                row: dict[str, Any] = {}
                import re as _re

                unescape = {"t": "\t", "n": "\n", "r": "\r", "0": "\0",
                            "\\": "\\", "=": "="}
                for pair in text.split("\t"):
                    if not pair:
                        continue
                    if "=" not in pair:
                        raise ValueError(f"no '=' in {pair!r}")
                    k, v = pair.split("=", 1)
                    # single-pass unescape: sequential .replace corrupts
                    # escaped backslashes followed by t/n
                    row[k] = _re.sub(
                        r"\\(.)",
                        lambda m: unescape.get(m.group(1), m.group(1)),
                        v,
                    )
                out.append(row if row else None)
            except (ValueError, UnicodeDecodeError):
                out.append(None)
        return out
