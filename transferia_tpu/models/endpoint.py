"""Endpoint parameter model + serialization registry.

Reference parity: pkg/abstract/model/endpoint.go (EndpointParams + ~40 opt-in
capability interfaces) and endpoint_registry.go / serialization.go (the
provider-keyed codec used to round-trip endpoint params through YAML/JSON).

Capabilities are opt-in methods/attributes on params classes rather than Go
interface assertions; the helpers below (`capability`) read them with safe
defaults, so providers only declare what they support.
"""

from __future__ import annotations

import dataclasses
import enum
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Type


class CleanupPolicy(str, enum.Enum):
    """Destination cleanup on (re)activation (model CleanupType)."""

    DROP = "drop"
    TRUNCATE = "truncate"
    DISABLED = "disabled"


@dataclass
class EndpointParams:
    """Base endpoint parameters; providers subclass with their own fields.

    Class attributes:
      PROVIDER: registry key (e.g. "pg", "ch", "kafka", "s3", "sample").
      IS_SOURCE/IS_TARGET: which roles the subclass may play.
    """

    PROVIDER = ""
    IS_SOURCE = False
    IS_TARGET = False

    # common opt-ins with defaults (endpoint.go capabilities)
    cleanup_policy: CleanupPolicy = CleanupPolicy.DROP

    def provider(self) -> str:
        return type(self).PROVIDER

    # -- serialization ------------------------------------------------------
    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        for k, v in list(d.items()):
            if isinstance(v, enum.Enum):
                d[k] = v.value
        d["__provider__"] = self.provider()
        d["__role__"] = "source" if type(self).IS_SOURCE else "target"
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "EndpointParams":
        kwargs = {}
        fields = {f.name: f for f in dataclasses.fields(cls)}
        for k, v in d.items():
            if k.startswith("__") or k not in fields:
                continue
            ftype = fields[k].type
            if fields[k].name == "cleanup_policy":
                v = CleanupPolicy(v)
            kwargs[k] = v
        return cls(**kwargs)


# provider -> role -> params class
_ENDPOINT_REGISTRY: dict[tuple[str, str], Type[EndpointParams]] = {}


def register_endpoint(cls: Type[EndpointParams]) -> Type[EndpointParams]:
    """Class decorator: register a params class for YAML/JSON round-trip."""
    role = "source" if cls.IS_SOURCE else "target"
    _ENDPOINT_REGISTRY[(cls.PROVIDER, role)] = cls
    return cls


def endpoint_from_dict(d: dict[str, Any],
                       provider: Optional[str] = None,
                       role: Optional[str] = None) -> EndpointParams:
    provider = provider or d.get("__provider__", "")
    role = role or d.get("__role__", "source")
    cls = _ENDPOINT_REGISTRY.get((provider, role))
    if cls is None:
        raise KeyError(
            f"unknown endpoint: provider={provider!r} role={role!r}; "
            f"known: {sorted(_ENDPOINT_REGISTRY)}"
        )
    return cls.from_dict(d)


def known_endpoints() -> list[tuple[str, str]]:
    return sorted(_ENDPOINT_REGISTRY)


def capability(params: Any, name: str, default: Any = None) -> Any:
    """Read an opt-in capability attribute/method with a default.

    e.g. capability(dst, "is_shardeable", False),
         capability(src, "parser_config", None).
    """
    v = getattr(params, name, default)
    return v() if callable(v) else v
