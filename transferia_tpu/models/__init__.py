"""Transfer/Endpoint model (reference: pkg/abstract/model/)."""

from transferia_tpu.models.endpoint import (
    CleanupPolicy,
    EndpointParams,
    endpoint_from_dict,
    register_endpoint,
)
from transferia_tpu.models.transfer import (
    DataObjects,
    RegularSnapshot,
    Runtime,
    ShardingUploadParams,
    Transfer,
    TransferType,
)

__all__ = [
    "CleanupPolicy",
    "EndpointParams",
    "endpoint_from_dict",
    "register_endpoint",
    "DataObjects",
    "RegularSnapshot",
    "Runtime",
    "ShardingUploadParams",
    "Transfer",
    "TransferType",
]
