"""Transfer model (pkg/abstract/model/transfer.go:15-36).

A Transfer binds source and target endpoint params, the transformation
chain config, an include-list of data objects, the runtime (parallelism),
and the pinned typesystem version.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional

from transferia_tpu.abstract.schema import TableID
from transferia_tpu.models.endpoint import EndpointParams, endpoint_from_dict
from transferia_tpu.typesystem.fallbacks import LATEST_VERSION


class TransferType(str, enum.Enum):
    """pkg/abstract/transfer_type.go."""

    SNAPSHOT_ONLY = "SNAPSHOT_ONLY"
    INCREMENT_ONLY = "INCREMENT_ONLY"
    SNAPSHOT_AND_INCREMENT = "SNAPSHOT_AND_INCREMENT"

    @property
    def has_snapshot(self) -> bool:
        return self in (TransferType.SNAPSHOT_ONLY,
                        TransferType.SNAPSHOT_AND_INCREMENT)

    @property
    def has_replication(self) -> bool:
        return self in (TransferType.INCREMENT_ONLY,
                        TransferType.SNAPSHOT_AND_INCREMENT)


@dataclass
class ShardingUploadParams:
    """local_runtime.go:30-36 ShardingUpload."""

    job_count: int = 1       # processes (k8s indexed-job completions)
    process_count: int = 4   # threads per process (part-queue semaphore)


@dataclass
class Runtime:
    """Local runtime config (pkg/abstract/local_runtime.go:3-7).

    current_job is this worker's index in sharded snapshot mode (index 0 =
    main worker that splits tables and publishes parts).
    """

    current_job: int = 0
    sharding: ShardingUploadParams = field(default_factory=ShardingUploadParams)
    replication_workers: int = 1

    @property
    def is_main(self) -> bool:
        return self.current_job == 0


@dataclass
class DataObjects:
    """Include-list of objects to transfer (transfer_dataobjects.go)."""

    include_object_ids: list[str] = field(default_factory=list)

    def include_ids(self) -> list[TableID]:
        return [TableID.parse(s) for s in self.include_object_ids]

    def empty(self) -> bool:
        return not self.include_object_ids


@dataclass
class IncrementalTableCfg:
    namespace: str = ""
    name: str = ""
    cursor_field: str = ""
    initial_state: str = ""


@dataclass
class RegularSnapshot:
    """Cron-driven incremental re-snapshot (pkg/abstract/regular_snapshot.go)."""

    enabled: bool = False
    cron: str = ""
    incremental: list[IncrementalTableCfg] = field(default_factory=list)


@dataclass
class Transfer:
    id: str = "transfer"
    type: TransferType = TransferType.SNAPSHOT_ONLY
    src: Optional[EndpointParams] = None
    dst: Optional[EndpointParams] = None
    transformation: Optional[dict[str, Any]] = None  # transform chain config
    data_objects: DataObjects = field(default_factory=DataObjects)
    regular_snapshot: RegularSnapshot = field(default_factory=RegularSnapshot)
    runtime: Runtime = field(default_factory=Runtime)
    type_system_version: int = LATEST_VERSION
    labels: dict[str, str] = field(default_factory=dict)
    # {"fingerprint": true}: snapshot workers fingerprint post-transform
    # batches inline (ops/rowhash.py), per-part aggregates merge through
    # the coordinator, and the table digests land in the operation state
    validation: Optional[dict[str, Any]] = None

    def fingerprint_validation(self) -> bool:
        return bool(self.validation and self.validation.get("fingerprint"))

    # -- convenience --------------------------------------------------------
    def src_provider(self) -> str:
        return self.src.provider() if self.src else ""

    def dst_provider(self) -> str:
        return self.dst.provider() if self.dst else ""

    def include_ids(self) -> list[TableID]:
        return self.data_objects.include_ids()

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "type": self.type.value,
            "src": self.src.to_dict() if self.src else None,
            "dst": self.dst.to_dict() if self.dst else None,
            "transformation": self.transformation,
            "data_objects": self.data_objects.include_object_ids,
            "regular_snapshot": {
                "enabled": self.regular_snapshot.enabled,
                "cron": self.regular_snapshot.cron,
                "incremental": [vars(i) for i in self.regular_snapshot.incremental],
            },
            "runtime": {
                "current_job": self.runtime.current_job,
                "job_count": self.runtime.sharding.job_count,
                "process_count": self.runtime.sharding.process_count,
                "replication_workers": self.runtime.replication_workers,
            },
            "type_system_version": self.type_system_version,
            "labels": self.labels,
        }

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "Transfer":
        rt = d.get("runtime") or {}
        rs = d.get("regular_snapshot") or {}
        return Transfer(
            id=d.get("id", "transfer"),
            type=TransferType(d.get("type", "SNAPSHOT_ONLY")),
            src=endpoint_from_dict(d["src"], role="source") if d.get("src") else None,
            dst=endpoint_from_dict(d["dst"], role="target") if d.get("dst") else None,
            transformation=d.get("transformation"),
            data_objects=DataObjects(d.get("data_objects") or []),
            regular_snapshot=RegularSnapshot(
                enabled=rs.get("enabled", False),
                cron=rs.get("cron", ""),
                incremental=[IncrementalTableCfg(**i)
                             for i in rs.get("incremental", [])],
            ),
            runtime=Runtime(
                current_job=rt.get("current_job", 0),
                sharding=ShardingUploadParams(
                    job_count=rt.get("job_count", 1),
                    process_count=rt.get("process_count", 4),
                ),
                replication_workers=rt.get("replication_workers", 1),
            ),
            type_system_version=d.get("type_system_version", LATEST_VERSION),
            labels=d.get("labels") or {},
        )
