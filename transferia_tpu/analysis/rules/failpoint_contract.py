"""FPT001 — failpoint site contract (chaos/).

The chaos plane's whole value is that `chaos/sites.py` is the complete
map of injection sites: `trtpu chaos` schedules over it, operators grep
it, and spec strings validate against it.  That only holds if call
sites can't drift from the catalog.  This rule (REG001-style project
rule) asserts, tree-wide:

  1. every `failpoint(...)` / `torn_rows(...)` call passes a string
     LITERAL site name (a variable would defeat spec validation and
     grep-ability);
  2. every such literal is registered in `chaos/sites.py`;
  3. every site name is owned by exactly ONE call site (two sites
     sharing a name would merge their hit counters and make per-site
     fire sequences ambiguous);
  4. every catalog entry is referenced by some call site (a dead
     catalog entry silently accepts specs that can never fire) — this
     pass only runs when the analyzed file set includes the catalog
     itself (`chaos/sites.py`): a narrowed `trtpu check some/dir` can't
     conclude anything about call sites it didn't parse.

The catalog itself is read via import (like REG001's load pass); unit
tests inject a synthetic catalog via `known_sites`.  Call sites inside
the chaos package itself and in tests are exempt — they exercise the
machinery, they aren't injection sites.
"""

from __future__ import annotations

import ast
from typing import Optional, Sequence

from transferia_tpu.analysis.engine import Finding, ProjectRule

_CALL_NAMES = ("failpoint", "torn_rows")
_EXEMPT_FRAGMENTS = ("transferia_tpu/chaos/", "tests/")


def _call_leaf(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


class FailpointContractRule(ProjectRule):
    id = "FPT001"
    severity = "error"
    description = ("failpoint site not a string literal, unregistered "
                   "in chaos/sites.py, claimed by multiple call sites, "
                   "or registered but never instrumented")
    # unit tests inject a synthetic catalog; None = import the real one
    known_sites: Optional[frozenset] = None
    # site names legitimately without an in-tree call site (none today)
    allow_unreferenced: frozenset = frozenset()

    def _catalog(self) -> Optional[frozenset]:
        if self.known_sites is not None:
            return self.known_sites
        try:
            from transferia_tpu.chaos.sites import site_names

            return site_names()
        except Exception:
            return None

    def check_project(self, root: str,
                      files: dict[str, tuple[ast.AST, list[str]]]
                      ) -> list[Finding]:
        findings: list[Finding] = []
        catalog = self._catalog()
        if catalog is None:
            findings.append(Finding(
                rule=self.id, severity="error", path="<catalog>",
                line=1, col=1,
                message="chaos/sites.py failed to import — the site "
                        "catalog is unreadable",
                snippet="chaos/sites.py"))
            return findings
        owners: dict[str, tuple[str, int]] = {}
        for relpath, (tree, lines) in sorted(files.items()):
            if any(frag in relpath for frag in _EXEMPT_FRAGMENTS):
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                if _call_leaf(node) not in _CALL_NAMES:
                    continue
                if not node.args:
                    findings.append(self.finding(
                        relpath, node,
                        f"{_call_leaf(node)}() call without a site "
                        f"name argument", lines))
                    continue
                arg = node.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    findings.append(self.finding(
                        relpath, node,
                        f"failpoint site name must be a string "
                        f"literal, not an expression — spec "
                        f"validation and FPT001 itself depend on "
                        f"greppable literals", lines))
                    continue
                name = arg.value
                if name not in catalog:
                    findings.append(self.finding(
                        relpath, node,
                        f"failpoint site {name!r} is not registered "
                        f"in chaos/sites.py", lines))
                    continue
                prev = owners.get(name)
                if prev is not None:
                    findings.append(self.finding(
                        relpath, node,
                        f"failpoint site {name!r} already "
                        f"instrumented at {prev[0]}:{prev[1]} — one "
                        f"site name, one call site (shared names "
                        f"merge hit counters)", lines))
                else:
                    owners[name] = (relpath, node.lineno)
        full_tree = any(rel.endswith("chaos/sites.py") for rel in files)
        if not full_tree:
            return findings
        for name in sorted(catalog - set(owners)
                           - self.allow_unreferenced):
            findings.append(Finding(
                rule=self.id, severity="error", path="<catalog>",
                line=1, col=1,
                message=f"site {name!r} is registered in "
                        f"chaos/sites.py but no call site references "
                        f"it — dead catalog entries accept specs that "
                        f"can never fire",
                snippet=name))
        return findings
