"""REG001 — plugin registry contract (providers/transformers/parsers).

The registries bind at import time and break only at transfer time:
a duplicate key silently shadows the earlier registration, and an
abstract class registered by mistake explodes on first instantiation
mid-snapshot.  This rule enforces the contract statically + at load:

  1. AST pass over the whole tree: every `register_transformer("k")` /
     `register_parser("k")` decorator literal and every `NAME = "k"` in
     a `@register_provider` class must be unique tree-wide (the runtime
     dicts can't see collisions — last writer wins silently);
  2. load pass: import the real registries
     (`load_builtin_providers()`, transform + parser plugin packages)
     and assert every registered provider class and every registered
     Transformer/Parser subclass is concrete (no remaining
     `__abstractmethods__`) and, for providers, that NAME matches its
     registry key.

The load pass reports an import failure as a finding rather than
crashing the linter: a registry that can't even import is the contract
violation.
"""

from __future__ import annotations

import ast
from typing import Sequence

from transferia_tpu.analysis.engine import Finding, ProjectRule


def _decorator_key(dec: ast.AST, factory: str) -> str | None:
    """The literal key of `@register_transformer("k")`-style decorators."""
    if isinstance(dec, ast.Call):
        name = dec.func
        leaf = name.attr if isinstance(name, ast.Attribute) else \
            name.id if isinstance(name, ast.Name) else ""
        if leaf == factory and dec.args \
                and isinstance(dec.args[0], ast.Constant) \
                and isinstance(dec.args[0].value, str):
            return dec.args[0].value
    return None


def _has_register_provider(node: ast.ClassDef) -> bool:
    for dec in node.decorator_list:
        leaf = dec.attr if isinstance(dec, ast.Attribute) else \
            dec.id if isinstance(dec, ast.Name) else ""
        if leaf == "register_provider":
            return True
    return False


def _class_name_attr(node: ast.ClassDef) -> str | None:
    for stmt in node.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name) and t.id == "NAME" \
                        and isinstance(stmt.value, ast.Constant) \
                        and isinstance(stmt.value.value, str):
                    return stmt.value.value
    return None


class RegistryContractRule(ProjectRule):
    id = "REG001"
    severity = "error"
    description = ("duplicate registry key, abstract class registered, "
                   "or provider NAME/key mismatch")
    # set False in unit tests that feed synthetic trees
    do_import_check = True

    def check_project(self, root: str,
                      files: dict[str, tuple[ast.AST, list[str]]]
                      ) -> list[Finding]:
        findings: list[Finding] = []
        self._check_duplicates(files, findings)
        if self.do_import_check:
            findings.extend(self.import_check())
        return findings

    # -- pass 1: tree-wide duplicate keys -----------------------------------
    def _check_duplicates(self, files, findings) -> None:
        seen: dict[tuple[str, str], tuple[str, int]] = {}

        def claim(kind: str, key: str, relpath: str, node, lines):
            prev = seen.get((kind, key))
            if prev is not None:
                findings.append(self.finding(
                    relpath, node,
                    f"duplicate {kind} key {key!r} — already registered "
                    f"at {prev[0]}:{prev[1]} (last registration wins "
                    f"silently)", lines))
            else:
                seen[(kind, key)] = (relpath, node.lineno)

        for relpath, (tree, lines) in sorted(files.items()):
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    if _has_register_provider(node):
                        key = _class_name_attr(node)
                        if key is None:
                            findings.append(self.finding(
                                relpath, node,
                                f"provider class {node.name} registered "
                                f"without a literal NAME", lines))
                        else:
                            claim("provider", key, relpath, node, lines)
                    for dec in node.decorator_list:
                        for factory, kind in (
                                ("register_transformer", "transformer"),
                                ("register_parser", "parser")):
                            key = _decorator_key(dec, factory)
                            if key is not None:
                                claim(kind, key, relpath, node, lines)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        for factory, kind in (
                                ("register_transformer", "transformer"),
                                ("register_parser", "parser")):
                            key = _decorator_key(dec, factory)
                            if key is not None:
                                claim(kind, key, relpath, node, lines)

    # -- pass 2: load the real registries -----------------------------------
    def import_check(self) -> list[Finding]:
        findings: list[Finding] = []

        def fail(msg: str) -> None:
            findings.append(Finding(
                rule=self.id, severity="error", path="<registry>",
                line=1, col=1, message=msg, snippet=msg))

        try:
            from transferia_tpu.providers import load_builtin_providers
            from transferia_tpu.providers.registry import (
                _PROVIDERS,
                Provider,
            )

            load_builtin_providers()
            for key, cls in sorted(_PROVIDERS.items()):
                if not issubclass(cls, Provider):
                    fail(f"provider {key!r}: {cls.__name__} is not a "
                         f"Provider subclass")
                if getattr(cls, "NAME", "") != key:
                    fail(f"provider {key!r}: class NAME "
                         f"{getattr(cls, 'NAME', '')!r} != registry key")
                missing = sorted(getattr(cls, "__abstractmethods__", ()))
                if missing:
                    fail(f"provider {key!r}: {cls.__name__} is abstract "
                         f"(missing {', '.join(missing)})")
        except Exception as e:  # registry failed to even import
            fail(f"provider registry failed to load: {e!r}")

        try:
            import transferia_tpu.transform  # noqa: F401 (loads plugins)
            from transferia_tpu.transform.base import Transformer

            for cls in _all_subclasses(Transformer):
                if getattr(cls, "TYPE", None) and \
                        getattr(cls, "__abstractmethods__", ()):
                    missing = sorted(cls.__abstractmethods__)
                    fail(f"transformer {cls.TYPE!r}: {cls.__name__} is "
                         f"abstract (missing {', '.join(missing)})")
        except Exception as e:
            fail(f"transformer registry failed to load: {e!r}")

        try:
            import transferia_tpu.parsers  # noqa: F401 (loads plugins)
            from transferia_tpu.parsers.base import Parser

            for cls in _all_subclasses(Parser):
                if getattr(cls, "TYPE", None) and \
                        getattr(cls, "__abstractmethods__", ()):
                    missing = sorted(cls.__abstractmethods__)
                    fail(f"parser {cls.TYPE!r}: {cls.__name__} is "
                         f"abstract (missing {', '.join(missing)})")
        except Exception as e:
            fail(f"parser registry failed to load: {e!r}")
        return findings


def _all_subclasses(base: type) -> list[type]:
    out, stack = [], [base]
    while stack:
        cls = stack.pop()
        for sub in cls.__subclasses__():
            out.append(sub)
            stack.append(sub)
    return out
