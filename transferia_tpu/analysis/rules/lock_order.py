"""LCK002 — whole-program lock-order cycle detection (potential deadlock).

Builds the interprocedural lock-order graph from
:mod:`transferia_tpu.analysis.callgraph`: an edge ``A -> B`` whenever
lock B is acquired — directly or through any resolvable call chain —
while lock A is held.  A cycle in that graph means two call paths
acquire the same pair (or ring) of locks in opposite orders: the static
analog of the runtime inversion that
:mod:`transferia_tpu.runtime.lockwatch` reports, using the same lock
identities (``lockwatch.named_lock`` names where present, otherwise
``module.Class.attr``).

Each finding prints one witness path per direction as ``file:line ->
file:line`` chains so the two conflicting acquisition orders can be
read straight out of the message.  Reentrant locks (RLock /
``named_lock(kind="rlock")``) never contribute self-edges; cycles
between *distinct* locks are reported regardless of kind — reentrancy
does not save an ABBA deadlock.

Suppress with ``# trtpu: ignore[LCK002]`` on the first witness line
when a cycle is protected by an external invariant the analysis cannot
see (e.g. the two paths are proven never concurrent).
"""

from __future__ import annotations

import ast
from typing import Sequence

from transferia_tpu.analysis import callgraph
from transferia_tpu.analysis.engine import Finding, ProjectRule


def _snippet(files, path: str, line: int) -> str:
    entry = files.get(path)
    if not entry:
        return ""
    lines = entry[1]
    if 0 < line <= len(lines):
        return lines[line - 1].strip()
    return ""


class LockOrderRule(ProjectRule):
    id = "LCK002"
    severity = "error"
    description = ("cycle in the whole-program acquired-while-holding "
                   "lock graph (potential deadlock)")

    def check_project(self, root: str,
                      files: dict[str, tuple[ast.AST, list[str]]]
                      ) -> list[Finding]:
        ix = callgraph.build_index(files)
        findings: list[Finding] = []
        for cycle in callgraph.find_cycles(ix):
            findings.append(self._cycle_finding(ix, cycle, files))
        return findings

    def _cycle_finding(self, ix: callgraph.ProjectIndex,
                       cycle: Sequence[str], files) -> Finding:
        ring = list(cycle) + [cycle[0]]
        edges = [ix.edges[(ring[i], ring[i + 1])]
                 for i in range(len(cycle))]
        order = " -> ".join(ring)
        witnesses = "; ".join(
            f"[{e.src} before {e.dst}] "
            f"{callgraph.format_witness(e)}" for e in edges)
        anchor_path, anchor_line, _ = edges[0].witness[0]
        msg = (f"potential deadlock: lock-order cycle {order}; "
               f"witnesses: {witnesses}")
        return Finding(rule=self.id, severity=self.severity,
                       path=anchor_path, line=anchor_line, col=1,
                       message=msg,
                       snippet=_snippet(files, anchor_path,
                                        anchor_line))
