"""NET001 — resource safety for sockets, HTTP connections and files.

Zero egress + single-core workers mean a hung connect/read blocks a
whole pipeline stage forever; the rule makes the timeout explicit at
every wire touchpoint:

  - `socket.create_connection(addr)` without a timeout (2nd positional
    arg or `timeout=` kwarg) — blocks in SYN retry for minutes;
  - `http.client.HTTP(S)Connection(host)` without `timeout=`;
  - `urllib.request.urlopen(url)` without `timeout=`;
  - `open(...)` consumed inline (argument of another call, or
    method-chained) — the file object is never closed; use `with`.

`timeout=None` is treated as deliberate (it reads as an explicit
choice at the call site) and is not flagged.
"""

from __future__ import annotations

import ast
from typing import Sequence

from transferia_tpu.analysis.engine import Finding, Rule
from transferia_tpu.analysis.engine import dotted_name as _dotted


def _has_timeout_kwarg(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" or kw.arg is None  # **kwargs: assume yes
               for kw in call.keywords)


class ResourceSafetyRule(Rule):
    id = "NET001"
    severity = "warning"
    description = ("socket/HTTP call without an explicit timeout, or a "
                   "file opened outside a context manager")

    def check_file(self, relpath: str, tree: ast.AST,
                   lines: Sequence[str]) -> list[Finding]:
        findings: list[Finding] = []
        parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func) or ""
            leaf = name.rsplit(".", 1)[-1]
            if name.endswith("socket.create_connection") \
                    or name == "create_connection":
                if len(node.args) < 2 and not _has_timeout_kwarg(node):
                    findings.append(self.finding(
                        relpath, node,
                        "socket.create_connection() without a timeout "
                        "blocks in SYN retransmit for minutes on a "
                        "dead host", lines))
            elif leaf in ("HTTPConnection", "HTTPSConnection"):
                if not _has_timeout_kwarg(node):
                    findings.append(self.finding(
                        relpath, node,
                        f"{leaf}() without timeout= hangs the stage on "
                        f"an unresponsive endpoint", lines))
            elif leaf == "urlopen":
                if not _has_timeout_kwarg(node):
                    findings.append(self.finding(
                        relpath, node,
                        "urlopen() without timeout= can block forever",
                        lines))
            elif name == "open":
                parent = parents.get(node)
                inline = (isinstance(parent, ast.Call)
                          or isinstance(parent, ast.Attribute))
                if inline:
                    findings.append(self.finding(
                        relpath, node,
                        "open() consumed inline leaks the file handle "
                        "on error — use `with open(...)`", lines))
        return findings
