"""TRC001 — chaos sites must be visible in causal traces (stats/trace.py).

When a failpoint fires, `chaos/failpoints.py:_record_fire` lands a
`chaos_fire` instant ON the active span — which is only useful if the
function hosting the injection site actually runs under a span (or
emits its own instant): otherwise the fire floats trace-less and a kill
trial's Perfetto timeline shows the *consequences* of a fault but never
the fault itself.  This rule keeps the contract as new sites land: any
`failpoint(...)` / `torn_rows(...)` call site (the same call set FPT001
polices) whose innermost enclosing function neither opens a span nor
emits a trace instant/complete is flagged.

"Opens a span" is syntactic on purpose: a call whose leaf name is
`span`, `instant`, or `complete` anywhere in the enclosing function
(the project idiom is `trace.span(...)` / `trace.instant(...)`; a
local alias like `sp = span(...)` also counts).  Attribute calls only
count when the receiver is a `trace` module reference (`trace.span`,
`stats.trace.instant`): an unrelated `.span()` — e.g. `re.Match.span`
— must not satisfy the contract.  Functions that only
*adopt* a context (`trace.adopted(...)`) do not pass — adoption makes
someone else's span current but records nothing, so a fire inside
still needs a local span/instant for the timeline to show where it
landed.

Call sites inside the chaos package and tests are exempt exactly as in
FPT001 — they exercise the machinery.  `allow_untraced` whitelists
site names whose host function is deliberately span-free (none today).
"""

from __future__ import annotations

import ast

from transferia_tpu.analysis.engine import Finding, ProjectRule

_CALL_NAMES = ("failpoint", "torn_rows")
_TRACE_LEAVES = ("span", "instant", "complete")
_EXEMPT_FRAGMENTS = ("transferia_tpu/chaos/", "tests/")


def _call_leaf(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _functions(tree: ast.AST) -> list[ast.AST]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _innermost_enclosing(funcs: list[ast.AST],
                         node: ast.AST) -> ast.AST | None:
    best = None
    for fn in funcs:
        end = getattr(fn, "end_lineno", fn.lineno)
        if fn.lineno <= node.lineno <= end:
            if best is None or fn.lineno > best.lineno:
                best = fn
    return best


def _receiver_dotted(fn: ast.Attribute) -> str:
    parts = []
    node = fn.value
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _is_trace_call(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        if fn.attr not in _TRACE_LEAVES:
            return False
        recv = _receiver_dotted(fn)
        return recv == "trace" or recv.endswith(".trace")
    if isinstance(fn, ast.Name):
        return fn.id in _TRACE_LEAVES
    return False


def _opens_trace(fn: ast.AST) -> bool:
    return any(isinstance(n, ast.Call) and _is_trace_call(n)
               for n in ast.walk(fn))


class TraceContractRule(ProjectRule):
    id = "TRC001"
    severity = "error"
    description = ("failpoint site whose enclosing function opens no "
                   "span and emits no trace instant — the chaos fire "
                   "would be invisible in causal timelines")
    # site names whose host function is deliberately span-free
    allow_untraced: frozenset = frozenset()

    def check_project(self, root: str,
                      files: dict[str, tuple[ast.AST, list[str]]]
                      ) -> list[Finding]:
        findings: list[Finding] = []
        for relpath, (tree, lines) in sorted(files.items()):
            if any(frag in relpath for frag in _EXEMPT_FRAGMENTS):
                continue
            funcs = _functions(tree)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                if _call_leaf(node) not in _CALL_NAMES:
                    continue
                if not node.args:
                    continue  # FPT001's finding; nothing to add here
                arg = node.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    continue  # ditto
                site = arg.value
                if site in self.allow_untraced:
                    continue
                encl = _innermost_enclosing(funcs, node)
                if encl is None:
                    findings.append(self.finding(
                        relpath, node,
                        f"failpoint site {site!r} at module level — "
                        f"fires can never land on a span", lines))
                    continue
                if not _opens_trace(encl):
                    findings.append(self.finding(
                        relpath, node,
                        f"failpoint site {site!r}: enclosing function "
                        f"{encl.name}() opens no span and emits no "
                        f"trace instant — a chaos fire here is "
                        f"invisible in the causal timeline (open a "
                        f"span or land an instant near the site)",
                        lines))
        return findings
