"""LCK001 — lock discipline in classes that own a threading lock.

Two hazards across the ~30 threaded modules:

1. a field written both under `with self._lock:` and outside it — the
   unlocked write races every locked reader (the lock is decoration);
2. a blocking call (time.sleep, socket/HTTP I/O) made while holding a
   lock — every other thread on that lock stalls behind the wire.

Scope is per-class: a class "owns" a lock when any method assigns
`self.<attr> = threading.Lock()/RLock()/Condition()`.  `__init__`,
`__new__` and `__del__` writes are constructor/teardown-time (object
not yet/no longer shared) and don't count as unlocked writes.
"""

from __future__ import annotations

import ast
from typing import Optional, Sequence

from transferia_tpu.analysis.engine import Finding, Rule

_LOCK_CTORS = {"Lock", "RLock", "Condition", "named_lock"}
_INIT_METHODS = {"__init__", "__new__", "__del__", "__init_subclass__"}
_BLOCKING_SIMPLE = {"time.sleep", "socket.create_connection",
                    "urllib.request.urlopen", "recv_exact"}
_BLOCKING_METHODS = {"recv", "recv_into", "sendall", "connect",
                     "accept", "getresponse", "urlopen"}


def _lock_attrs(cls: ast.ClassDef) -> set[str]:
    """self.<attr> names assigned a threading lock anywhere in the
    class."""
    out: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if not (isinstance(v, ast.Call) and _ctor_name(v.func)):
            continue
        for t in node.targets:
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                out.add(t.attr)
    return out


def _ctor_name(fn: ast.AST) -> bool:
    if isinstance(fn, ast.Attribute):
        return fn.attr in _LOCK_CTORS
    if isinstance(fn, ast.Name):
        return fn.id in _LOCK_CTORS
    return False


def _is_self_lock(expr: ast.AST, locks: set[str]) -> bool:
    return (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self" and expr.attr in locks)


def _call_name(node: ast.Call) -> Optional[str]:
    fn = node.func
    parts = []
    while isinstance(fn, ast.Attribute):
        parts.append(fn.attr)
        fn = fn.value
    if isinstance(fn, ast.Name):
        parts.append(fn.id)
    elif parts:
        parts.append("<expr>")
    return ".".join(reversed(parts)) if parts else None


class _MethodScan(ast.NodeVisitor):
    """Walk one method, tracking whether we're inside `with self.<lock>`.

    Nested function defs are skipped: they execute later, under whatever
    lock state holds at call time, not here.
    """

    def __init__(self, locks: set[str]):
        self.locks = locks
        self.depth = 0  # >0 while holding a lock
        # attr -> list[(node, held)] in source order
        self.writes: list[tuple[str, ast.AST, bool]] = []
        self.blocking: list[tuple[ast.Call, str]] = []

    def visit_With(self, node: ast.With) -> None:
        # items enter left-to-right: `with self._lock, connect():` runs
        # connect() while already holding the lock, but
        # `with connect(), self._lock:` does not
        entered = 0
        for item in node.items:
            self.visit(item.context_expr)
            if _is_self_lock(item.context_expr, self.locks):
                self.depth += 1
                entered += 1
        for stmt in node.body:
            self.visit(stmt)
        self.depth -= entered

    visit_AsyncWith = visit_With

    def visit_FunctionDef(self, node):  # nested defs: skip
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def _record_target(self, target: ast.AST) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._record_target(el)
            return
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self" \
                and target.attr not in self.locks:
            self.writes.append((target.attr, target, self.depth > 0))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record_target(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_target(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_target(node.target)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if self.depth > 0:
            name = _call_name(node) or ""
            leaf = name.rsplit(".", 1)[-1]
            # `self.recv()` is a call into our own class (scanned on its
            # own), but `self.sock.recv()` is real socket I/O
            own_method = name == f"self.{leaf}"
            if name in _BLOCKING_SIMPLE or (
                    "." in name and leaf in _BLOCKING_METHODS
                    and not own_method):
                self.blocking.append((node, name))
        self.generic_visit(node)


class LockDisciplineRule(Rule):
    id = "LCK001"
    severity = "error"
    description = ("field written both under and outside the owning "
                   "lock, or blocking I/O while holding a lock")

    def check_file(self, relpath: str, tree: ast.AST,
                   lines: Sequence[str]) -> list[Finding]:
        findings: list[Finding] = []
        for cls in ast.walk(tree):
            if isinstance(cls, ast.ClassDef):
                self._check_class(relpath, cls, lines, findings)
        return findings

    def _check_class(self, relpath: str, cls: ast.ClassDef,
                     lines: Sequence[str],
                     findings: list[Finding]) -> None:
        locks = _lock_attrs(cls)
        if not locks:
            return
        locked_attrs: set[str] = set()
        unlocked: list[tuple[str, ast.AST]] = []
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            scan = _MethodScan(locks)
            # `_locked` suffix = caller-holds-the-lock convention
            # (asynchronizer._flush_locked et al.): treat the whole
            # method body as a held region
            if meth.name.endswith("_locked"):
                scan.depth += 1
            for stmt in meth.body:
                scan.visit(stmt)
            init_like = meth.name in _INIT_METHODS
            for attr, node, held in scan.writes:
                if held:
                    locked_attrs.add(attr)
                elif not init_like:
                    unlocked.append((attr, node))
            for call, name in scan.blocking:
                findings.append(self.finding(
                    relpath, call,
                    f"blocking call {name}() while holding "
                    f"{cls.name}.{'/'.join(sorted(locks))} — other "
                    f"threads stall behind the I/O", lines,
                    severity="warning"))
        for attr, node in unlocked:
            if attr in locked_attrs:
                findings.append(self.finding(
                    relpath, node,
                    f"{cls.name}.{attr} is written under "
                    f"{'/'.join(sorted(locks))} elsewhere but written "
                    f"here without it — racy", lines))
