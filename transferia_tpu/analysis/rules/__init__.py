"""Built-in rule set for `trtpu check`."""

from __future__ import annotations

from transferia_tpu.analysis.engine import Rule
from transferia_tpu.analysis.rules.device_purity import DevicePurityRule
from transferia_tpu.analysis.rules.exception_hygiene import (
    ExceptionHygieneRule,
)
from transferia_tpu.analysis.rules.failpoint_contract import (
    FailpointContractRule,
)
from transferia_tpu.analysis.rules.lock_discipline import LockDisciplineRule
from transferia_tpu.analysis.rules.registry_contract import (
    RegistryContractRule,
)
from transferia_tpu.analysis.rules.resource_safety import ResourceSafetyRule
from transferia_tpu.analysis.rules.trace_contract import TraceContractRule

ALL_RULE_CLASSES: tuple[type, ...] = (
    DevicePurityRule,
    LockDisciplineRule,
    ExceptionHygieneRule,
    ResourceSafetyRule,
    RegistryContractRule,
    FailpointContractRule,
    TraceContractRule,
)


def default_rules() -> list[Rule]:
    return [cls() for cls in ALL_RULE_CLASSES]


__all__ = [
    "ALL_RULE_CLASSES",
    "default_rules",
    "DevicePurityRule",
    "LockDisciplineRule",
    "ExceptionHygieneRule",
    "FailpointContractRule",
    "ResourceSafetyRule",
    "RegistryContractRule",
    "TraceContractRule",
]
