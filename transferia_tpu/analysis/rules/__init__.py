"""Built-in rule set for `trtpu check`."""

from __future__ import annotations

from transferia_tpu.analysis.engine import Rule
from transferia_tpu.analysis.rules.device_purity import DevicePurityRule
from transferia_tpu.analysis.rules.exception_hygiene import (
    ExceptionHygieneRule,
)
from transferia_tpu.analysis.rules.failpoint_contract import (
    FailpointContractRule,
)
from transferia_tpu.analysis.rules.knob_registry import KnobRegistryRule
from transferia_tpu.analysis.rules.lock_discipline import LockDisciplineRule
from transferia_tpu.analysis.rules.lock_order import LockOrderRule
from transferia_tpu.analysis.rules.registry_contract import (
    RegistryContractRule,
)
from transferia_tpu.analysis.rules.resource_safety import ResourceSafetyRule
from transferia_tpu.analysis.rules.thread_lifecycle import (
    ThreadLifecycleRule,
)
from transferia_tpu.analysis.rules.trace_contract import TraceContractRule

ALL_RULE_CLASSES: tuple[type, ...] = (
    DevicePurityRule,
    LockDisciplineRule,
    LockOrderRule,
    ThreadLifecycleRule,
    ExceptionHygieneRule,
    ResourceSafetyRule,
    RegistryContractRule,
    FailpointContractRule,
    TraceContractRule,
    KnobRegistryRule,
)


def default_rules() -> list[Rule]:
    return [cls() for cls in ALL_RULE_CLASSES]


__all__ = [
    "ALL_RULE_CLASSES",
    "default_rules",
    "DevicePurityRule",
    "KnobRegistryRule",
    "LockDisciplineRule",
    "LockOrderRule",
    "ThreadLifecycleRule",
    "ExceptionHygieneRule",
    "FailpointContractRule",
    "ResourceSafetyRule",
    "RegistryContractRule",
    "TraceContractRule",
]
