"""THD001 — thread-lifecycle lint (leaked threads, executors, timers).

A non-daemon `threading.Thread` that is never joined keeps the
interpreter alive after `main` returns; a `ThreadPoolExecutor` that is
neither a context manager nor explicitly shut down leaks its workers;
a `threading.Timer` that is never cancelled fires into torn-down state
during shutdown.  All three have bitten long-running fleet processes.

- ``threading.Thread(...)`` must pass ``daemon=True``, or the bound
  name must see ``.join(...)`` (or ``.daemon = True``) in scope;
- ``ThreadPoolExecutor(...)`` must be entered as a context manager, or
  the bound name must see ``.shutdown(...)`` in scope;
- ``threading.Timer(...)`` must be daemonized or the bound name must
  see ``.cancel()`` in scope.

Scoping matches ownership, not the raw file: a local variable's
lifecycle must resolve inside its function (nested closures included);
a ``self._thread`` attribute's lifecycle may live in any method of the
same class (``start()`` spawns, ``join()``/``close()`` reaps).  A list
of threads built by a comprehension is credited when the loop variable
iterating that list is joined (``for t in threads: t.join()``).
Lifecycle management split across *modules* is itself the hazard this
rule exists to surface — suppress a considered exception with
``# trtpu: ignore[THD001]`` on the constructor line.
"""

from __future__ import annotations

import ast
from typing import Optional, Sequence

from transferia_tpu.analysis.engine import Finding, Rule

_THREAD_CTORS = {"Thread": "thread", "Timer": "timer"}
_EXECUTOR_CTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}
_LIFECYCLE_ATTRS = {"join", "shutdown", "cancel"}


def _leaf(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _bind_name(target: ast.AST) -> Optional[str]:
    """'t' for `t = ...`, '_pool' for `self._pool = ...`."""
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute) and \
            isinstance(target.value, ast.Name) and \
            target.value.id == "self":
        return target.attr
    return None


def _has_true_kw(call: ast.Call, kw_name: str) -> bool:
    for kw in call.keywords:
        if kw.arg == kw_name and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def _ctor_calls(value: ast.AST) -> list[ast.Call]:
    """Constructor Call nodes a binding hands its target: the direct
    call, or the element of a list/set comprehension / literal."""
    if isinstance(value, ast.Call):
        return [value]
    if isinstance(value, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
        if isinstance(value.elt, ast.Call):
            return [value.elt]
    if isinstance(value, (ast.List, ast.Tuple, ast.Set)):
        return [e for e in value.elts if isinstance(e, ast.Call)]
    return []


class _Scope:
    """Lifecycle evidence for one ownership scope (a function's locals
    or a class's `self.*` attributes)."""

    def __init__(self):
        self.lifecycle: dict[str, set[str]] = {}   # name -> attrs seen
        self.daemonized: set[str] = set()
        self.aliases: dict[str, set[str]] = {}     # loop var -> sources

    def saw(self, name: str, attr: str) -> None:
        self.lifecycle.setdefault(name, set()).add(attr)

    def has(self, names: set, attr: str) -> bool:
        expanded = set(names)
        for var, sources in self.aliases.items():
            if sources & expanded:
                expanded.add(var)
        return any(attr in self.lifecycle.get(n, ()) for n in expanded)

    def daemon(self, names: set) -> bool:
        return bool(names & self.daemonized)


def _collect(scope_nodes, scope: _Scope, self_attrs: bool) -> None:
    """Fill `scope` from the statements of one ownership scope.

    `self_attrs=True` records `self.X` evidence (class scope);
    otherwise local-Name evidence (function scope, nested functions
    included — closures commonly own the reaping)."""

    def name_of(node: ast.AST) -> Optional[str]:
        if self_attrs:
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                return node.attr
            return None
        return node.id if isinstance(node, ast.Name) else None

    for top in scope_nodes:
        for node in ast.walk(top):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _LIFECYCLE_ATTRS:
                base = name_of(node.func.value)
                if base:
                    scope.saw(base, node.func.attr)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            t.attr == "daemon":
                        base = name_of(t.value)
                        if base:
                            scope.daemonized.add(base)
            elif isinstance(node, (ast.For, ast.AsyncFor)) and \
                    not self_attrs:
                # `for t in threads:` — credit t's lifecycle calls to
                # the iterated collection
                if isinstance(node.target, ast.Name) and \
                        isinstance(node.iter, ast.Name):
                    scope.aliases.setdefault(
                        node.target.id, set()).add(node.iter.id)


class ThreadLifecycleRule(Rule):
    id = "THD001"
    severity = "error"
    description = ("thread/executor/timer created without a visible "
                   "shutdown path (daemon/join/shutdown/cancel)")

    def check_file(self, relpath: str, tree: ast.AST,
                   lines: Sequence[str]) -> list[Finding]:
        findings: list[Finding] = []
        with_ctxs: set[int] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call):
                        with_ctxs.add(id(item.context_expr))

        body = tree.body if isinstance(tree, ast.Module) else []
        module_stmts = [s for s in body if not isinstance(
            s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))]
        self._check_scope(relpath, module_stmts, False, with_ctxs,
                          lines, findings)
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_scope(relpath, [node], False, with_ctxs,
                                  lines, findings)
            elif isinstance(node, ast.ClassDef):
                # class scope owns self.* bindings across all methods
                self._check_scope(relpath, [node], True, with_ctxs,
                                  lines, findings)
                for meth in node.body:
                    if isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._check_scope(relpath, [meth], False,
                                          with_ctxs, lines, findings)
        findings.sort(key=lambda f: (f.line, f.col))
        return findings

    def _check_scope(self, relpath, scope_nodes, self_attrs: bool,
                     with_ctxs, lines, findings) -> None:
        scope = _Scope()
        _collect(scope_nodes, scope, self_attrs)
        for top in scope_nodes:
            for node in ast.walk(top):
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = node.targets if isinstance(
                        node, ast.Assign) else [node.target]
                    names = set()
                    for t in targets:
                        if self_attrs:
                            if isinstance(t, ast.Attribute) and \
                                    isinstance(t.value, ast.Name) and \
                                    t.value.id == "self":
                                names.add(t.attr)
                        elif isinstance(t, ast.Name):
                            names.add(t.id)
                    if not names or node.value is None:
                        continue
                    for call in _ctor_calls(node.value):
                        f = self._check_ctor(relpath, call, names,
                                             scope, with_ctxs, lines)
                        if f:
                            findings.append(f)
                elif not self_attrs and isinstance(node, ast.Expr) \
                        and isinstance(node.value, ast.Call):
                    call = node.value
                    inner = call
                    if isinstance(call.func, ast.Attribute) and \
                            isinstance(call.func.value, ast.Call):
                        inner = call.func.value  # Thread(...).start()
                    f = self._check_ctor(relpath, inner, set(),
                                         scope, with_ctxs, lines)
                    if f:
                        findings.append(f)

    def _check_ctor(self, relpath, call: ast.Call, names: set,
                    scope: _Scope, with_ctxs,
                    lines) -> Optional[Finding]:
        leaf = _leaf(call.func)
        if leaf in _THREAD_CTORS:
            kind = _THREAD_CTORS[leaf]
            if _has_true_kw(call, "daemon") or scope.daemon(names):
                return None
            if kind == "thread" and scope.has(names, "join"):
                return None
            if kind == "timer" and (scope.has(names, "cancel")
                                    or scope.has(names, "join")):
                return None
            what = "Thread" if kind == "thread" else "Timer"
            fix = ("pass daemon=True or join it on every exit path"
                   if kind == "thread"
                   else "pass daemon=True or cancel it on shutdown")
            bound = f" bound to {sorted(names)[0]!r}" if names else \
                " (never bound — cannot be joined)"
            return self.finding(
                relpath, call,
                f"{what}{bound} has no visible lifecycle in its "
                f"owning scope: {fix}", lines)
        if leaf in _EXECUTOR_CTORS:
            if id(call) in with_ctxs:
                return None
            if scope.has(names, "shutdown"):
                return None
            bound = f" bound to {sorted(names)[0]!r}" if names else \
                " (never bound — cannot be shut down)"
            return self.finding(
                relpath, call,
                f"{leaf}{bound} is neither a context manager nor "
                f"shut down in its owning scope; worker threads leak",
                lines)
        return None
