"""TPU001 — device purity inside jit/pallas-traced functions.

A `.item()`, `float()`, `np.asarray()` or data-dependent Python `if`
inside a `@jax.jit`/pallas function forces a device→host sync (or a
retrace per branch): the silent throughput killers the PR-1 tracer can
only observe after the fact.  This rule finds them at parse time.

Traced contexts recognized:
  - decorators: `@jax.jit`, `@jit`, `@functools.partial(jax.jit, ...)`,
    `@partial(jax.jit, ...)`, `@pl.pallas_call(...)`, `@pallas_call(...)`
  - functions/lambdas passed to a `jax.jit(...)` call anywhere in the
    same module (`fn = jax.jit(program)` — the dominant idiom in
    ops/fused.py, ops/rowhash.py, parallel/mesh.py)

`static_argnums=` / `static_argnames=` on the jit call are honored:
branching on a static argument is concrete at trace time and is NOT
flagged.  `x is None` / `x is not None` / `isinstance(x, ...)` tests
are likewise trace-time concrete.
"""

from __future__ import annotations

import ast
from typing import Optional, Sequence

from transferia_tpu.analysis.engine import Finding, Rule, dotted_name

_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_HOST_SYNC_CALLS = {
    ("jax", "device_get"): "jax.device_get() copies the value to host",
    ("np", "asarray"): "np.asarray() on a traced value syncs to host",
    ("np", "array"): "np.array() on a traced value syncs to host",
    ("numpy", "asarray"): "numpy.asarray() on a traced value syncs to host",
    ("numpy", "array"): "numpy.array() on a traced value syncs to host",
}


_dotted = dotted_name


def _is_jit_ref(node: ast.AST) -> bool:
    return _dotted(node) in ("jax.jit", "jit")


def _is_pallas_ref(node: ast.AST) -> bool:
    d = _dotted(node)
    return d is not None and (
        d.endswith("pallas_call") or ".pallas." in d or
        d.startswith("pl.") or d in ("pl", "pallas"))


class _JitCall:
    """One `jax.jit(...)` / `functools.partial(jax.jit, ...)` call with
    its static_argnums / static_argnames extracted."""

    def __init__(self, call: ast.Call):
        self.static_nums: set[int] = set()
        self.static_names: set[str] = set()
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                self.static_nums = set(_int_tuple(kw.value))
            elif kw.arg == "static_argnames":
                self.static_names = set(_str_tuple(kw.value))

    def static_params(self, fn: ast.AST) -> set[str]:
        args = getattr(fn, "args", None)
        if args is None:
            return set(self.static_names)
        names = [a.arg for a in args.posonlyargs + args.args]
        out = set(self.static_names)
        for i in self.static_nums:
            if 0 <= i < len(names):
                out.add(names[i])
        return out


def _int_tuple(node: ast.AST) -> list[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, int)]
    return []


def _str_tuple(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)]
    return []


def _decorator_jit(fn: ast.AST) -> Optional[_JitCall]:
    """A _JitCall if fn carries a jit/pallas decorator, else None."""
    for dec in getattr(fn, "decorator_list", []):
        if _is_jit_ref(dec):
            return _JitCall(ast.Call(func=dec, args=[], keywords=[]))
        if isinstance(dec, ast.Call):
            if _is_jit_ref(dec.func):
                return _JitCall(dec)
            # functools.partial(jax.jit, static_argnums=...)
            if _dotted(dec.func) in ("functools.partial", "partial") \
                    and dec.args and _is_jit_ref(dec.args[0]):
                return _JitCall(dec)
            if _is_pallas_ref(dec.func):
                return _JitCall(dec)
        if _is_pallas_ref(dec):
            return _JitCall(ast.Call(func=dec, args=[], keywords=[]))
    return None


class DevicePurityRule(Rule):
    id = "TPU001"
    severity = "error"
    description = ("host-sync call or data-dependent Python branch "
                   "inside a jit/pallas-traced function")
    # where the jitted kernels live; host-side modules branch on array
    # values legitimately (after an explicit device_get)
    paths = ("ops/", "parallel/", "transform/plugins/")

    def check_file(self, relpath: str, tree: ast.AST,
                   lines: Sequence[str]) -> list[Finding]:
        findings: list[Finding] = []
        # pass 1: module-wide map of names handed to jax.jit(...)
        jitted_names: dict[str, _JitCall] = {}
        jitted_lambdas: list[tuple[ast.Lambda, _JitCall]] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _is_jit_ref(node.func) \
                    and node.args:
                target, jc = node.args[0], _JitCall(node)
                if isinstance(target, ast.Name):
                    jitted_names[target.id] = jc
                elif isinstance(target, ast.Lambda):
                    jitted_lambdas.append((target, jc))
        # pass 2: visit every traced function body
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                jc = _decorator_jit(node) or jitted_names.get(node.name)
                if jc is not None:
                    self._check_traced(relpath, node, jc, lines, findings)
        for lam, jc in jitted_lambdas:
            self._check_traced(relpath, lam, jc, lines, findings)
        return findings

    def _check_traced(self, relpath: str, fn: ast.AST, jc: _JitCall,
                      lines: Sequence[str],
                      findings: list[Finding]) -> None:
        static = jc.static_params(fn)
        args = getattr(fn, "args", None)
        traced_params = set()
        if args is not None:
            traced_params = {a.arg for a in
                             args.posonlyargs + args.args +
                             args.kwonlyargs} - static
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                self._check_node(relpath, node, traced_params, static,
                                 lines, findings)

    def _check_node(self, relpath: str, node: ast.AST,
                    traced: set[str], static: set[str],
                    lines: Sequence[str],
                    findings: list[Finding]) -> None:
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) \
                    and fn.attr in _HOST_SYNC_METHODS and not node.args:
                findings.append(self.finding(
                    relpath, node,
                    f".{fn.attr}() forces a device->host sync inside a "
                    f"traced function", lines))
                return
            key_msg = _HOST_SYNC_CALLS.get(
                tuple((_dotted(fn) or "").rsplit(".", 1)[-2:])
                if _dotted(fn) and "." in _dotted(fn) else ("", ""))
            if key_msg:
                findings.append(self.finding(
                    relpath, node, f"{key_msg} inside a traced function",
                    lines))
                return
            if isinstance(fn, ast.Name) and fn.id in ("float", "int") \
                    and len(node.args) == 1 \
                    and not isinstance(node.args[0], ast.Constant) \
                    and _mentions(node.args[0], traced):
                findings.append(self.finding(
                    relpath, node,
                    f"{fn.id}() on a traced value concretizes it "
                    f"(device->host sync); use jnp casts instead",
                    lines))
                return
        if isinstance(node, (ast.If, ast.IfExp, ast.While)):
            test = node.test
            if _is_trace_time_test(test, static):
                return
            if _mentions(test, traced):
                kind = ("while" if isinstance(node, ast.While) else "if")
                findings.append(self.finding(
                    relpath, node,
                    f"data-dependent Python `{kind}` on a traced value "
                    f"(concretization error or silent retrace); use "
                    f"jnp.where/lax.cond or mark the argument static",
                    lines))

    def applies_to(self, relpath: str) -> bool:
        # linkprobe deliberately measures host<->device syncs
        if relpath.endswith("ops/linkprobe.py"):
            return False
        return super().applies_to(relpath)


def _mentions(node: ast.AST, names: set[str]) -> bool:
    if not names:
        return False
    return any(isinstance(n, ast.Name) and n.id in names
               for n in ast.walk(node))


def _is_trace_time_test(test: ast.AST, static: set[str]) -> bool:
    """Tests that are concrete at trace time: `x is None`,
    `isinstance(...)`, comparisons of static params, `len(...)` of a
    static, attribute tests like `x.ndim == 2` (shape metadata)."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _is_trace_time_test(test.operand, static)
    if isinstance(test, ast.BoolOp):
        return all(_is_trace_time_test(v, static) for v in test.values)
    if isinstance(test, ast.Call):
        d = _dotted(test.func) or ""
        return d in ("isinstance", "len", "callable", "hasattr")
    if isinstance(test, ast.Compare):
        if any(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return True
        operands = [test.left] + list(test.comparators)
        # shape/dtype metadata and len() are trace-time concrete
        meta = ("shape", "ndim", "dtype", "size")
        for o in operands:
            if isinstance(o, ast.Call) \
                    and (_dotted(o.func) or "") == "len":
                return True
            if isinstance(o, ast.Attribute) and o.attr in meta:
                return True
            if isinstance(o, ast.Subscript) \
                    and isinstance(o.value, ast.Attribute) \
                    and o.value.attr in meta:
                return True
    if isinstance(test, ast.Name) and test.id in static:
        return True
    if isinstance(test, ast.Attribute):
        return test.attr in ("shape", "ndim", "dtype", "size")
    return False
