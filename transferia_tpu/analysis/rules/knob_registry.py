"""KNB001 — env-knob drift between code and the README knob table.

Every tuning knob in this tree is an environment variable prefixed
``TRANSFERIA_TPU_`` or ``BENCH_``.  Three kinds of drift accumulate
silently: a module growing its own ``os.environ.get`` (bypassing the
:mod:`transferia_tpu.runtime.knobs` registry, so the knob is invisible
to runtime enumeration), a knob added to code but never documented, and
a README row outliving the knob it described.  This rule pins all
three:

- **direct read** — ``os.environ[...]`` / ``os.environ.get`` /
  ``os.getenv`` of a matching name anywhere except
  ``runtime/knobs.py`` itself (writes are fine: tests and launchers
  *set* knobs);
- **undocumented knob** — a name passed to a ``knobs.env_*`` helper
  that never appears in README.md;
- **dead doc row** — a matching name in README.md that no code reads.

Knob names are resolved statically: string literals, or module-level
``ENV_FOO = "TRANSFERIA_TPU_FOO"`` constants referenced by name.
``bench.py`` sits outside the default scan path but is a first-class
knob consumer, so the rule reads it from disk explicitly.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Optional

from transferia_tpu.analysis.engine import Finding, ProjectRule

_KNOB_RE = re.compile(r"\b(?:TRANSFERIA_TPU|BENCH)_[A-Z][A-Z0-9_]*\b")
_HELPER_NAMES = frozenset(
    {"env_raw", "env_str", "env_int", "env_float", "env_bool"})
_EXEMPT_FILES = frozenset({"transferia_tpu/runtime/knobs.py"})
_EXTRA_FILES = ("bench.py",)
_DOC_FILE = "README.md"


def _is_knob(name: object) -> bool:
    return isinstance(name, str) and bool(_KNOB_RE.fullmatch(name))


class _FileScan(ast.NodeVisitor):
    """Direct env reads + knobs.env_* uses for one module."""

    def __init__(self, relpath: str):
        self.relpath = relpath
        self.consts: dict[str, str] = {}     # ENV_FOO -> literal
        self.direct: list[tuple[str, ast.AST]] = []
        self.via_knobs: list[tuple[str, ast.AST]] = []
        self._store_subscripts: set[int] = set()

    def _resolve(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and _is_knob(node.value):
            return node.value
        if isinstance(node, ast.Name):
            return self.consts.get(node.id)
        return None

    def scan(self, tree: ast.AST) -> None:
        # module-level string constants first (forward refs are rare
        # but cheap to support)
        for node in tree.body if isinstance(tree, ast.Module) else []:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    _is_knob(node.value.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.consts[t.id] = node.value.value
        self.visit(tree)

    # -- env access patterns ------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            # os.environ.get(K) / environ.get(K)
            if fn.attr in ("get", "setdefault", "pop") and \
                    self._is_environ(fn.value):
                name = self._resolve(node.args[0]) if node.args else None
                if name and fn.attr == "get":
                    self.direct.append((name, node))
            elif fn.attr == "getenv" and \
                    isinstance(fn.value, ast.Name) and \
                    fn.value.id == "os":
                name = self._resolve(node.args[0]) if node.args else None
                if name:
                    self.direct.append((name, node))
            elif fn.attr in _HELPER_NAMES:
                self._note_helper(node)
        elif isinstance(fn, ast.Name) and fn.id in _HELPER_NAMES:
            self._note_helper(node)
        self.generic_visit(node)

    def _note_helper(self, node: ast.Call) -> None:
        # knobs.env_int("KEY", ...) puts the key first; the
        # coordinator.interface.env_float shim takes the environ
        # mapping first and the key second — accept either slot
        for arg in node.args[:2]:
            name = self._resolve(arg)
            if name:
                self.via_knobs.append((name, node))
                return

    def visit_Assign(self, node: ast.Assign) -> None:
        # environ[K] = v is a write — exempt its Subscript target
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                self._store_subscripts.add(id(t))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if id(node) not in self._store_subscripts and \
                self._is_environ(node.value):
            name = self._resolve(node.slice)
            if name:
                self.direct.append((name, node))
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                self._store_subscripts.add(id(t))
        self.generic_visit(node)

    @staticmethod
    def _is_environ(node: ast.AST) -> bool:
        if isinstance(node, ast.Attribute) and node.attr == "environ":
            return True
        return isinstance(node, ast.Name) and node.id == "environ"


class KnobRegistryRule(ProjectRule):
    id = "KNB001"
    severity = "error"
    description = ("env knob bypasses the runtime.knobs registry or "
                   "drifts from the README knob table")

    def check_project(self, root: str,
                      files: dict[str, tuple[ast.AST, list[str]]]
                      ) -> list[Finding]:
        scans: dict[str, tuple[_FileScan, list[str]]] = {}
        for rel in sorted(files):
            tree, lines = files[rel]
            sc = _FileScan(rel)
            sc.scan(tree)
            scans[rel] = (sc, lines)
        for rel in _EXTRA_FILES:
            if rel in scans:
                continue
            abspath = os.path.join(root, rel)
            try:
                with open(abspath, encoding="utf-8") as fh:
                    source = fh.read()
                tree = ast.parse(source, filename=rel)
            except (OSError, SyntaxError, UnicodeDecodeError):
                continue
            sc = _FileScan(rel)
            sc.scan(tree)
            scans[rel] = (sc, source.splitlines())

        documented = self._doc_names(root)
        findings: list[Finding] = []
        read_anywhere: set[str] = set()
        reported_undoc: set[str] = set()

        for rel in sorted(scans):
            sc, lines = scans[rel]
            for name, node in sc.direct:
                read_anywhere.add(name)
                if rel in _EXEMPT_FILES:
                    continue
                findings.append(self._at(
                    rel, node, lines,
                    f"env knob {name} read directly from the "
                    f"environment; route it through "
                    f"transferia_tpu.runtime.knobs so it registers "
                    f"and stays enumerable"))
            for name, node in sc.via_knobs:
                read_anywhere.add(name)
                if name not in documented and \
                        name not in reported_undoc:
                    reported_undoc.add(name)
                    findings.append(self._at(
                        rel, node, lines,
                        f"env knob {name} is not documented in the "
                        f"README knob table"))

        for name, line_no, text in documented.get("__rows__", []):
            if name not in read_anywhere:
                findings.append(Finding(
                    rule=self.id, severity=self.severity,
                    path=_DOC_FILE, line=line_no, col=1,
                    message=(f"README documents env knob {name} "
                             f"but no code reads it (dead doc row)"),
                    snippet=text.strip()))
        return findings

    def _at(self, rel: str, node: ast.AST, lines,
            message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        snippet = lines[line - 1].strip() if 0 < line <= len(lines) \
            else ""
        return Finding(rule=self.id, severity=self.severity, path=rel,
                       line=line, col=getattr(node, "col_offset", 0) + 1,
                       message=message, snippet=snippet)

    @staticmethod
    def _doc_names(root: str) -> dict:
        """{name} membership dict + '__rows__' -> (name, line, text)
        for the first README mention of each knob."""
        out: dict = {}
        rows: list[tuple[str, int, str]] = []
        path = os.path.join(root, _DOC_FILE)
        try:
            with open(path, encoding="utf-8") as fh:
                for i, line in enumerate(fh, start=1):
                    for m in _KNOB_RE.finditer(line):
                        name = m.group(0)
                        if name not in out:
                            out[name] = True
                            rows.append((name, i, line))
        except OSError:
            pass
        out["__rows__"] = rows
        return out
