"""EXC001 — exception hygiene.

Flags silent swallows: `except:` / `except Exception:` /
`except BaseException:` whose body does nothing (pass/.../continue)
and never logs.  A swallowed device-dispatch error is the worst case —
the pipeline keeps pumping batches into a dead mesh — so over-broad
excepts whose try-body dispatches to jax are flagged even when they
re-handle, unless they log or re-raise.

A swallow can be legitimate (best-effort close() on teardown): carry a
justifying comment AND a `# trtpu: ignore[EXC001]` pragma on the
`except` line, or log at debug.
"""

from __future__ import annotations

import ast
from typing import Sequence

from transferia_tpu.analysis.engine import Finding, Rule

_BROAD = {"Exception", "BaseException"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error",
                "exception", "critical", "log"}
_DISPATCH_MARKERS = {"jit", "device_put", "pallas_call", "block_until_ready",
                     "device_dispatch"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in _BROAD:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _BROAD:
            return True
    return False


def _is_noop_body(body: Sequence[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue  # docstring / `...`
        return False
    return True


def _logs_or_raises(body: Sequence[ast.stmt]) -> bool:
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _LOG_METHODS:
                return True
    return False


def _dispatches_to_device(try_body: Sequence[ast.stmt]) -> bool:
    for stmt in try_body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Attribute) \
                    and node.attr in _DISPATCH_MARKERS:
                return True
    return False


class ExceptionHygieneRule(Rule):
    id = "EXC001"
    severity = "warning"
    description = ("silent `except Exception: pass` (no logging), or "
                   "an over-broad except wrapping device dispatch")

    def check_file(self, relpath: str, tree: ast.AST,
                   lines: Sequence[str]) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if not _is_broad(handler):
                    continue
                if _is_noop_body(handler.body):
                    findings.append(self.finding(
                        relpath, handler,
                        "broad except silently swallows the error — "
                        "log at debug or add a justifying comment + "
                        "`# trtpu: ignore[EXC001]`", lines))
                elif _dispatches_to_device(node.body) \
                        and not _logs_or_raises(handler.body):
                    findings.append(self.finding(
                        relpath, handler,
                        "broad except wraps device dispatch without "
                        "logging or re-raising — a dead mesh keeps "
                        "accepting batches silently", lines))
        return findings
