"""Interprocedural infrastructure for `trtpu check` project rules.

The per-function rules (LCK001 et al.) see one method at a time; the
concurrency rules need the *whole-program* picture: which qualified
function acquires which lock, who calls whom while holding what, and
therefore which global lock order the tree implies.  This module builds
that picture once per run:

- **module-qualified symbol index** — every class/function keyed as
  ``package.module.Class.method``;
- **lock alias resolution** — a lock is identified by its owning
  class/field (``module.Class.attr``) or, when created through
  ``lockwatch.named_lock("name")``, by that runtime name, so static
  findings and runtime lockwatch findings agree on identity;
  ``threading.Condition(self._lock)`` aliases to the wrapped lock;
- **held-region tracking** — ``with self._lock:`` blocks, bare
  ``.acquire()``/``.release()`` pairs, and the ``*_locked``
  caller-holds convention (a ``_locked`` method's body is *not* an
  acquisition — the edge is charged to the caller that actually holds
  the lock);
- **conservative call resolution** — ``self.m()``, module-local and
  imported functions, constructors, and attribute chains typed via
  ``self.x = Class(...)`` assignments, parameter annotations, and
  return annotations (``def _op(...) -> _OpState``);
- the **lock-order graph**: edge ``A -> B`` when B is acquired
  (possibly through calls) while A is held, each edge carrying its
  first witness chain of ``file:line`` steps.

Resolution is deliberately conservative: an unresolvable callee or lock
expression contributes nothing (no guessed edges), so every edge in the
graph is backed by a concrete witness chain.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional, Sequence

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}
_NAMED_LOCK = "named_lock"
_MAX_CHAIN = 8          # witness chain length cap
_MAX_FIXPOINT = 25      # transitive-acquire iterations cap


def _mod_name(relpath: str) -> str:
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _dotted(node: ast.AST) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class LockDef:
    """One lock identity (owning class/field or lockwatch name)."""

    qual: str
    kind: str           # lock | rlock | condition
    path: str
    line: int


@dataclass
class Event:
    """One acquisition or resolvable call inside a function body."""

    kind: str           # "acquire" | "call"
    target: str         # lock qual | callee qual
    line: int
    held: tuple         # ((lock_qual, line_of_acquisition), ...)


@dataclass
class FuncInfo:
    qual: str
    path: str
    line: int
    cls: Optional[str] = None       # owning class qual
    returns: Optional[str] = None   # resolved return class qual
    events: list = field(default_factory=list)


@dataclass
class ClassInfo:
    qual: str
    path: str
    line: int
    lock_attrs: dict = field(default_factory=dict)  # attr -> lock qual
    attr_types: dict = field(default_factory=dict)  # attr -> class qual
    methods: set = field(default_factory=set)


@dataclass
class Edge:
    """Lock-order edge A -> B with its first witness chain."""

    src: str
    dst: str
    witness: tuple      # ((path, line, note), ...)


class ProjectIndex:
    """The whole-tree symbol/lock/call index (built once per run)."""

    def __init__(self):
        self.classes: dict[str, ClassInfo] = {}
        self.functions: dict[str, FuncInfo] = {}
        self.locks: dict[str, LockDef] = {}
        self.module_locks: dict[str, dict[str, str]] = {}  # mod -> name -> qual
        self.imports: dict[str, dict[str, str]] = {}       # mod -> alias -> target
        self.module_symbols: dict[str, dict[str, str]] = {}  # mod -> name -> qual
        self.edges: dict[tuple, Edge] = {}
        # func qual -> lock qual -> witness chain to the acquire
        self.acquires: dict[str, dict[str, tuple]] = {}

    # -- name resolution ------------------------------------------------
    def resolve_symbol(self, module: str, name: str) -> Optional[str]:
        """A bare name in `module` -> fully qualified symbol, when the
        target exists in the parsed tree."""
        local = self.module_symbols.get(module, {})
        if name in local:
            return local[name]
        imp = self.imports.get(module, {})
        if name in imp:
            tgt = imp[name]
            if tgt in self.classes or tgt in self.functions:
                return tgt
            # `import x.y as z` -> z maps to a module
            return tgt
        return None

    def resolve_class(self, module: str, name: str) -> Optional[str]:
        q = self.resolve_symbol(module, name)
        if q in self.classes:
            return q
        # dotted: mod_alias.Class
        if "." in name:
            head, _, rest = name.partition(".")
            base = self.imports.get(module, {}).get(head)
            if base:
                cand = f"{base}.{rest}"
                if cand in self.classes:
                    return cand
        return None

    def resolve_annotation(self, module: str,
                           ann: Optional[ast.AST]) -> Optional[str]:
        """Class qual from a return/param annotation; unwraps
        Optional[...] and string annotations."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.Subscript):
            base = _dotted(ann.slice) if not isinstance(
                ann.slice, ast.Tuple) else None
            if base:
                return self.resolve_class(module, base)
            return None
        name = _dotted(ann)
        return self.resolve_class(module, name) if name else None


def _lock_ctor(call: ast.Call) -> Optional[tuple[str, Optional[str],
                                                 Optional[ast.AST]]]:
    """(kind, explicit_name, alias_expr) when `call` constructs a lock.

    alias_expr is the wrapped lock for `threading.Condition(other)`.
    """
    fn = call.func
    leaf = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None)
    if leaf in _LOCK_CTORS:
        alias = call.args[0] if (leaf == "Condition" and call.args) \
            else None
        return _LOCK_CTORS[leaf], None, alias
    if leaf == _NAMED_LOCK:
        name = None
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            name = call.args[0].value
        kind = "lock"
        for kw in call.keywords:
            if kw.arg == "kind" and isinstance(kw.value, ast.Constant):
                kind = str(kw.value.value)
        return kind, name, None
    return None


class _BodyScan:
    """Walk one function body tracking the held-lock stack and
    recording acquire/call events (engine of the lock-order graph)."""

    def __init__(self, index: ProjectIndex, module: str, path: str,
                 func: FuncInfo, cls: Optional[ClassInfo]):
        self.ix = index
        self.module = module
        self.path = path
        self.func = func
        self.cls = cls
        self.local_types: dict[str, str] = {}
        self.held: list[tuple[str, int]] = []

    # -- typing ---------------------------------------------------------
    def _type_of(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Name):
            if expr.id == "self":
                return self.cls.qual if self.cls else None
            return self.local_types.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self._type_of(expr.value)
            if base and base in self.ix.classes:
                return self.ix.classes[base].attr_types.get(expr.attr)
            return None
        if isinstance(expr, ast.Call):
            callee = self._callee(expr)
            if callee is None:
                return None
            if callee in self.ix.classes:
                return callee
            fi = self.ix.functions.get(callee)
            return fi.returns if fi else None
        return None

    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Name):
            q = self.ix.module_locks.get(self.module, {}).get(expr.id)
            if q:
                return q
            tgt = self.ix.imports.get(self.module, {}).get(expr.id)
            if tgt and tgt in self.ix.locks:
                return tgt
            return None
        if isinstance(expr, ast.Attribute):
            base = self._type_of(expr.value)
            if base and base in self.ix.classes:
                return self.ix.classes[base].lock_attrs.get(expr.attr)
        return None

    def _callee(self, call: ast.Call) -> Optional[str]:
        fn = call.func
        if isinstance(fn, ast.Name):
            q = self.ix.resolve_symbol(self.module, fn.id)
            if q and (q in self.ix.functions or q in self.ix.classes):
                return q
            return None
        if isinstance(fn, ast.Attribute):
            # self.m() / typed_expr.m() / module_alias.f()
            base_t = self._type_of(fn.value)
            if base_t and base_t in self.ix.classes:
                ci = self.ix.classes[base_t]
                if fn.attr in ci.methods:
                    return f"{base_t}.{fn.attr}"
                return None
            name = _dotted(fn)
            if name:
                head, _, rest = name.partition(".")
                base = self.ix.imports.get(self.module, {}).get(head)
                if base and rest:
                    cand = f"{base}.{rest}"
                    if cand in self.ix.functions or \
                            cand in self.ix.classes:
                        return cand
        return None

    # -- events ---------------------------------------------------------
    def _emit_acquire(self, qual: str, line: int) -> None:
        self.func.events.append(Event("acquire", qual, line,
                                      tuple(self.held)))

    def _emit_call(self, qual: str, line: int) -> None:
        if qual in self.ix.classes:
            ctor = f"{qual}.__init__"
            if ctor not in self.ix.functions:
                return
            qual = ctor
        self.func.events.append(Event("call", qual, line,
                                      tuple(self.held)))

    def _scan_expr(self, expr: Optional[ast.AST]) -> None:
        """Record calls (and bare acquire/release) inside an
        expression; nested defs/lambdas execute later — skipped."""
        if expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                # ast.walk is pre-order; prune by ignoring their calls
                for sub in ast.walk(node):
                    sub._cg_skip = True  # type: ignore[attr-defined]
                continue
            if not isinstance(node, ast.Call) or \
                    getattr(node, "_cg_skip", False):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in ("acquire", "release"):
                lq = self._lock_of(fn.value)
                if lq:
                    if fn.attr == "acquire":
                        self._emit_acquire(lq, node.lineno)
                        self.held.append((lq, node.lineno))
                    else:
                        for i in range(len(self.held) - 1, -1, -1):
                            if self.held[i][0] == lq:
                                del self.held[i]
                                break
                    continue
            callee = self._callee(node)
            if callee:
                self._emit_call(callee, node.lineno)

    def _bind_target(self, target: ast.AST, value: ast.AST) -> None:
        if isinstance(target, ast.Name):
            t = self._type_of(value)
            if t:
                self.local_types[target.id] = t
            else:
                self.local_types.pop(target.id, None)

    def scan(self, stmts: Sequence[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                entered = 0
                for item in stmt.items:
                    lq = self._lock_of(item.context_expr)
                    if lq is None and isinstance(item.context_expr,
                                                 ast.Call):
                        # with self._queue(name).lock: ... is an
                        # Attribute; with pool.guard(): a call we
                        # can't type — scan for nested calls either way
                        pass
                    if lq:
                        self._emit_acquire(lq, item.context_expr.lineno)
                        self.held.append((lq, item.context_expr.lineno))
                        entered += 1
                    else:
                        self._scan_expr(item.context_expr)
                self.scan(stmt.body)
                for _ in range(entered):
                    self.held.pop()
                continue
            if isinstance(stmt, ast.Assign):
                self._scan_expr(stmt.value)
                for t in stmt.targets:
                    self._bind_target(t, stmt.value)
                continue
            if isinstance(stmt, ast.AnnAssign):
                self._scan_expr(stmt.value)
                if stmt.value is not None:
                    self._bind_target(stmt.target, stmt.value)
                elif isinstance(stmt.target, ast.Name):
                    ann_t = self.ix.resolve_annotation(
                        self.module, stmt.annotation)
                    if ann_t:
                        self.local_types[stmt.target.id] = ann_t
                continue
            if isinstance(stmt, ast.AugAssign):
                self._scan_expr(stmt.value)
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                self._scan_expr(stmt.test)
                self.scan(stmt.body)
                self.scan(stmt.orelse)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_expr(stmt.iter)
                self.scan(stmt.body)
                self.scan(stmt.orelse)
                continue
            if isinstance(stmt, ast.Try):
                self.scan(stmt.body)
                for h in stmt.handlers:
                    self.scan(h.body)
                self.scan(stmt.orelse)
                self.scan(stmt.finalbody)
                continue
            # Expr / Return / Raise / Assert / Delete / ...
            for val in ast.iter_child_nodes(stmt):
                if isinstance(val, ast.expr):
                    self._scan_expr(val)


def build_index(files: dict[str, tuple[ast.AST, list[str]]]
                ) -> ProjectIndex:
    """Build the whole-tree index from the engine's parsed-file map."""
    ix = ProjectIndex()
    mods = {rel: _mod_name(rel) for rel in files}

    # pass A: symbols, imports, lock definitions, attribute types -------
    for rel in sorted(files):
        tree, _lines = files[rel]
        mod = mods[rel]
        imp: dict[str, str] = {}
        syms: dict[str, str] = {}
        mlocks: dict[str, str] = {}
        ix.imports[mod] = imp
        ix.module_symbols[mod] = syms
        ix.module_locks[mod] = mlocks
        for node in tree.body if isinstance(tree, ast.Module) else []:
            if isinstance(node, ast.Import):
                for a in node.names:
                    imp[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    imp[a.asname or a.name] = f"{node.module}.{a.name}"
            elif isinstance(node, ast.ClassDef):
                cq = f"{mod}.{node.name}"
                syms[node.name] = cq
                ci = ClassInfo(qual=cq, path=rel, line=node.lineno)
                ci.methods = {m.name for m in node.body if isinstance(
                    m, (ast.FunctionDef, ast.AsyncFunctionDef))}
                ix.classes[cq] = ci
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                fq = f"{mod}.{node.name}"
                syms[node.name] = fq
                ix.functions[fq] = FuncInfo(qual=fq, path=rel,
                                            line=node.lineno)
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                got = _lock_ctor(node.value)
                if got is None:
                    continue
                kind, name, _alias = got
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        q = name or f"{mod}.{t.id}"
                        mlocks[t.id] = q
                        ix.locks.setdefault(q, LockDef(
                            q, kind, rel, node.lineno))

    # pass A2: class lock attrs + attr types (needs symbol table) -------
    for rel in sorted(files):
        tree, _lines = files[rel]
        mod = mods[rel]
        for node in tree.body if isinstance(tree, ast.Module) else []:
            if not isinstance(node, ast.ClassDef):
                continue
            ci = ix.classes[f"{mod}.{node.name}"]
            pending_alias: list[tuple[str, ast.AST, int]] = []
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign) or \
                        not isinstance(sub.value, ast.Call):
                    continue
                tgts = [t for t in sub.targets
                        if isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"]
                if not tgts:
                    continue
                got = _lock_ctor(sub.value)
                if got is not None:
                    kind, name, alias = got
                    for t in tgts:
                        if alias is not None:
                            pending_alias.append((t.attr, alias,
                                                  sub.lineno))
                            continue
                        q = name or f"{ci.qual}.{t.attr}"
                        ci.lock_attrs[t.attr] = q
                        ix.locks.setdefault(q, LockDef(
                            q, kind, rel, sub.lineno))
                    continue
                # self.x = ClassName(...) attribute typing
                fn_name = _dotted(sub.value.func)
                if fn_name:
                    cq = ix.resolve_class(mod, fn_name)
                    if cq:
                        for t in tgts:
                            ci.attr_types[t.attr] = cq
            # Condition(self._lock) aliases resolve after lock attrs
            for attr, alias, lineno in pending_alias:
                if isinstance(alias, ast.Attribute) and \
                        isinstance(alias.value, ast.Name) and \
                        alias.value.id == "self" and \
                        alias.attr in ci.lock_attrs:
                    ci.lock_attrs[attr] = ci.lock_attrs[alias.attr]
                else:
                    q = f"{ci.qual}.{attr}"
                    ci.lock_attrs[attr] = q
                    ix.locks.setdefault(q, LockDef(
                        q, "condition", rel, lineno))

    # pass B: function events -------------------------------------------
    for rel in sorted(files):
        tree, _lines = files[rel]
        mod = mods[rel]
        for node in tree.body if isinstance(tree, ast.Module) else []:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _scan_function(ix, mod, rel, node, None)
            elif isinstance(node, ast.ClassDef):
                ci = ix.classes[f"{mod}.{node.name}"]
                for meth in node.body:
                    if isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        _scan_function(ix, mod, rel, meth, ci)

    _propagate_acquires(ix)
    _build_edges(ix)
    return ix


def _scan_function(ix: ProjectIndex, mod: str, rel: str,
                   node, ci: Optional[ClassInfo]) -> None:
    qual = f"{ci.qual}.{node.name}" if ci else f"{mod}.{node.name}"
    fi = ix.functions.get(qual)
    if fi is None:
        fi = ix.functions[qual] = FuncInfo(qual=qual, path=rel,
                                           line=node.lineno)
    fi.cls = ci.qual if ci else None
    fi.returns = ix.resolve_annotation(mod, node.returns)
    scan = _BodyScan(ix, mod, rel, fi, ci)
    for arg in list(node.args.args) + list(node.args.kwonlyargs):
        t = ix.resolve_annotation(mod, arg.annotation)
        if t:
            scan.local_types[arg.arg] = t
    scan.scan(node.body)


def _propagate_acquires(ix: ProjectIndex) -> None:
    """acq[f] = locks f may acquire transitively, with one witness
    chain each (first/shortest found, deterministic)."""
    acq: dict[str, dict[str, tuple]] = {}
    for q in sorted(ix.functions):
        fi = ix.functions[q]
        mine: dict[str, tuple] = {}
        for ev in fi.events:
            if ev.kind == "acquire" and ev.target not in mine:
                mine[ev.target] = ((fi.path, ev.line,
                                    f"acquires {ev.target}"),)
        acq[q] = mine
    for _ in range(_MAX_FIXPOINT):
        changed = False
        for q in sorted(ix.functions):
            fi = ix.functions[q]
            mine = acq[q]
            for ev in fi.events:
                if ev.kind != "call":
                    continue
                for lock, chain in sorted(acq.get(ev.target,
                                                  {}).items()):
                    if lock in mine or len(chain) >= _MAX_CHAIN:
                        continue
                    mine[lock] = ((fi.path, ev.line,
                                   f"calls {ev.target}"),) + chain
                    changed = True
        if not changed:
            break
    ix.acquires = acq


def _build_edges(ix: ProjectIndex) -> None:
    for q in sorted(ix.functions):
        fi = ix.functions[q]
        for ev in fi.events:
            if not ev.held:
                continue
            if ev.kind == "acquire":
                for (held_q, held_line) in ev.held:
                    if held_q == ev.target:
                        continue
                    key = (held_q, ev.target)
                    if key not in ix.edges:
                        ix.edges[key] = Edge(held_q, ev.target, (
                            (fi.path, held_line, f"holds {held_q}"),
                            (fi.path, ev.line,
                             f"acquires {ev.target}")))
            else:
                for lock, chain in sorted(
                        ix.acquires.get(ev.target, {}).items()):
                    for (held_q, held_line) in ev.held:
                        if held_q == lock:
                            continue
                        key = (held_q, lock)
                        if key not in ix.edges:
                            ix.edges[key] = Edge(held_q, lock, (
                                (fi.path, held_line,
                                 f"holds {held_q}"),
                                (fi.path, ev.line,
                                 f"calls {ev.target}")) + chain)


def find_cycles(ix: ProjectIndex) -> list[list[str]]:
    """Deterministic list of lock-order cycles: every 2-cycle, plus one
    shortest representative cycle for any larger SCC not already
    covered by a 2-cycle."""
    adj: dict[str, set[str]] = {}
    for (a, b) in ix.edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    cycles: list[list[str]] = []
    seen_pairs: set[frozenset] = set()
    for a in sorted(adj):
        for b in sorted(adj[a]):
            if a < b and a in adj.get(b, ()):  # 2-cycle
                cycles.append([a, b])
                seen_pairs.add(frozenset((a, b)))
    for scc in _sccs(adj):
        if len(scc) < 2:
            continue
        if any(frozenset((a, b)) in seen_pairs
               for a in scc for b in scc if a < b):
            continue
        cyc = _shortest_cycle(adj, sorted(scc)[0], scc)
        if cyc:
            cycles.append(cyc)
    return cycles


def _sccs(adj: dict[str, set[str]]) -> list[set[str]]:
    """Tarjan (iterative), deterministic over sorted nodes."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on: set[str] = set()
    stack: list[str] = []
    out: list[set[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(adj.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = set()
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.add(w)
                    if w == node:
                        break
                out.append(comp)

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)
    return out


def _shortest_cycle(adj: dict[str, set[str]], start: str,
                    scc: set[str]) -> Optional[list[str]]:
    """BFS within the SCC from `start` back to itself."""
    frontier = [(start, [start])]
    visited = {start}
    while frontier:
        nxt = []
        for node, path in frontier:
            for w in sorted(adj.get(node, ())):
                if w == start:
                    return path
                if w in scc and w not in visited:
                    visited.add(w)
                    nxt.append((w, path + [w]))
        frontier = nxt
    return None


def format_witness(edge: Edge) -> str:
    """`file:line -> file:line` chain for one edge."""
    return " -> ".join(f"{p}:{ln}" for (p, ln, _note) in edge.witness)
