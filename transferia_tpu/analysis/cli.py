"""`trtpu check` implementation (also installed as `trtpu-check`).

Exit codes:
  0 — no new (non-baselined) findings
  1 — new findings and --strict
  2 — unusable invocation (bad path, bad rule id)

Without --strict the command always exits 0 so it can run as an
informational step; CI uses `trtpu check --strict` as the fast
pre-test gate (no jax compile, sub-second on this tree).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from transferia_tpu.analysis import baseline as baseline_mod
from transferia_tpu.analysis.engine import format_human, run_rules
from transferia_tpu.analysis.rules import default_rules


def repo_root() -> str:
    """The directory holding the `transferia_tpu` package (baseline and
    reported paths are relative to it)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def add_check_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("paths", nargs="*", default=[],
                   help="files/dirs to analyze "
                        "(default: the transferia_tpu/ tree)")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on any new (non-baselined) finding")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: "
                        f"{baseline_mod.DEFAULT_BASELINE} at the repo "
                        f"root; 'none' disables)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to accept all current "
                        "findings")
    p.add_argument("--rules", default="",
                   help="comma-separated rule ids to run "
                        "(default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule set and exit")


def run_check(args) -> int:
    rules = default_rules()
    if args.list_rules:
        for r in rules:
            scope = " [" + ", ".join(r.paths) + "]" if r.paths else ""
            print(f"{r.id} ({r.severity}){scope}: {r.description}")
        return 0
    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")
                  if r.strip()}
        known = {r.id for r in rules}
        unknown = wanted - known
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}; "
                  f"known: {', '.join(sorted(known))}", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in wanted]

    root = repo_root()
    paths = args.paths or ["transferia_tpu"]
    for p in paths:
        abs_p = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(abs_p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2
    result = run_rules(paths, rules, root=root)

    baseline_path: Optional[str] = None
    if args.baseline != "none":
        baseline_path = args.baseline or os.path.join(
            root, baseline_mod.DEFAULT_BASELINE)
    if args.update_baseline:
        if baseline_path is None:
            print("--update-baseline requires a baseline file",
                  file=sys.stderr)
            return 2
        if args.paths or args.rules:
            # a narrowed run only sees a subset of findings; saving it
            # would silently drop every other tree's baselined entry
            print("--update-baseline requires a full run (no explicit "
                  "paths or --rules)", file=sys.stderr)
            return 2
        n = baseline_mod.save(baseline_path, result.findings)
        print(f"baseline: {n} finding(s) -> {baseline_path}")
        return 0
    known = baseline_mod.load(baseline_path) if baseline_path else set()
    new, old = baseline_mod.split(result.findings, known)

    if args.as_json:
        print(json.dumps({
            "files_checked": result.files_checked,
            "new": [f.to_json() for f in new],
            "baselined": [f.to_json() for f in old],
            "parse_errors": [f.to_json() for f in result.parse_errors],
        }, indent=1))
    else:
        print(format_human(result, new, len(old)))
        dead = baseline_mod.stale(result.findings, known)
        if dead:
            print(f"note: {len(dead)} baseline entr"
                  f"{'y is' if len(dead) == 1 else 'ies are'} stale "
                  f"(fixed findings) — rerun with --update-baseline")
    failed = bool(new or result.parse_errors)
    return 1 if (args.strict and failed) else 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="trtpu-check",
        description="framework-aware static analysis for the "
                    "transferia-tpu tree")
    add_check_args(p)
    return run_check(p.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
