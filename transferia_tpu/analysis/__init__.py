"""Framework-aware static analysis (`trtpu check`).

An AST-based lint engine purpose-built for this codebase's hazard
classes: device purity inside jit/pallas kernels (TPU001), lock
discipline in threaded modules (LCK001), exception hygiene (EXC001),
socket/file resource safety (NET001), and the plugin-registry contract
(REG001).  See ARCHITECTURE.md "Static analysis" for the suppression
syntax and baseline workflow.
"""

from transferia_tpu.analysis.engine import (
    CheckResult,
    Finding,
    ProjectRule,
    Rule,
    Suppressions,
    run_rules,
)
from transferia_tpu.analysis.rules import ALL_RULE_CLASSES, default_rules

__all__ = [
    "CheckResult",
    "Finding",
    "ProjectRule",
    "Rule",
    "Suppressions",
    "run_rules",
    "ALL_RULE_CLASSES",
    "default_rules",
]
