"""Rule engine for `trtpu check` — framework-aware static analysis.

The standard toolchain (flake8/mypy) can't see the three hazard classes
this engine exists for: host syncs hidden inside jit/pallas kernels,
shared state mutated across the lock-using threaded modules, and the
compile-time plugin registry whose contract otherwise breaks only at
transfer time.  Rules are small AST visitors (plus one whole-project
rule that imports the real registries); the engine owns file walking,
`# trtpu: ignore[...]` suppressions, the committed baseline, and output
formatting so pre-existing findings never block CI.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

SEVERITIES = ("error", "warning")


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.jit' for an Attribute/Name chain, None when the chain roots
    in anything else (a call result, a subscript) — shared by the rules
    so chain-handling fixes land everywhere at once."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass(frozen=True)
class Finding:
    """One diagnostic: rule id + location + message.

    `snippet` is the stripped source line — it feeds the baseline
    fingerprint so findings survive unrelated line insertions above
    them (fingerprints must not embed absolute line numbers).
    """

    rule: str
    severity: str
    path: str  # repo-relative, posix separators
    line: int
    col: int
    message: str
    snippet: str = ""

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} [{self.severity}] {self.message}")

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class Rule:
    """Per-file AST rule.  Subclasses set `id`, `severity`,
    `description` and implement `check_file`.

    `paths` (optional tuple of path fragments) scopes the rule to files
    whose repo-relative path contains one of the fragments — e.g.
    device-purity only makes sense where jitted kernels live.
    """

    id: str = ""
    severity: str = "warning"
    description: str = ""
    paths: Optional[tuple[str, ...]] = None

    def applies_to(self, relpath: str) -> bool:
        if self.paths is None:
            return True
        return any(frag in relpath for frag in self.paths)

    def check_file(self, relpath: str, tree: ast.AST,
                   lines: Sequence[str]) -> list[Finding]:
        raise NotImplementedError

    def finding(self, relpath: str, node: ast.AST, message: str,
                lines: Sequence[str],
                severity: Optional[str] = None) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0) + 1
        snippet = lines[line - 1].strip() if 0 < line <= len(lines) else ""
        return Finding(rule=self.id, severity=severity or self.severity,
                       path=relpath, line=line, col=col,
                       message=message, snippet=snippet)


class ProjectRule(Rule):
    """Whole-tree rule (sees every parsed file at once; may import the
    package under analysis, e.g. to load the real plugin registries)."""

    def check_file(self, relpath, tree, lines):  # pragma: no cover
        return []

    def check_project(self, root: str,
                      files: dict[str, tuple[ast.AST, list[str]]]
                      ) -> list[Finding]:
        raise NotImplementedError


# -- suppressions -----------------------------------------------------------

_IGNORE_RE = re.compile(
    r"#\s*trtpu:\s*ignore(?P<file>-file)?"
    r"(?:\[(?P<rules>[A-Za-z0-9_,\s]+)\])?")


@dataclass
class Suppressions:
    """`# trtpu: ignore[RULE]` pragmas for one file.

    - on a line: suppresses matching findings reported on that line
      (use the line carrying the flagged expression for multi-line
      statements);
    - `# trtpu: ignore-file[RULE]` anywhere at module level: suppresses
      the rule for the whole file;
    - bare `# trtpu: ignore` (no rule list) suppresses every rule.
    """

    by_line: dict[int, frozenset] = field(default_factory=dict)
    whole_file: frozenset = frozenset()

    ALL = frozenset(["*"])

    @classmethod
    def scan(cls, source: str) -> "Suppressions":
        by_line: dict[int, frozenset] = {}
        whole: set[str] = set()
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(source).readline)
            comments = [(t.start[0], t.string) for t in tokens
                        if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # fall back to a line scan; a comment inside a string may
            # produce a stray suppression, which is harmless
            comments = [(i + 1, line) for i, line
                        in enumerate(source.splitlines()) if "#" in line]
        for lineno, text in comments:
            m = _IGNORE_RE.search(text)
            if not m:
                continue
            rules = (frozenset(r.strip().upper() for r in
                               m.group("rules").split(",") if r.strip())
                     if m.group("rules") else cls.ALL)
            if m.group("file"):
                whole |= rules
            else:
                by_line[lineno] = by_line.get(lineno, frozenset()) | rules
        return cls(by_line=by_line, whole_file=frozenset(whole))

    def suppressed(self, finding: Finding) -> bool:
        for rules in (self.whole_file,
                      self.by_line.get(finding.line, frozenset())):
            if "*" in rules or finding.rule.upper() in rules:
                return True
        return False


# -- engine -----------------------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules"}


def iter_python_files(paths: Sequence[str], root: str) -> list[str]:
    """Expand files/dirs into a sorted list of repo-relative .py paths."""
    out: set[str] = set()
    for p in paths:
        abs_p = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(abs_p) and abs_p.endswith(".py"):
            out.add(os.path.relpath(abs_p, root))
            continue
        for dirpath, dirnames, filenames in os.walk(abs_p):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for fn in filenames:
                if fn.endswith(".py"):
                    out.add(os.path.relpath(
                        os.path.join(dirpath, fn), root))
    return sorted(p.replace(os.sep, "/") for p in out)


@dataclass
class CheckResult:
    findings: list[Finding]
    parse_errors: list[Finding]
    files_checked: int

    @property
    def all(self) -> list[Finding]:
        return self.parse_errors + self.findings


def run_rules(paths: Sequence[str], rules: Sequence[Rule],
              root: str = ".") -> CheckResult:
    """Parse every file once, run each applicable rule, apply pragmas."""
    root = os.path.abspath(root)
    relpaths = iter_python_files(paths, root)
    findings: list[Finding] = []
    parse_errors: list[Finding] = []
    parsed: dict[str, tuple[ast.AST, list[str]]] = {}
    supps: dict[str, Suppressions] = {}
    for rel in relpaths:
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=rel)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            parse_errors.append(Finding(
                rule="PARSE", severity="error", path=rel,
                line=getattr(e, "lineno", None) or 1, col=1,
                message=f"cannot analyze: {e}"))
            continue
        parsed[rel] = (tree, source.splitlines())
        supps[rel] = Suppressions.scan(source)

    file_rules = [r for r in rules if not isinstance(r, ProjectRule)]
    project_rules = [r for r in rules if isinstance(r, ProjectRule)]
    for rel, (tree, lines) in parsed.items():
        for rule in file_rules:
            if rule.applies_to(rel):
                findings.extend(rule.check_file(rel, tree, lines))
    for rule in project_rules:
        findings.extend(rule.check_project(root, parsed))
    findings = [f for f in findings
                if not supps.get(f.path, Suppressions()).suppressed(f)]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return CheckResult(findings=findings, parse_errors=parse_errors,
                       files_checked=len(parsed))


def format_human(result: CheckResult, new: Iterable[Finding],
                 baselined_count: int) -> str:
    new = list(new)
    out = [f.format() for f in result.parse_errors]
    out += [f.format() for f in new]
    errors = sum(1 for f in new if f.severity == "error")
    out.append(
        f"checked {result.files_checked} files: "
        f"{len(new)} new finding(s) ({errors} error(s)), "
        f"{baselined_count} baselined")
    return "\n".join(out)
