"""Committed-baseline support for `trtpu check`.

Pre-existing findings are recorded (fingerprinted) in a JSON file so
`--strict` only fails on NEW findings — the same ratchet pattern as
mypy/ruff baselines.  Fingerprints hash (path, rule, source-line text,
occurrence index) rather than line numbers, so a finding stays matched
when unrelated code shifts it up or down the file.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Iterable, Sequence

from transferia_tpu.analysis.engine import Finding

DEFAULT_BASELINE = ".trtpu-baseline.json"
_VERSION = 1


def fingerprints(findings: Sequence[Finding]) -> list[str]:
    """Stable ids, parallel to `findings` (sorted order expected).

    The occurrence counter disambiguates identical snippets (two
    `except Exception: pass` in one file) without pinning to line
    numbers.
    """
    seen: dict[tuple[str, str, str], int] = {}
    out = []
    for f in findings:
        key = (f.path, f.rule, f.snippet)
        n = seen.get(key, 0)
        seen[key] = n + 1
        digest = hashlib.sha1(
            f"{f.path}|{f.rule}|{f.snippet}|{n}".encode()).hexdigest()[:16]
        out.append(digest)
    return out


def load(path: str) -> set[str]:
    if not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return set(data.get("findings", {}))


def save(path: str, findings: Sequence[Finding]) -> int:
    """Write the baseline for `findings`; returns the entry count."""
    entries = {}
    for fp, f in zip(fingerprints(findings), findings):
        entries[fp] = {"rule": f.rule, "path": f.path,
                       "message": f.message, "snippet": f.snippet}
    payload = {"version": _VERSION,
               "findings": dict(sorted(entries.items()))}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=False)
        fh.write("\n")
    return len(entries)


def split(findings: Sequence[Finding], baseline: set[str]
          ) -> tuple[list[Finding], list[Finding]]:
    """-> (new, baselined)."""
    new, old = [], []
    for fp, f in zip(fingerprints(findings), findings):
        (old if fp in baseline else new).append(f)
    return new, old


def stale(findings: Sequence[Finding], baseline: set[str]) -> set[str]:
    """Baseline entries no longer produced (candidates for cleanup)."""
    return baseline - set(fingerprints(findings))
