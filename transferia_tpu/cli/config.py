"""transfer.yaml parsing (reference: cmd/trcli/config/config.go:19-133).

Shape (config/model.go:38-54):

    id: my-transfer
    type: SNAPSHOT_ONLY            # | INCREMENT_ONLY | SNAPSHOT_AND_INCREMENT
    src:
      type: sample                 # provider name
      params: { ... }              # provider endpoint params
    dst:
      type: stdout
      params: { ... }
    transformation:
      transformers:
        - mask_field: {columns: [email], salt: "${MASK_SALT}"}
    data_objects: ["ns.table", ...]
    regular_snapshot: {enabled: true, cron: "0 3 * * *", incremental: [...]}
    runtime: {job_count: 1, process_count: 4}
    type_system_version: 1

Environment substitution `${VAR}` / `${VAR:default}` in all string scalars
(config.go:112-133); unknown top-level keys are rejected (strict
mapstructure parity, config.go:80-110).
"""

from __future__ import annotations

import os
import re
from typing import Any

import yaml

from transferia_tpu.models import Transfer, TransferType
from transferia_tpu.models.endpoint import endpoint_from_dict
from transferia_tpu.models.transfer import (
    DataObjects,
    IncrementalTableCfg,
    RegularSnapshot,
    Runtime,
    ShardingUploadParams,
)
from transferia_tpu.typesystem.fallbacks import LATEST_VERSION

_ENV_RE = re.compile(r"\$\{([A-Za-z_][A-Za-z0-9_]*)(?::([^}]*))?\}")

_KNOWN_KEYS = {
    "id", "type", "src", "dst", "transformation", "data_objects",
    "regular_snapshot", "runtime", "type_system_version", "labels",
    "validation",
}


class ConfigError(ValueError):
    pass


def _substitute_env(value: Any) -> Any:
    if isinstance(value, str):
        def repl(m):
            var, default = m.group(1), m.group(2)
            v = os.environ.get(var)
            if v is None:
                if default is not None:
                    return default
                raise ConfigError(f"environment variable {var} is not set")
            return v

        return _ENV_RE.sub(repl, value)
    if isinstance(value, dict):
        return {k: _substitute_env(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_substitute_env(v) for v in value]
    return value


def parse_transfer_yaml(text: str) -> Transfer:
    raw = yaml.safe_load(text)
    if not isinstance(raw, dict):
        raise ConfigError("transfer config must be a YAML mapping")
    raw = _substitute_env(raw)
    unknown = set(raw) - _KNOWN_KEYS
    if unknown:
        raise ConfigError(
            f"unknown config keys: {sorted(unknown)}; "
            f"known: {sorted(_KNOWN_KEYS)}"
        )
    for side in ("src", "dst"):
        if side not in raw:
            raise ConfigError(f"missing required key {side!r}")
        ep = raw[side]
        if not isinstance(ep, dict) or "type" not in ep:
            raise ConfigError(f"{side} must be a mapping with a 'type' key")
    try:
        ttype = TransferType(raw.get("type", "SNAPSHOT_ONLY"))
    except ValueError as e:
        raise ConfigError(
            f"bad transfer type {raw.get('type')!r}; valid: "
            f"{[t.value for t in TransferType]}"
        ) from e

    def endpoint(side: str, role: str):
        ep = raw[side]
        params = dict(ep.get("params") or {})
        try:
            return endpoint_from_dict(params, provider=ep["type"], role=role)
        except KeyError as e:
            raise ConfigError(str(e)) from e

    # providers self-register endpoint classes on import
    from transferia_tpu.providers import load_builtin_providers

    load_builtin_providers()

    rt = raw.get("runtime") or {}
    rs = raw.get("regular_snapshot") or {}
    return Transfer(
        id=str(raw.get("id", "transfer")),
        type=ttype,
        src=endpoint("src", "source"),
        dst=endpoint("dst", "target"),
        transformation=raw.get("transformation"),
        data_objects=DataObjects(list(raw.get("data_objects") or [])),
        regular_snapshot=RegularSnapshot(
            enabled=bool(rs.get("enabled", False)),
            cron=rs.get("cron", ""),
            incremental=[
                IncrementalTableCfg(**i) for i in rs.get("incremental", [])
            ],
        ),
        runtime=Runtime(
            current_job=int(rt.get("current_job", 0)),
            sharding=ShardingUploadParams(
                job_count=int(rt.get("job_count", 1)),
                process_count=int(rt.get("process_count", 4)),
            ),
            replication_workers=int(rt.get("replication_workers", 1)),
        ),
        type_system_version=int(
            raw.get("type_system_version", LATEST_VERSION)
        ),
        labels=dict(raw.get("labels") or {}),
        validation=raw.get("validation"),
    )


def load_transfer(path: str) -> Transfer:
    with open(path) as fh:
        return parse_transfer_yaml(fh.read())
