"""trtpu: the command-line interface.

Reference parity: cmd/trcli/main.go:37-160 — subcommands activate /
replicate / upload / check / validate / describe, global flags for the
coordinator (memory | filestore), worker sharding indices, log level, and a
Prometheus metrics port.  The memory coordinator refuses job_count > 1
(main.go:118-121) since parts can't be shared across processes in memory.
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys
import threading

from transferia_tpu.coordinator import new_coordinator
from transferia_tpu.coordinator.interface import TransferStatus


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="trtpu",
        description="TPU-native data transfer: snapshot + CDC replication",
    )
    p.add_argument("--log-level", default="info",
                   choices=["debug", "info", "warning", "error"])
    p.add_argument("--coordinator", default="memory",
                   choices=["memory", "filestore", "s3"],
                   help="control-plane backend")
    p.add_argument("--coordinator-dir", default="",
                   help="shared directory for --coordinator filestore")
    p.add_argument("--coordinator-bucket", default="",
                   help="bucket for --coordinator s3")
    p.add_argument("--coordinator-endpoint", default="",
                   help="S3-compatible endpoint URL (default: AWS)")
    p.add_argument("--coordinator-region", default="us-east-1",
                   help="region for --coordinator s3 signing")
    p.add_argument("--coordinator-prefix", default="",
                   help="key prefix inside the coordinator bucket")
    p.add_argument("--job-index", type=int, default=0,
                   help="this worker's index (0 = main)")
    p.add_argument("--job-count", type=int, default=0,
                   help="override runtime.job_count")
    p.add_argument("--process-count", type=int, default=0,
                   help="override runtime.process_count")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve Prometheus metrics on this port (0 = off)")
    p.add_argument("--health-port", type=int, default=0,
                   help="serve /health on this port (0 = off)")
    p.add_argument("--operation-id", default="",
                   help="shared operation id for sharded snapshot workers "
                        "(default: op-<transfer id>)")

    sub = p.add_subparsers(dest="command", required=True)

    def add_transfer_cmd(name, help_):
        c = sub.add_parser(name, help=help_)
        c.add_argument("--transfer", required=True,
                       help="path to transfer.yaml")
        return c

    add_transfer_cmd("activate", "snapshot + prepare replication")
    rep = add_transfer_cmd("replicate",
                           "activate if needed, then run replication")
    rep.add_argument("--max-attempts", type=int, default=0,
                     help="stop after N failed attempts (0 = retry forever)")
    up = add_transfer_cmd("upload", "ad-hoc copy of explicit tables")
    up.add_argument("--table", action="append", default=[],
                    help="table to upload (repeatable), e.g. ns.name")
    add_transfer_cmd("reupload",
                     "cleanup and re-snapshot every table "
                     "(worker/tasks/reupload.go)")
    at = add_transfer_cmd("add-tables",
                          "snapshot new tables into a live transfer and "
                          "widen its include list")
    at.add_argument("--table", action="append", default=[], required=True,
                    help="table to add (repeatable), e.g. ns.name")
    rt = add_transfer_cmd("remove-tables",
                          "narrow the include list (target data stays)")
    rt.add_argument("--table", action="append", default=[], required=True,
                    help="table to remove (repeatable), e.g. ns.name")
    chk_static = sub.add_parser(
        "check",
        help="static analysis: device purity, lock discipline, "
             "exception/resource hygiene, registry contracts "
             "(see --list-rules; data validation moved to `checksum`)")
    from transferia_tpu.analysis.cli import add_check_args

    add_check_args(chk_static)
    chk = add_transfer_cmd(
        "checksum", "full data-validation task (sampling, type-aware "
        "comparators; worker/tasks/checksum.go)")
    chk.add_argument("--table", action="append", default=[],
                     help="restrict to a table (repeatable), e.g. ns.name")
    chk.add_argument("--size-threshold", type=int, default=None,
                     help="bytes above which tables are compared by "
                          "sampling instead of a full scan "
                          "(default 20 MiB; 0 = always sample)")
    chk.add_argument("--strict-types", action="store_true",
                     help="require exact canonical type equality instead "
                          "of family-level equivalence")
    chk.add_argument("--method", choices=["compare", "fingerprint"],
                     default="compare",
                     help="fingerprint: order-independent digest per "
                          "table (device-reduced when profitable, O(1) "
                          "memory); row-level compare runs only on "
                          "digest mismatch")
    chk.add_argument("--fingerprint-backend",
                     choices=["auto", "host", "device"], default="auto",
                     help="where the fingerprint reduction runs "
                          "(auto measures, see ops/linkprobe.py)")
    chk.add_argument("--against-operation", default="",
                     help="compare the TARGET against the table "
                          "fingerprints a snapshot recorded inline "
                          "(validation: {fingerprint: true}) under this "
                          "operation id — no source re-read")
    add_transfer_cmd("validate", "parse and validate the transfer config")
    add_transfer_cmd("deactivate",
                     "release source resources (replication slots etc.)")
    sniff = add_transfer_cmd("sniff",
                             "preview sample rows from the source")
    sniff.add_argument("--rows", type=int, default=5,
                       help="rows per table")
    reg = add_transfer_cmd("regular-snapshot",
                           "run the cron-driven re-snapshot loop")
    reg.add_argument("--max-runs", type=int, default=0,
                     help="stop after N runs (0 = forever)")
    desc = sub.add_parser("describe",
                          help="dump provider endpoint param schemas")
    desc.add_argument("--provider", default="",
                      help="limit to one provider")
    tsd = sub.add_parser("typesystem-docs",
                         help="generate per-provider typesystem.md files")
    tsd.add_argument("--out", default="docs/typesystem",
                     help="output directory")
    trc = sub.add_parser(
        "trace",
        help="run a transfer with pipeline tracing on; write a "
             "Perfetto-loadable timeline + per-stage summary")
    trc.add_argument("--transfer", default="",
                     help="path to transfer.yaml (default: built-in "
                          "sample->stdout demo with a fused mask+filter "
                          "chain)")
    trc.add_argument("--out", default="trace.json",
                     help="Chrome trace-event JSON output path "
                          "(open in Perfetto / chrome://tracing)")
    trc.add_argument("--seconds", type=float, default=10.0,
                     help="capture window for replication transfers "
                          "(snapshot transfers run to completion)")
    trc.add_argument("--rows", type=int, default=50_000,
                     help="demo source rows (only without --transfer)")
    trc.add_argument("--fleet", default="", metavar="TRANSFER_ID",
                     help="fleet mode: instead of running anything, "
                          "merge the durable obs segments from the "
                          "coordinator (stats/fleetobs.py) into ONE "
                          "Perfetto timeline for this transfer — spans "
                          "from every worker process that touched it, "
                          "linked under the propagated trace ids "
                          "('all' = every trace in the scope)")
    cha = sub.add_parser(
        "chaos",
        help="seeded fault-injection trials over the built-in sample "
             "transfers; audits at-least-once delivery, bounded "
             "duplication, checkpoint monotonicity and post-retry "
             "fingerprint equality (chaos/)")
    cha.add_argument("--trials", type=int, default=5,
                     help="trials per mode")
    cha.add_argument("--seed", type=int, default=7,
                     help="master seed: derives every trial's fault "
                          "schedule and PRNG draws (replayable)")
    cha.add_argument("--mode", default="both",
                     choices=["snapshot", "replication", "worker_crash",
                              "scheduler_kill", "fleet_distributed",
                              "lock_order", "arrow_ipc", "exactly_once",
                              "snapshot_and_increment", "both", "all"],
                     help="worker_crash kills a sharded worker mid-part "
                          "and audits lease reclamation + epoch "
                          "fencing; scheduler_kill kills a fleet "
                          "worker slot at a dispatch decision and "
                          "audits kill/rebalance (no transfer lost or "
                          "double-admitted); fleet_distributed runs "
                          "the durable-queue fleet gauntlet (scheduler "
                          "failover, worker kill mid-part with ticket "
                          "reclaim, interactive preemption with "
                          "resume-from-committed-parts, exactly-once "
                          "delivery, and byte-identical replay of the "
                          "admission/claim/preempt logs across two "
                          "runs of one seed); lock_order re-runs the "
                          "fleet_distributed gauntlet with the "
                          "runtime lock-order sentinel armed "
                          "(runtime/lockwatch.py) and additionally "
                          "requires ZERO lock-order inversions per "
                          "seed; arrow_ipc audits the "
                          "zero-copy interchange wire (arrow_ipc "
                          "source → memory); exactly_once audits the "
                          "staged two-phase commit (zero duplicate/"
                          "lost rows under torn writes, mid-publish "
                          "kills and zombie replay, per capable sink "
                          "backend); snapshot_and_increment audits the "
                          "MVCC consistent cutover (seeded aborts "
                          "mid-snapshot/mid-delta-append/mid-cutover/"
                          "mid-compaction, exactly-once merged reads, "
                          "zombie publishes fenced at both epochs, "
                          "compaction byte-equivalence, and "
                          "byte-identical fire/admission/cutover logs "
                          "across two runs of one seed); both = "
                          "snapshot+replication; all adds worker_crash "
                          "+ scheduler_kill + fleet_distributed + "
                          "lock_order + arrow_ipc + exactly_once + "
                          "snapshot_and_increment")
    cha.add_argument("--rows", type=int, default=0,
                     help="snapshot source rows (default 4096)")
    cha.add_argument("--messages", type=int, default=0,
                     help="replication broker messages (default 300)")
    cha.add_argument("--spec", default=None,
                     help="explicit failpoint spec for every trial "
                          "(overrides the seed-derived schedule; "
                          "grammar: chaos/failpoints.py)")
    cha.add_argument("--json", action="store_true", dest="as_json",
                     help="machine-readable report")
    fli = sub.add_parser(
        "flight",
        help="Arrow Flight shard-handoff server over the interchange "
             "plane (interchange/flight.py): `serve` publishes parts "
             "for worker→worker DoGet at wire speed, `bench` measures "
             "pivot vs IPC vs shm vs Flight on this host")
    fli.add_argument("action", choices=["serve", "bench"])
    fli.add_argument("--host", default="127.0.0.1",
                     help="serve: bind address")
    fli.add_argument("--port", type=int, default=8815,
                     help="serve: bind port (0 = ephemeral)")
    fli.add_argument("--shm", action="store_true",
                     help="enable the same-host shared-memory fast "
                          "path (co-located clients map segments "
                          "instead of pulling the gRPC stream)")
    fli.add_argument("--path", default="",
                     help="serve: preload parts from Arrow IPC "
                          "stream(s) at this file/dir/glob")
    fli.add_argument("--uri", default="",
                     help="bench: benchmark against an existing server "
                          "(default: self-hosted loopback)")
    fli.add_argument("--rows", type=int, default=200_000,
                     help="bench: rows moved per path")
    fli.add_argument("--batch-rows", type=int, default=16_384)
    fli.add_argument("--streams", default="1,2,4,8",
                     help="bench: comma-separated substream counts for "
                          "the multi-stream scaling curve over the "
                          "dict-heavy shape (default 1,2,4,8)")
    fli.add_argument("--json", action="store_true", dest="as_json",
                     help="bench: machine-readable report")
    flt = sub.add_parser(
        "fleet",
        help="fleet control plane (fleet/): `bench` drives 100+ "
             "concurrent sample→memory transfers through the "
             "admission/fair-share scheduler and reports p50/p99 "
             "dispatch latency, Jain fairness under a 10:1 tenant "
             "skew, and the delivery audit")
    flt.add_argument("action", choices=["bench"])
    flt.add_argument("--transfers", type=int, default=120,
                     help="bench: concurrent transfers to schedule")
    flt.add_argument("--workers", type=int, default=8,
                     help="bench: worker slots")
    flt.add_argument("--lanes", type=int, default=2,
                     help="bench: max in-flight transfers per worker")
    flt.add_argument("--rows", type=int, default=256,
                     help="bench: rows per transfer")
    flt.add_argument("--seed", type=int, default=7,
                     help="bench: tenant-mix shuffle seed")
    flt.add_argument("--json", action="store_true", dest="as_json",
                     help="machine-readable report")
    wk = sub.add_parser(
        "worker",
        help="run a supervised fleet worker process: claim tickets "
             "from the coordinator's durable admission queue (WDRR "
             "fair share), run them through the snapshot engine, "
             "heartbeat the ticket lease, drain gracefully on SIGTERM "
             "(fleet/worker.py; pair with --coordinator filestore|s3 "
             "so N processes share one queue)")
    wk.add_argument("--queue", default="fleet",
                    help="durable admission queue name")
    wk.add_argument("--worker-index", type=int, default=-1,
                    help="this worker's index (-1 = derive from pid)")
    wk.add_argument("--heartbeat", type=float, default=1.0,
                    help="ticket lease renewal interval (seconds)")
    wk.add_argument("--idle-exit", type=float, default=0.0,
                    help="exit after this many seconds with nothing "
                         "claimable (0 = run until SIGTERM)")
    wk.add_argument("--max-tickets", type=int, default=0,
                    help="exit after running N tickets (0 = unbounded)")
    top = sub.add_parser(
        "top",
        help="live per-transfer / per-tenant resource console: polls "
             "GET /debug/ledger on a running worker's health port and "
             "renders who is burning rows, bytes, H2D, launches, and "
             "wait time (stats/ledger.py)")
    top.add_argument("--url", default="http://127.0.0.1:8080",
                     help="health server base URL of the worker "
                          "(--health-port)")
    top.add_argument("--interval", type=float, default=2.0,
                     help="seconds between frames")
    top.add_argument("--frames", type=int, default=0,
                     help="stop after N frames (0 = until Ctrl-C)")
    top.add_argument("--limit", type=int, default=20,
                     help="transfer rows per frame")
    top.add_argument("--json", action="store_true", dest="as_json",
                     help="print one raw snapshot (ledger, or the "
                          "merged fleet view under --fleet) and exit")
    top.add_argument("--once", action="store_true",
                     help="render one formatted frame and exit "
                          "(scripting / CI smokes)")
    top.add_argument("--fleet", action="store_true",
                     help="cluster pane: merge the durable obs "
                          "segments of every worker process from the "
                          "coordinator (global --coordinator* flags) "
                          "instead of polling one worker's health "
                          "port — fleet ledger, per-worker liveness "
                          "ages, merged latency histograms, "
                          "cross-process conservation")
    sl = sub.add_parser(
        "slo",
        help="SLO verdicts: burn-rate objectives (fast 5m / slow 1h "
             "windows) over the merged obs-segment stream plus the "
             "per-transfer freshness watermarks (stats/slo.py); "
             "default polls GET /debug/slo on a worker's health "
             "port, --fleet evaluates the coordinator's segments "
             "directly, --demo runs a sample→memory transfer and "
             "judges it")
    sl.add_argument("--url", default="http://127.0.0.1:8080",
                    help="health server base URL of the worker")
    sl.add_argument("--fleet", action="store_true",
                    help="evaluate the durable obs segments from the "
                         "coordinator (global --coordinator* flags) "
                         "instead of polling a worker health port — "
                         "any process computes identical verdicts "
                         "from the same segments")
    sl.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable verdicts")
    sl.add_argument("--demo", action="store_true",
                    help="self-contained smoke: run the sample→stdout "
                         "demo transfer locally, then evaluate this "
                         "process's own state")
    sl.add_argument("--rows", type=int, default=50_000,
                    help="demo rows")
    ex = sub.add_parser(
        "explain",
        help="critical-path attribution: walk the causal trace "
             "(parent/child spans + cross-process flow links) and "
             "attribute end-to-end wall time to pipeline stages "
             "(decode, device dispatch, queue wait, wire, publish) "
             "with a top-3-levers summary (stats/critpath.py); "
             "`explain demo` runs the sample→stdout demo transfer "
             "with tracing and explains it, `explain <transfer-id>` "
             "merges the fleet obs segments for that transfer")
    ex.add_argument("target",
                    help="'demo' or a transfer id to explain from the "
                         "coordinator's obs segments")
    ex.add_argument("--rows", type=int, default=50_000,
                    help="demo rows")
    ex.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report")
    return p


def _setup(args) -> None:
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper()),
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
    )
    from transferia_tpu.runtime import knobs

    if knobs.env_str("TRANSFERIA_TPU_TRACE", "") not in (
            "", "0", "false", "no"):
        # headless span capture: worker processes in a fleet can't be
        # handed a --trace flag per run, but their obs segments export
        # span deltas (stats/fleetobs.py) — this env knob turns the
        # ring on so `trtpu trace --fleet` has cross-process spans
        from transferia_tpu.stats import trace as _trace

        _trace.enable(True)
    # secret redaction + value truncation on every handler
    # (internal/logger/sanitizer_encoder.go + json_truncator.go parity)
    from transferia_tpu.utils.logsanitize import install as _install_san

    _install_san()
    if args.metrics_port:
        try:
            from prometheus_client import start_http_server

            start_http_server(args.metrics_port)
            logging.info("metrics on :%d", args.metrics_port)
        except ImportError:
            logging.warning("prometheus_client missing; metrics disabled")
    if args.health_port:
        _start_health_server(args.health_port)
    # cgroup-derived RAM budget (runtime/shared/limits.go parity)
    from transferia_tpu.runtime.limits import apply_resource_limits

    apply_resource_limits()


def _query_seconds(path: str, default: float = 5.0) -> float:
    """?seconds=N off a debug-endpoint path (callers cap at 60)."""
    from urllib.parse import parse_qs, urlparse

    q = parse_qs(urlparse(path).query)
    try:
        return float(q.get("seconds", [default])[0])
    except ValueError:
        return default


def _start_health_server(port: int) -> int:
    """Minimal /health endpoint (pkg/serverutil healthcheck).

    Returns the bound port (port=0 binds an ephemeral one — tests)."""
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        # chunked transfer encoding (the streamed /debug/trace) needs 1.1
        protocol_version = "HTTP/1.1"

        def do_GET(self):
            if self.path.startswith("/debug/trace"):
                # span timeline capture (stats/trace.py): enables tracing
                # for ?seconds=N (cap 60), returns Chrome trace-event
                # JSON loadable in Perfetto / chrome://tracing.  The
                # window runs on a helper thread with a hard deadline
                # (503 when it blows) and the multi-MB document STREAMS
                # as chunks — a long capture must neither pin this
                # worker forever nor materialize 100k events in one
                # bytes blob
                from transferia_tpu.stats import trace

                secs = _query_seconds(self.path)
                try:
                    doc = trace.capture_seconds(secs)
                except TimeoutError as e:
                    body = json.dumps({"error": str(e)}).encode()
                    self.send_response(503)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                for chunk in trace.iter_chrome_trace_chunks(doc):
                    data = chunk.encode()
                    self.wfile.write(
                        f"{len(data):X}\r\n".encode() + data + b"\r\n")
                self.wfile.write(b"0\r\n\r\n")
                return
            elif self.path.startswith("/debug/fleet/obs"):
                # fleet-wide observability pane: obs segments from N
                # worker processes merged through the registered
                # coordinator (stats/fleetobs.py) — cluster ledger,
                # per-worker liveness, merged latency histograms, and
                # the cross-process conservation check
                from transferia_tpu.stats import fleetobs

                view = fleetobs.debug_fleet_obs()
                if view is None:
                    body = json.dumps({
                        "error": "no obs runtime registered (run under "
                                 "`trtpu worker` with an obs-capable "
                                 "coordinator)"}).encode()
                    status = 503
                else:
                    body = fleetobs.dumps_view(view).encode()
                    status = 200
                ctype = "application/json"
            elif self.path.startswith("/debug/slo"):
                # burn-rate verdicts + freshness watermarks: fleet-wide
                # through the registered obs runtime when there is one,
                # this process's own state otherwise (stats/slo.py —
                # pure over the segments, so every process agrees)
                from transferia_tpu.stats import slo

                body = json.dumps(slo.debug_slo(),
                                  default=str).encode()
                ctype = "application/json"
                status = 200
            elif self.path.startswith("/debug/ledger"):
                # per-transfer/per-tenant resource attribution + the
                # conservation reconciliation (stats/ledger.py); the
                # `trtpu top` console polls this
                from transferia_tpu.stats.ledger import LEDGER

                body = json.dumps(LEDGER.snapshot()).encode()
                ctype = "application/json"
                status = 200
            elif self.path.startswith("/debug/profile"):
                # sampling CPU profile (reference: always-on pprof,
                # cmd/trcli/main.go:62-64); ?seconds=N caps at 60
                from transferia_tpu.stats.profiler import sample_seconds

                secs = _query_seconds(self.path)
                body = sample_seconds(secs).format(30).encode()
                ctype = "text/plain"
                status = 200
            elif self.path.startswith("/debug/fleet"):
                # fleet control plane state: admission queues, per-
                # tenant debt, dispatch latency percentiles, and the
                # autoscaling hints (desired_workers) — the scrape
                # surface an autoscaler reads (fleet/scheduler.py)
                from transferia_tpu import fleet

                body = json.dumps(fleet.debug_snapshot()).encode()
                ctype = "application/json"
                status = 200
            elif self.path == "/debug/threads":
                # pprof-style stack dump (reference serves pprof on :8080)
                import traceback

                frames = sys._current_frames()
                names = {t.ident: t.name for t in threading.enumerate()}
                parts = []
                for ident, frame in frames.items():
                    parts.append(
                        f"Thread {names.get(ident, '?')} ({ident}):\n"
                        + "".join(traceback.format_stack(frame))
                    )
                body = "\n".join(parts).encode()
                ctype = "text/plain"
                status = 200
            else:
                body = b'{"status":"ok"}'
                ctype = "application/json"
                status = 200 if self.path in ("/", "/health") else 404
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv.server_address[1]


def _coordinator(args):
    if args.coordinator == "filestore":
        if not args.coordinator_dir:
            raise SystemExit(
                "--coordinator filestore requires --coordinator-dir"
            )
        return new_coordinator("filestore", root=args.coordinator_dir)
    if args.coordinator == "s3":
        if not args.coordinator_bucket:
            raise SystemExit(
                "--coordinator s3 requires --coordinator-bucket "
                "(credentials via AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY)"
            )
        return new_coordinator(
            "s3",
            bucket=args.coordinator_bucket,
            endpoint=args.coordinator_endpoint,
            region=args.coordinator_region,
            prefix=args.coordinator_prefix,
        )
    # memory coordinator cannot share parts across processes
    if args.job_count > 1:
        raise SystemExit(
            "--coordinator memory does not support --job-count > 1; "
            "use --coordinator filestore or s3 (main.go:118-121 parity)"
        )
    return new_coordinator("memory")


def _load_transfer(args):
    from transferia_tpu.cli.config import load_transfer

    transfer = load_transfer(args.transfer)
    transfer.runtime.current_job = args.job_index
    if args.job_count:
        transfer.runtime.sharding.job_count = args.job_count
    if args.process_count:
        transfer.runtime.sharding.process_count = args.process_count
    return transfer


def cli() -> int:
    """Console-script entry (trtpu).  Process-wide signal tweaks live
    HERE, not in main(): tests call main() in-process and a leaked
    SIGPIPE=SIG_DFL would turn any broken-pipe write later in the run
    into silent process death."""
    try:
        # die quietly when piped into head & co.
        signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    except (AttributeError, ValueError):
        pass  # non-POSIX
    return main()


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    _setup(args)

    if args.command == "describe":
        return cmd_describe(args)
    if args.command == "validate":
        return cmd_validate(args)
    if args.command == "typesystem-docs":
        return cmd_typesystem_docs(args)
    if args.command == "trace":
        return cmd_trace(args)
    if args.command == "check":
        from transferia_tpu.analysis.cli import run_check

        return run_check(args)
    if args.command == "chaos":
        return cmd_chaos(args)
    if args.command == "flight":
        return cmd_flight(args)
    if args.command == "fleet":
        return cmd_fleet(args)
    if args.command == "worker":
        return cmd_worker(args)
    if args.command == "top":
        return cmd_top(args)
    if args.command == "slo":
        return cmd_slo(args)
    if args.command == "explain":
        return cmd_explain(args)

    transfer = _load_transfer(args)
    cp = _coordinator(args)
    # an include list widened/narrowed by add-/remove-tables overrides the
    # spec on restart (add_tables.go persists through the coordinator)
    from transferia_tpu.tasks import apply_persisted_include_list

    apply_persisted_include_list(transfer, cp)

    if args.command == "activate":
        from transferia_tpu.tasks import activate_delivery

        activate_delivery(transfer, cp,
                          operation_id=args.operation_id or None)
        print(f"transfer {transfer.id}: activated")
        return 0

    if args.command == "upload":
        from transferia_tpu.tasks import upload

        upload(transfer, cp, args.table,
               operation_id=args.operation_id or None)
        print(f"transfer {transfer.id}: uploaded {len(args.table)} table(s)")
        return 0

    if args.command == "reupload":
        from transferia_tpu.tasks import reupload

        reupload(transfer, cp, operation_id=args.operation_id or None)
        print(f"transfer {transfer.id}: reuploaded")
        return 0

    if args.command == "add-tables":
        from transferia_tpu.tasks import add_tables

        add_tables(transfer, cp, args.table,
                   operation_id=args.operation_id or None)
        print(f"transfer {transfer.id}: added {len(args.table)} table(s)")
        return 0

    if args.command == "remove-tables":
        from transferia_tpu.tasks import remove_tables

        remove_tables(transfer, cp, args.table)
        print(f"transfer {transfer.id}: removed {len(args.table)} table(s)")
        return 0

    if args.command == "replicate":
        return cmd_replicate(args, transfer, cp)

    if args.command == "checksum":
        return cmd_checksum(args, transfer)

    if args.command == "deactivate":
        from transferia_tpu.providers.registry import get_provider

        get_provider(transfer.src_provider(), transfer).deactivate()
        cp.set_status(transfer.id, TransferStatus.DEACTIVATED)
        print(f"transfer {transfer.id}: deactivated")
        return 0

    if args.command == "sniff":
        from transferia_tpu.providers.registry import get_provider

        sample = get_provider(transfer.src_provider(), transfer).sniff(
            max_rows=args.rows
        )
        print(json.dumps(sample, indent=2, default=str))
        return 0

    if args.command == "regular-snapshot":
        from transferia_tpu.runtime.local import run_regular_snapshot

        run_regular_snapshot(transfer, cp, max_runs=args.max_runs)
        return 0

    raise SystemExit(f"unknown command {args.command}")


def cmd_replicate(args, transfer, cp) -> int:
    """replicate (cmd/trcli/replicate/replicate.go:50-101): activate when
    no prior state, then loop the replication worker."""
    from transferia_tpu.runtime import run_replication
    from transferia_tpu.tasks import activate_delivery

    state = cp.get_transfer_state(transfer.id)
    if state.get("status") != "activated":
        activate_delivery(transfer, cp)
    if not transfer.type.has_replication:
        print("transfer is snapshot-only; nothing to replicate")
        return 0
    stop = threading.Event()

    def handle_sig(signum, frame):
        logging.info("signal %d: stopping replication", signum)
        stop.set()

    signal.signal(signal.SIGINT, handle_sig)
    signal.signal(signal.SIGTERM, handle_sig)
    run_replication(transfer, cp, stop_event=stop,
                    max_attempts=args.max_attempts)
    return 0


def _checksum_against_operation(args, dst_storage) -> int:
    """Target-only validation: fingerprint every table the snapshot
    recorded (inline validation digests in the operation state) and
    compare — the source is never re-read."""
    from transferia_tpu.abstract.interfaces import is_columnar
    from transferia_tpu.abstract.schema import TableID
    from transferia_tpu.abstract.table import TableDescription
    from transferia_tpu.columnar.batch import ColumnBatch
    from transferia_tpu.ops.rowhash import TableFingerprinter

    cp = _coordinator(args)
    state = cp.get_operation_state(args.against_operation)
    recorded = state.get("table_fingerprints") or {}
    if not recorded:
        print(f"operation {args.against_operation}: no recorded "
              f"fingerprints (was the snapshot run with validation: "
              f"{{fingerprint: true}}?)", file=sys.stderr)
        return 2
    rc = 0
    for fqtn, want in sorted(recorded.items()):
        tid = TableID.parse(fqtn)
        fp = TableFingerprinter(backend=args.fingerprint_backend)

        def pusher(batch):
            if is_columnar(batch):
                fp.push(batch)
                return
            rows = [it for it in batch if it.is_row_event()]
            if rows:
                fp.push(ColumnBatch.from_rows(rows))

        try:
            dst_storage.load_table(TableDescription(id=tid), pusher)
        except Exception as e:
            print(f"{fqtn}: ERROR reading target: {e}")
            rc = 1
            continue
        got = fp.result().digest()
        if got == want:
            print(f"{fqtn}: OK [fingerprint] {got}")
        else:
            print(f"{fqtn}: MISMATCH [fingerprint] uploaded={want} "
                  f"target={got}")
            rc = 1
    return rc


def cmd_checksum(args, transfer) -> int:
    """Full validation task (checksum.go Checksum): sampling storages,
    type-aware comparators, streaming compare."""
    from transferia_tpu.abstract.schema import TableID
    from transferia_tpu.factories.storage import new_storage
    from transferia_tpu.providers.registry import get_provider
    from transferia_tpu.tasks.checksum import (
        ChecksumParameters,
        compare_checksum,
        heterogeneous_data_types,
    )

    dst_provider = get_provider(transfer.dst_provider(), transfer)
    # never fall back to .storage(): that reads transfer.src and would
    # vacuously compare the source against itself
    dst_storage = dst_provider.destination_storage()
    if dst_storage is None:
        print("destination provider has no storage view of the target; "
              "cannot checksum", file=sys.stderr)
        return 2
    if args.against_operation:
        return _checksum_against_operation(args, dst_storage)
    src_storage = new_storage(transfer)
    params = ChecksumParameters()
    if args.size_threshold is not None:
        params.table_size_threshold = args.size_threshold
    params.method = args.method
    params.fingerprint_backend = args.fingerprint_backend
    tables = None
    if args.table:
        tables = []
        for spec in args.table:
            ns, _, name = spec.rpartition(".")
            tables.append(TableID(ns, name))
    same = transfer.src_provider() == transfer.dst_provider()
    eq = ((lambda a, b: a == b) if (args.strict_types or same)
          else heterogeneous_data_types)
    report = compare_checksum(src_storage, dst_storage, tables,
                              params, equal_data_types=eq)
    print(report.summary())
    return 0 if report.ok else 1


def _demo_trace_transfer(rows: int):
    """sample->stdout snapshot with a fusable mask+filter chain: a
    self-contained timeline demo that exercises source decode, the
    fused device transform (mask+filter), the row pivot (verbose stdout
    sink unpivots a slice), and the sink — no external services."""
    from transferia_tpu.models import Transfer, TransferType
    from transferia_tpu.providers.sample import SampleSourceParams
    from transferia_tpu.providers.stdout import StdoutTargetParams

    return Transfer(
        id="trace-demo",
        type=TransferType.SNAPSHOT_ONLY,
        src=SampleSourceParams(preset="iot", rows=rows),
        dst=StdoutTargetParams(verbose=True, max_rows_printed=2),
        transformation={"transformers": [
            {"mask_field": {"columns": ["device_id"], "salt": "trace"}},
            {"filter_rows": {"filter": "event_id >= 0"}},
        ]},
    )


def cmd_trace(args) -> int:
    """Run one transfer with tracing enabled; write trace.json (Chrome
    trace-event format, open in Perfetto) and print the stage summary
    (p50/p99 per stage, overlap factor, bytes moved) plus the device
    telemetry counters."""
    import time as _time

    from transferia_tpu.stats import trace
    from transferia_tpu.stats.ledger import LEDGER
    from transferia_tpu.stats.registry import Metrics

    if args.fleet:
        return cmd_trace_fleet(args)
    if args.transfer:
        transfer = _load_transfer(args)
    else:
        transfer = _demo_trace_transfer(args.rows)
    cp = _coordinator(args)
    metrics = Metrics()
    trace.reset()
    trace.TELEMETRY.reset()  # fresh counters for this one-shot run
    trace.enable(True)
    t0 = _time.perf_counter()
    try:
        if transfer.type.has_replication:
            from transferia_tpu.runtime import run_replication

            stop = threading.Event()
            timer = threading.Timer(max(0.5, args.seconds), stop.set)
            timer.daemon = True
            timer.start()
            try:
                run_replication(transfer, cp, metrics=metrics,
                                stop_event=stop)
            finally:
                timer.cancel()
        else:
            from transferia_tpu.tasks import SnapshotLoader

            SnapshotLoader(transfer, cp, metrics=metrics).upload_tables()
    finally:
        # export in the finally: a failed transfer is exactly when the
        # timeline matters most — the spans up to the failure survive
        wall = _time.perf_counter() - t0
        trace.enable(False)
        trace.TELEMETRY.fold_into(metrics)  # prometheus exposure
        LEDGER.fold_into(metrics)
        n_events = trace.write_chrome_trace(args.out)
        print(f"trace: {n_events} events -> {args.out} "
              f"(open in https://ui.perfetto.dev or chrome://tracing)")
        print(trace.format_summary(wall))
        print("device telemetry: "
              + json.dumps(trace.TELEMETRY.snapshot()))
    return 0


def cmd_trace_fleet(args) -> int:
    """`trtpu trace --fleet <transfer>`: stitch the durable obs
    segments of every process that touched the transfer into ONE
    Perfetto timeline (stats/fleetobs.py) — each worker process is a
    pid lane, cross-process parent links render as flow arrows."""
    from transferia_tpu.stats import fleetobs

    cp = _coordinator(args)
    if not cp.supports_obs_segments():
        print("coordinator has no obs-segment support; nothing to "
              "merge", file=sys.stderr)
        return 2
    scope = fleetobs.default_scope()
    segments = cp.list_obs_segments(scope)
    if not segments:
        print(f"no obs segments under scope {scope!r} — are workers "
              f"running with observability export on?", file=sys.stderr)
        return 2
    transfer_filter = "" if args.fleet == "all" else args.fleet
    doc = fleetobs.export_fleet_chrome_trace(
        segments, transfer_id=transfer_filter)
    with open(args.out, "w") as fh:
        json.dump(doc, fh)
    other = doc["otherData"]
    view = fleetobs.merge_segments(segments)
    cons = view["conservation"]
    print(f"fleet trace: {len(doc['traceEvents'])} events from "
          f"{other['processes']} process(es) "
          f"({other['segments']} segments, "
          f"{other['corrupt_segments']} torn) -> {args.out} "
          f"(open in https://ui.perfetto.dev)")
    print(f"fleet conservation: "
          f"{'OK' if cons['ok'] else 'DRIFT ' + json.dumps(cons['drift'])}")
    if transfer_filter and other["processes"] == 0:
        print(f"no spans matched transfer {transfer_filter!r} "
              f"(check the id, or pass 'all')", file=sys.stderr)
        return 1
    return 0


def cmd_chaos(args) -> int:
    """Seeded chaos trials + delivery-invariant audit (chaos/runner.py).

    Exit 0 when every trial upholds every invariant; 1 otherwise.
    Embedded soaks fold per-site fire counts into their own registry
    via runner.run_trials(metrics=...) / failpoints.fold_into; the
    one-shot CLI just prints the report."""
    from transferia_tpu.chaos import runner as chaos_runner
    from transferia_tpu.chaos.failpoints import (
        FailpointSpecError,
        parse_spec,
    )

    if args.spec:
        try:
            parse_spec(args.spec)
        except FailpointSpecError as e:
            print(f"bad --spec: {e}", file=sys.stderr)
            return 2
    kwargs = dict(trials=args.trials, seed=args.seed, mode=args.mode,
                  spec=args.spec)
    if args.rows:
        kwargs["rows"] = args.rows
    if args.messages:
        kwargs["messages"] = args.messages
    report = chaos_runner.run_trials(**kwargs)
    if args.as_json:
        print(json.dumps(report.to_dict(), indent=1))
    else:
        print(report.format_summary())
    return 0 if report.passed else 1


def cmd_flight(args) -> int:
    """Arrow Flight shard-handoff server / loopback benchmark."""
    from transferia_tpu.interchange._pyarrow import (
        PyArrowUnavailable,
        have_flight,
    )

    if not have_flight():
        try:
            from transferia_tpu.interchange._pyarrow import flight

            flight("trtpu flight")
        except PyArrowUnavailable as e:
            print(str(e), file=sys.stderr)
            return 2
    if args.action == "bench":
        from transferia_tpu.interchange.bench import (
            format_report,
            run_interchange_bench,
        )

        counts = tuple(int(t) for t in args.streams.split(",") if t)
        report = run_interchange_bench(
            rows=args.rows, batch_rows=args.batch_rows,
            flight_uri=args.uri or None,
            stream_counts=counts or (1, 2, 4, 8))
        if args.as_json:
            print(json.dumps(report, indent=1))
        else:
            print(format_report(report))
        return 0

    from transferia_tpu.interchange.flight import ShardFlightServer

    server = ShardFlightServer(f"grpc://{args.host}:{args.port}",
                               enable_shm=args.shm)
    try:
        if args.path:
            from transferia_tpu.providers.arrow_ipc import (
                ArrowIpcSourceParams,
                ArrowIpcStorage,
            )
            from transferia_tpu.providers.flight import part_key

            storage = ArrowIpcStorage(ArrowIpcSourceParams(path=args.path))
            from transferia_tpu.abstract.table import TableDescription

            for tid in storage.table_list():
                desc = TableDescription(id=tid)
                for i, part in enumerate(storage.shard_table(desc)):
                    batches: list = []
                    storage.load_table(part, batches.append)
                    rows = server.publish(part_key(tid, str(i)), batches)
                    logging.info("flight: published %s part %d (%d rows)",
                                 tid, i, rows)
        print(f"flight: serving on grpc://{args.host}:{server.port}"
              + (" (shm handoff enabled)" if args.shm else ""))
        stop = threading.Event()
        signal.signal(signal.SIGINT, lambda *a: stop.set())
        signal.signal(signal.SIGTERM, lambda *a: stop.set())
        stop.wait()
        return 0
    finally:
        server.close()


def cmd_fleet(args) -> int:
    """Fleet scheduler bench (fleet/bench.py).  Exit 0 only when every
    transfer delivered, nothing was lost or double-admitted, and the
    Jain fairness index held >= 0.9 under the skewed tenant mix."""
    from transferia_tpu.fleet.bench import format_report, run_fleet_bench

    report = run_fleet_bench(
        transfers=args.transfers, workers=args.workers,
        lanes=args.lanes, rows=args.rows, seed=args.seed)
    if args.as_json:
        print(json.dumps(report, indent=1))
    else:
        print(format_report(report))
    return 0 if report["ok"] else 1


def cmd_worker(args) -> int:
    """Run one fleet worker process against the durable admission
    queue (`trtpu worker`, fleet/worker.py).  SIGTERM/SIGINT request a
    graceful drain: the running transfer yields at its next part
    boundary, the claim is released back to the queue, and the process
    exits 0 — a peer resumes the transfer from its committed parts."""
    import os

    from transferia_tpu.fleet.worker import FleetWorker

    cp = _coordinator(args)
    if args.coordinator == "memory":
        logging.warning(
            "worker on a memory coordinator: the queue is invisible to "
            "other processes (use --coordinator filestore or s3 for a "
            "real fleet)")
    if args.worker_index >= 0:
        index = args.worker_index
    else:
        # random, not pid-derived: every containerized worker is pid 1,
        # and two workers sharing an id could renew each other's claims
        # (the epoch-scoped renewal also defends, but unique ids keep
        # health reports and steal attribution readable)
        index = int.from_bytes(os.urandom(3), "big") % 1_000_000
    worker = FleetWorker(
        cp, queue=args.queue, worker_index=index,
        heartbeat_interval=args.heartbeat,
        idle_exit_seconds=args.idle_exit,
        max_tickets=args.max_tickets)
    if cp.supports_obs_segments():
        # give this process's health port the fleet panes
        # (/debug/fleet/obs merged view, /debug/fleet worker liveness)
        from transferia_tpu.stats import fleetobs

        fleetobs.register_runtime(cp,
                                  health_scope=f"fleet:{args.queue}")
    stop = threading.Event()

    def handle_sig(signum, frame):
        logging.info("signal %d: draining worker %s", signum,
                     worker.worker_id)
        worker.request_drain()
        stop.set()

    signal.signal(signal.SIGINT, handle_sig)
    signal.signal(signal.SIGTERM, handle_sig)
    logging.info("fleet worker %s serving queue %r", worker.worker_id,
                 args.queue)
    worker.run(stop)
    print(f"worker {worker.worker_id}: {worker.tickets_run} ticket(s) "
          f"run")
    return 0


def cmd_top(args) -> int:
    """Live resource console: per-process over GET /debug/ledger
    (stats/ledger.py format_top), or — with --fleet — the merged
    cluster pane from the coordinator's durable obs segments
    (stats/fleetobs.py).  One frame per --interval, ANSI clear between
    frames on a tty; --once renders a single frame (CI smokes),
    --json dumps one raw snapshot."""
    import time as _time
    import urllib.request

    from transferia_tpu.stats.ledger import format_top

    if args.fleet:
        return cmd_top_fleet(args)
    url = args.url.rstrip("/") + "/debug/ledger"
    frames = 0
    try:
        while True:
            try:
                with urllib.request.urlopen(url, timeout=10) as resp:
                    snap = json.loads(resp.read())
            except (OSError, ValueError) as e:
                # ValueError: a 200 that isn't our JSON (wrong service
                # or a proxy splash page on the port)
                print(f"trtpu top: {url}: {e}", file=sys.stderr)
                return 2
            if not isinstance(snap, dict) or "totals" not in snap:
                # valid JSON but not a ledger snapshot: same wrong-
                # service story as a parse failure, same exit
                print(f"trtpu top: {url}: response is not a "
                      f"/debug/ledger snapshot (wrong service?)",
                      file=sys.stderr)
                return 2
            # lag/SLO columns ride the same poll, best-effort: an old
            # worker without /debug/slo still renders a plain frame
            slo_url = args.url.rstrip("/") + "/debug/slo"
            try:
                with urllib.request.urlopen(slo_url, timeout=10) as r:
                    verdicts = json.loads(r.read())
                if isinstance(verdicts, dict) and \
                        "objectives" in verdicts:
                    snap["slo"] = verdicts
            except (OSError, ValueError):
                pass
            if args.as_json:
                print(json.dumps(snap, indent=1))
                return 0
            if frames and sys.stdout.isatty():
                print("\x1b[2J\x1b[H", end="")
            print(format_top(snap, limit=args.limit), flush=True)
            frames += 1
            if args.once or (args.frames and frames >= args.frames):
                return 0
            _time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


def cmd_top_fleet(args) -> int:
    """`trtpu top --fleet`: the cluster pane.  Reads every worker
    process's durable obs segments through the coordinator (global
    --coordinator* flags), merges them (latest cumulative state per
    process, summed across processes), and renders the fleet ledger
    with per-worker liveness ages and merged latency tails."""
    import time as _time

    from transferia_tpu.stats import fleetobs

    cp = _coordinator(args)
    if not cp.supports_obs_segments():
        print("trtpu top --fleet: coordinator has no obs-segment "
              "support", file=sys.stderr)
        return 2
    frames = 0
    try:
        while True:
            try:
                view = fleetobs.read_view(cp)
            except Exception as e:
                print(f"trtpu top --fleet: segment read failed: {e}",
                      file=sys.stderr)
                return 2
            if args.as_json:
                print(fleetobs.dumps_view(view))
                return 0
            if frames and sys.stdout.isatty():
                print("\x1b[2J\x1b[H", end="")
            print(fleetobs.format_fleet_top(view, limit=args.limit),
                  flush=True)
            frames += 1
            if args.once or (args.frames and frames >= args.frames):
                return 0
            _time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


def _run_demo_snapshot(rows: int) -> None:
    """One traced sample→stdout snapshot in THIS process (the `trtpu
    slo --demo` / `trtpu explain demo` substrate)."""
    from transferia_tpu.coordinator import MemoryCoordinator
    from transferia_tpu.stats import trace
    from transferia_tpu.stats.registry import Metrics
    from transferia_tpu.tasks import SnapshotLoader

    trace.reset()
    trace.enable(True)
    try:
        SnapshotLoader(_demo_trace_transfer(rows), MemoryCoordinator(),
                       metrics=Metrics()).upload_tables()
    finally:
        trace.enable(False)


def cmd_slo(args) -> int:
    """`trtpu slo`: burn-rate verdicts + freshness watermarks.  URL
    mode polls GET /debug/slo with the `trtpu top` error contract
    (non-JSON / wrong-shape bodies exit 2); --fleet evaluates the
    coordinator's obs segments directly; --demo runs the sample
    snapshot locally first so the verdicts have data to judge."""
    import urllib.request

    from transferia_tpu.stats import slo

    if args.demo:
        _run_demo_snapshot(args.rows)
        view = slo.evaluate(slo.local_segments())
        view["scope"] = "demo"
    elif args.fleet:
        from transferia_tpu.stats import fleetobs

        cp = _coordinator(args)
        if not cp.supports_obs_segments():
            print("trtpu slo --fleet: coordinator has no obs-segment "
                  "support", file=sys.stderr)
            return 2
        scope = fleetobs.default_scope()
        segments = cp.list_obs_segments(scope)
        if not segments:
            print(f"trtpu slo --fleet: no obs segments under scope "
                  f"{scope!r}", file=sys.stderr)
            return 2
        view = slo.evaluate(segments)
        view["scope"] = scope
    else:
        url = args.url.rstrip("/") + "/debug/slo"
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                view = json.loads(resp.read())
        except (OSError, ValueError) as e:
            print(f"trtpu slo: {url}: {e}", file=sys.stderr)
            return 2
        if not isinstance(view, dict) or "objectives" not in view:
            # valid JSON but not an SLO payload (wrong service, or the
            # evaluator surfaced an error dict): exit 2, like top
            detail = view.get("error") if isinstance(view, dict) \
                else "response is not a /debug/slo payload"
            print(f"trtpu slo: {url}: {detail}", file=sys.stderr)
            return 2
    if args.as_json:
        print(json.dumps(view, indent=1, default=str))
    else:
        print(slo.format_verdicts(view))
    return 0 if view.get("ok") else 1


def cmd_explain(args) -> int:
    """`trtpu explain`: critical-path attribution.  `demo` runs the
    traced sample snapshot in-process and explains its own spans; a
    transfer id merges the coordinator's obs segments (multi-worker
    critical path via cross-process flow links)."""
    from transferia_tpu.stats import critpath

    if args.target == "demo":
        _run_demo_snapshot(args.rows)
        records = critpath.records_from_local()
        report = critpath.explain(records, transfer_id="trace-demo")
    else:
        from transferia_tpu.stats import fleetobs

        cp = _coordinator(args)
        if not cp.supports_obs_segments():
            print("trtpu explain: coordinator has no obs-segment "
                  "support", file=sys.stderr)
            return 2
        scope = fleetobs.default_scope()
        segments = cp.list_obs_segments(scope)
        if not segments:
            print(f"trtpu explain: no obs segments under scope "
                  f"{scope!r} — are workers running with observability "
                  f"export on?", file=sys.stderr)
            return 2
        records = critpath.records_from_segments(segments)
        report = critpath.explain(records, transfer_id=args.target)
    if not report.get("spans"):
        print("trtpu explain: no spans found (tracing off, or the "
              "transfer id matched nothing)", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(report, indent=1, default=str))
    else:
        print(critpath.format_report(report))
    return 0


def cmd_validate(args) -> int:
    from transferia_tpu.cli.config import load_transfer

    try:
        transfer = load_transfer(args.transfer)
    except Exception as e:
        print(f"INVALID: {e}", file=sys.stderr)
        return 1
    # also validate the transformer chain compiles
    from transferia_tpu.transform import build_chain

    try:
        build_chain(transfer.transformation)
    except Exception as e:
        print(f"INVALID transformation: {e}", file=sys.stderr)
        return 1
    print(f"OK: {transfer.id} ({transfer.type.value}) "
          f"{transfer.src_provider()} -> {transfer.dst_provider()}")
    return 0


def cmd_typesystem_docs(args) -> int:
    """Generate per-provider typesystem.md (typesystem/schema_doc.go)."""
    import os

    from transferia_tpu.providers import load_builtin_providers
    from transferia_tpu.typesystem.rules import (
        doc_markdown,
        supported_providers,
    )

    load_builtin_providers()
    os.makedirs(args.out, exist_ok=True)
    for provider in supported_providers():
        path = os.path.join(args.out, f"{provider}.md")
        with open(path, "w") as fh:
            fh.write(doc_markdown(provider))
        print(path)
    return 0


def cmd_describe(args) -> int:
    """Dump endpoint params JSON schemas (trcli describe)."""
    import dataclasses

    from transferia_tpu.models.endpoint import _ENDPOINT_REGISTRY
    from transferia_tpu.providers import load_builtin_providers

    load_builtin_providers()
    out = {}
    for (provider, role), cls in sorted(_ENDPOINT_REGISTRY.items()):
        if args.provider and provider != args.provider:
            continue
        fields = {}
        for f in dataclasses.fields(cls):
            default = f.default if f.default is not dataclasses.MISSING \
                else None
            fields[f.name] = {
                "type": str(f.type),
                "default": default.value
                if hasattr(default, "value") else default,
            }
        out[f"{provider}/{role}"] = fields
    print(json.dumps(out, indent=2, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(cli())
