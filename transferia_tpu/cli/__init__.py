"""trtpu command-line interface (reference: cmd/trcli/)."""
