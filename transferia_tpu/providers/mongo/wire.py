"""MongoDB OP_MSG wire client.

Message: header (messageLength, requestID, responseTo, opCode=2013) +
flagBits (int32) + section kind 0 (one BSON command document).  Commands
run against the `admin` or target database via the `$db` field; SCRAM
auth uses saslStart/saslContinue.  Exhaustible cursors via find/getMore.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import os
import socket
import struct
import threading
from base64 import b64decode, b64encode
from typing import Any, Iterator, Optional

from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.providers.mongo import bson
from transferia_tpu.utils.net import recv_exact

OP_MSG = 2013


class MongoError(CategorizedError):
    def __init__(self, message: str, code: int = 0):
        super().__init__(CategorizedError.SOURCE, message)
        self.code = code


class MongoConnection:
    def __init__(self, host: str = "localhost", port: int = 27017,
                 user: str = "", password: str = "",
                 auth_db: str = "admin", timeout: float = 60.0):
        self.host = host
        self.port = port
        self.user = user
        self.password = password
        self.auth_db = auth_db
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self._req = 0
        self._lock = threading.Lock()

    def connect(self) -> "MongoConnection":
        self.sock = socket.create_connection((self.host, self.port),
                                             timeout=self.timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        hello = self.command("admin", {"hello": 1})
        if self.user:
            mechs = hello.get("saslSupportedMechs", [])
            self._scram_auth()
        return self

    def close(self) -> None:
        if self.sock is not None:
            self.sock.close()
            self.sock = None

    # -- OP_MSG -------------------------------------------------------------
    def command(self, db: str, cmd: dict) -> dict:
        body = dict(cmd)
        body["$db"] = db
        payload = struct.pack("<I", 0) + b"\x00" + bson.encode(body)
        with self._lock:
            self._req += 1
            req = self._req
            header = struct.pack("<iiii", 16 + len(payload), req, 0, OP_MSG)
            # I/O under self._lock is the design: the lock serializes
            # request/response framing on the single wire connection
            try:
                self.sock.sendall(header + payload)  # trtpu: ignore[LCK001]
                resp_len = struct.unpack(
                    "<i", recv_exact(self.sock, 4)  # trtpu: ignore[LCK001]
                )[0]
                resp = recv_exact(  # trtpu: ignore[LCK001]
                    self.sock, resp_len - 4)
            except (OSError, ConnectionError) as e:
                raise MongoError(f"mongo io error: {e}") from e
        # resp: requestID(4) responseTo(4) opCode(4) flags(4) kind(1) doc
        op_code = struct.unpack_from("<i", resp, 8)[0]
        if op_code != OP_MSG:
            raise MongoError(f"unexpected opcode {op_code}")
        doc, _ = bson.decode(resp, 17)
        if doc.get("ok") != 1 and doc.get("ok") != 1.0:
            raise MongoError(
                f"{doc.get('codeName', 'Error')}: "
                f"{doc.get('errmsg', 'command failed')}",
                code=int(doc.get("code", 0)),
            )
        return doc

    # -- auth (SCRAM-SHA-256) ----------------------------------------------
    def _scram_auth(self) -> None:
        nonce = b64encode(os.urandom(18)).decode()
        first_bare = f"n={self.user},r={nonce}"
        start = self.command(self.auth_db, {
            "saslStart": 1,
            "mechanism": "SCRAM-SHA-256",
            "payload": bson.Binary(("n,," + first_bare).encode()),
            "options": {"skipEmptyExchange": True},
        })
        server_first = bytes(
            start["payload"].raw if isinstance(start["payload"], bson.Binary)
            else start["payload"]
        ).decode()
        parts = dict(p.split("=", 1) for p in server_first.split(","))
        r, s, i = parts["r"], parts["s"], int(parts["i"])
        if not r.startswith(nonce):
            raise MongoError("SCRAM nonce mismatch")
        salted = hashlib.pbkdf2_hmac("sha256", self.password.encode(),
                                     b64decode(s), i)
        client_key = hmac_mod.new(salted, b"Client Key",
                                  hashlib.sha256).digest()
        stored = hashlib.sha256(client_key).digest()
        without_proof = f"c={b64encode(b'n,,').decode()},r={r}"
        auth_msg = ",".join([first_bare, server_first, without_proof])
        sig = hmac_mod.new(stored, auth_msg.encode(),
                           hashlib.sha256).digest()
        proof = b64encode(bytes(
            a ^ b for a, b in zip(client_key, sig)
        )).decode()
        final = self.command(self.auth_db, {
            "saslContinue": 1,
            "conversationId": start.get("conversationId", 1),
            "payload": bson.Binary(
                f"{without_proof},p={proof}".encode()
            ),
        })
        fin_payload = bytes(
            final["payload"].raw
            if isinstance(final["payload"], bson.Binary)
            else final["payload"]
        ).decode()
        server_key = hmac_mod.new(salted, b"Server Key",
                                  hashlib.sha256).digest()
        expect = hmac_mod.new(server_key, auth_msg.encode(),
                              hashlib.sha256).digest()
        got = dict(p.split("=", 1) for p in fin_payload.split(","))
        if b64decode(got.get("v", "")) != expect:
            raise MongoError("SCRAM server signature mismatch")

    # -- cursors ------------------------------------------------------------
    def find_all(self, db: str, collection: str,
                 filter: Optional[dict] = None,
                 sort: Optional[dict] = None,
                 projection: Optional[dict] = None,
                 batch_size: int = 1000) -> Iterator[list[dict]]:
        """Yields batches of documents until the cursor is exhausted."""
        cmd: dict[str, Any] = {
            "find": collection,
            "batchSize": batch_size,
        }
        if filter:
            cmd["filter"] = filter
        if sort:
            cmd["sort"] = sort
        if projection:
            cmd["projection"] = projection
        out = self.command(db, cmd)
        cursor = out["cursor"]
        batch = cursor.get("firstBatch", [])
        if batch:
            yield batch
        cid = cursor.get("id", 0)
        while cid:
            out = self.command(db, {
                "getMore": cid, "collection": collection,
                "batchSize": batch_size,
            })
            cursor = out["cursor"]
            batch = cursor.get("nextBatch", [])
            cid = cursor.get("id", 0)
            if batch:
                yield batch

    def list_collections(self, db: str) -> list[str]:
        out = self.command(db, {"listCollections": 1,
                                "nameOnly": True})
        return sorted(
            c["name"] for c in out["cursor"].get("firstBatch", [])
            if c.get("type", "collection") == "collection"
            and not c["name"].startswith("system.")
        )

    def count(self, db: str, collection: str) -> int:
        out = self.command(db, {"count": collection})
        return int(out.get("n", 0))
