"""MongoDB provider.

Reference parity: pkg/providers/mongo/ — snapshot with per-collection
parallelization units (parallelization_unit*.go), change-stream
replication (change_stream.go), bulk-op sink (sink_bulk_operations.go).
The client is a dependency-free BSON codec + OP_MSG wire implementation
(this image ships no pymongo): hello, SCRAM-SHA-256 auth, find/getMore
cursors, insert/update/delete, aggregate (change streams).
"""

from transferia_tpu.providers.mongo.provider import (
    MongoProvider,
    MongoSourceParams,
    MongoTargetParams,
)

__all__ = ["MongoProvider", "MongoSourceParams", "MongoTargetParams"]
