"""Minimal BSON codec (the subset MongoDB's commands and documents use).

Types: double, string, document, array, binary, ObjectId, bool, UTC
datetime, null, int32, timestamp, int64, decimal128 (passed through as
bytes).  Unknown element types raise — silent truncation would corrupt
document streams.
"""

from __future__ import annotations

import struct
from typing import Any


class ObjectId:
    __slots__ = ("raw",)

    def __init__(self, raw: bytes):
        if len(raw) != 12:
            raise ValueError("ObjectId must be 12 bytes")
        self.raw = raw

    def __repr__(self) -> str:
        return f"ObjectId({self.raw.hex()})"

    def __str__(self) -> str:
        return self.raw.hex()

    def __eq__(self, other) -> bool:
        return isinstance(other, ObjectId) and other.raw == self.raw

    def __hash__(self) -> int:
        return hash(self.raw)


class Binary:
    __slots__ = ("subtype", "raw")

    def __init__(self, raw: bytes, subtype: int = 0):
        self.raw = raw
        self.subtype = subtype


class Timestamp:
    """BSON timestamp (oplog ordinal), distinct from UTC datetime."""

    __slots__ = ("t", "i")

    def __init__(self, t: int, i: int):
        self.t = t
        self.i = i

    def __repr__(self) -> str:
        return f"Timestamp({self.t}, {self.i})"


class UTCDateTime:
    """Milliseconds since epoch (kept numeric; no tz library games)."""

    __slots__ = ("ms",)

    def __init__(self, ms: int):
        self.ms = ms


def encode(doc: dict) -> bytes:
    out = bytearray()
    for key, value in doc.items():
        out += _encode_element(key, value)
    return struct.pack("<i", len(out) + 5) + bytes(out) + b"\x00"


def _encode_element(key: str, v: Any) -> bytes:
    name = key.encode() + b"\x00"
    if isinstance(v, bool):  # before int!
        return b"\x08" + name + (b"\x01" if v else b"\x00")
    if isinstance(v, float):
        return b"\x01" + name + struct.pack("<d", v)
    if isinstance(v, str):
        b = v.encode()
        return b"\x02" + name + struct.pack("<i", len(b) + 1) + b + b"\x00"
    if isinstance(v, dict):
        return b"\x03" + name + encode(v)
    if isinstance(v, (list, tuple)):
        return b"\x04" + name + encode(
            {str(i): item for i, item in enumerate(v)}
        )
    if isinstance(v, Binary):
        return b"\x05" + name + struct.pack("<iB", len(v.raw), v.subtype) \
            + v.raw
    if isinstance(v, bytes):
        return b"\x05" + name + struct.pack("<iB", len(v), 0) + v
    if isinstance(v, ObjectId):
        return b"\x07" + name + v.raw
    if isinstance(v, UTCDateTime):
        return b"\x09" + name + struct.pack("<q", v.ms)
    if v is None:
        return b"\x0a" + name
    if isinstance(v, Timestamp):
        return b"\x11" + name + struct.pack("<II", v.i, v.t)
    if isinstance(v, int):
        if -(2**31) <= v < 2**31:
            return b"\x10" + name + struct.pack("<i", v)
        return b"\x12" + name + struct.pack("<q", v)
    raise TypeError(f"bson: cannot encode {type(v).__name__}")


def decode(data: bytes, offset: int = 0) -> tuple[dict, int]:
    """Decode one document at offset; returns (doc, end_offset)."""
    length = struct.unpack_from("<i", data, offset)[0]
    end = offset + length
    pos = offset + 4
    doc: dict = {}
    while pos < end - 1:
        etype = data[pos]
        pos += 1
        nul = data.index(b"\x00", pos)
        key = data[pos:nul].decode()
        pos = nul + 1
        doc[key], pos = _decode_value(etype, data, pos)
    return doc, end


def _decode_value(etype: int, data: bytes, pos: int) -> tuple[Any, int]:
    if etype == 0x01:
        return struct.unpack_from("<d", data, pos)[0], pos + 8
    if etype == 0x02:
        ln = struct.unpack_from("<i", data, pos)[0]
        s = data[pos + 4:pos + 4 + ln - 1].decode("utf-8", "replace")
        return s, pos + 4 + ln
    if etype == 0x03:
        return decode(data, pos)
    if etype == 0x04:
        arr_doc, end = decode(data, pos)
        return [arr_doc[k] for k in sorted(arr_doc, key=int)], end
    if etype == 0x05:
        ln, subtype = struct.unpack_from("<iB", data, pos)
        raw = bytes(data[pos + 5:pos + 5 + ln])
        return (raw if subtype == 0 else Binary(raw, subtype)), pos + 5 + ln
    if etype == 0x06:  # undefined (deprecated)
        return None, pos
    if etype == 0x07:
        return ObjectId(bytes(data[pos:pos + 12])), pos + 12
    if etype == 0x08:
        return data[pos] == 1, pos + 1
    if etype == 0x09:
        return UTCDateTime(struct.unpack_from("<q", data, pos)[0]), pos + 8
    if etype == 0x0A:
        return None, pos
    if etype == 0x0B:  # regex: two cstrings
        n1 = data.index(b"\x00", pos)
        n2 = data.index(b"\x00", n1 + 1)
        return {"$regex": data[pos:n1].decode(),
                "$options": data[n1 + 1:n2].decode()}, n2 + 1
    if etype == 0x10:
        return struct.unpack_from("<i", data, pos)[0], pos + 4
    if etype == 0x11:
        i, t = struct.unpack_from("<II", data, pos)
        return Timestamp(t, i), pos + 8
    if etype == 0x12:
        return struct.unpack_from("<q", data, pos)[0], pos + 8
    if etype == 0x13:  # decimal128: surface raw bytes
        return Binary(bytes(data[pos:pos + 16]), 0x13), pos + 16
    raise ValueError(f"bson: unsupported element type 0x{etype:02x}")


def to_jsonish(v: Any) -> Any:
    """BSON value -> JSON-serializable canonical form (for ANY columns)."""
    if isinstance(v, dict):
        return {k: to_jsonish(x) for k, x in v.items()}
    if isinstance(v, list):
        return [to_jsonish(x) for x in v]
    if isinstance(v, ObjectId):
        return {"$oid": str(v)}
    if isinstance(v, UTCDateTime):
        return {"$date": v.ms}
    if isinstance(v, Timestamp):
        return {"$timestamp": {"t": v.t, "i": v.i}}
    if isinstance(v, Binary):
        import base64

        return {"$binary": base64.b64encode(v.raw).decode(),
                "$type": v.subtype}
    if isinstance(v, bytes):
        import base64

        return {"$binary": base64.b64encode(v).decode(), "$type": 0}
    return v
