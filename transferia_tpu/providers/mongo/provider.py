"""Mongo storage/sink/change-stream source.

Documents map to the reference's mongo row shape: `_id` (key, canonical
utf8 via extended-JSON for ObjectIds) + `document` (ANY).  Snapshot loads
page per collection (each collection is a parallelization unit,
parallelization_unit*.go); replication tails a cluster-wide change stream
with resume tokens checkpointed through the coordinator; the sink applies
replace/delete bulk ops (sink_bulk_operations.go).
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.change_item import ChangeItem, OldKeys
from transferia_tpu.abstract.interfaces import (
    AsyncSink,
    Batch,
    Pusher,
    ShardingStorage,
    Sinker,
    Source,
    Storage,
    TableInfo,
    is_columnar,
)
from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.abstract.schema import (
    CanonicalType,
    ColSchema,
    TableID,
    TableSchema,
)
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch
from transferia_tpu.coordinator.interface import Coordinator
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.mongo import bson
from transferia_tpu.providers.mongo.wire import MongoConnection, MongoError
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)

logger = logging.getLogger(__name__)

DOC_SCHEMA = TableSchema([
    ColSchema("_id", CanonicalType.UTF8, primary_key=True),
    ColSchema("document", CanonicalType.ANY),
])


@register_endpoint
@dataclass
class MongoSourceParams(EndpointParams):
    PROVIDER = "mongo"
    IS_SOURCE = True

    host: str = "localhost"
    port: int = 27017
    user: str = ""
    password: str = ""
    auth_db: str = "admin"
    database: str = ""
    collections: list[str] = field(default_factory=list)  # [] = all
    batch_rows: int = 1000
    shard_parts: int = 0   # split big collections by _id ranges when > 1


@register_endpoint
@dataclass
class MongoTargetParams(EndpointParams):
    PROVIDER = "mongo"
    IS_TARGET = True

    host: str = "localhost"
    port: int = 27017
    user: str = ""
    password: str = ""
    auth_db: str = "admin"
    database: str = ""      # "" -> use the item's namespace


def _conn(params) -> MongoConnection:
    return MongoConnection(
        host=params.host, port=params.port, user=params.user,
        password=params.password, auth_db=params.auth_db,
    ).connect()


def _id_str(v) -> str:
    return json.dumps(bson.to_jsonish(v), sort_keys=True, default=str) \
        if not isinstance(v, (str, int, float)) else str(v)


def _docs_to_batch(tid: TableID, docs: list[dict]) -> ColumnBatch:
    return ColumnBatch.from_pydict(tid, DOC_SCHEMA, {
        "_id": [_id_str(d.get("_id")) for d in docs],
        "document": [bson.to_jsonish(d) for d in docs],
    })


class MongoStorage(Storage, ShardingStorage):
    def __init__(self, params: MongoSourceParams):
        self.params = params
        self._c: Optional[MongoConnection] = None

    @property
    def conn(self) -> MongoConnection:
        if self._c is None:
            self._c = _conn(self.params)
        return self._c

    def close(self) -> None:
        if self._c is not None:
            self._c.close()
            self._c = None

    def _collections(self) -> list[str]:
        if self.params.collections:
            return self.params.collections
        return self.conn.list_collections(self.params.database)

    def table_list(self, include=None):
        out = {}
        for coll in self._collections():
            tid = TableID(self.params.database, coll)
            if include and not any(tid.include_matches(p) for p in include):
                continue
            out[tid] = TableInfo(
                eta_rows=self.conn.count(self.params.database, coll),
                schema=DOC_SCHEMA,
            )
        return out

    def table_schema(self, table: TableID) -> TableSchema:
        return DOC_SCHEMA

    def exact_table_rows_count(self, table: TableID) -> int:
        return self.conn.count(table.namespace, table.name)

    def shard_table(self, table: TableDescription) -> list[TableDescription]:
        """_id-range splits (reference parallelization_unit*.go): walk the
        sorted _id index and cut shard_parts ranges.  Only JSON-safe _id
        types (str/int/float) split — exotic ids keep one part (the filter
        travels as a string through the coordinator)."""
        parts = self.params.shard_parts
        total = table.eta_rows or self.exact_table_rows_count(table.id)
        if parts <= 1 or total < parts * 2:
            return [table]
        chunk = (total + parts - 1) // parts
        # one serial _id-projection pre-pass (analogous to an index scan;
        # the reference's splitVector metadata path needs admin rights) —
        # large batches keep round-trips low.  EVERY id's type is checked:
        # MongoDB range queries are type-bracketed, so a mixed-type
        # collection split at e.g. [25, "s0"] would silently drop the
        # numbers above 25 — mixed types refuse to split.
        boundaries: list = []
        seen = 0
        splittable = True
        id_type = None
        for docs in self.conn.find_all(
                table.id.namespace, table.id.name,
                sort={"_id": 1}, projection={"_id": 1},
                batch_size=max(self.params.batch_rows, 10_000)):
            for d in docs:
                v = d.get("_id")
                t = (int if isinstance(v, int)
                     and not isinstance(v, bool) else type(v))
                t = int if t is float else t  # numbers compare cross-type
                if id_type is None:
                    id_type = t
                if t is not id_type or not isinstance(v, (str, int,
                                                          float)) or \
                        isinstance(v, bool):
                    splittable = False
                    break
                if seen and seen % chunk == 0:
                    boundaries.append(v)
                seen += 1
            if not splittable:
                break
        if not splittable or not boundaries:
            return [table]
        import json as _json

        edges = [None] + boundaries + [None]
        out = []
        for i in range(len(edges) - 1):
            rng = {}
            if edges[i] is not None:
                rng["gte"] = edges[i]
            if edges[i + 1] is not None:
                rng["lt"] = edges[i + 1]
            out.append(TableDescription(
                id=table.id, filter=f"idrange:{_json.dumps(rng)}",
                eta_rows=chunk))
        return out

    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        conn = _conn(self.params)  # dedicated cursor per part
        filt = None
        if table.filter.startswith("idrange:"):
            import json as _json

            rng = _json.loads(table.filter[len("idrange:"):])
            cond = {}
            if "gte" in rng:
                cond["$gte"] = rng["gte"]
            if "lt" in rng:
                cond["$lt"] = rng["lt"]
            filt = {"_id": cond}
        try:
            for docs in conn.find_all(
                    table.id.namespace, table.id.name,
                    filter=filt, sort={"_id": 1},
                    batch_size=self.params.batch_rows):
                pusher(_docs_to_batch(table.id, docs))
        finally:
            conn.close()

    def ping(self) -> None:
        self.conn.command("admin", {"ping": 1})


class MongoChangeStreamSource(Source):
    """Cluster/database change stream with resume-token checkpoints
    (change_stream.go)."""

    STATE_KEY = "mongo_resume_token"

    def __init__(self, params: MongoSourceParams, transfer_id: str,
                 coordinator: Optional[Coordinator]):
        self.params = params
        self.transfer_id = transfer_id
        self.cp = coordinator
        self._stop = threading.Event()

    def run(self, sink: AsyncSink) -> None:
        conn = _conn(self.params)
        try:
            stage: dict = {"$changeStream": {"fullDocument": "updateLookup"}}
            if not self.params.database:
                # no database scoping: watch the whole cluster (a db-level
                # stream on admin would silently see nothing)
                stage["$changeStream"]["allChangesForCluster"] = True
            if self.cp is not None:
                token = self.cp.get_transfer_state(self.transfer_id).get(
                    self.STATE_KEY
                )
                if token:
                    stage["$changeStream"]["resumeAfter"] = {"_data":
                                                             token}
            out = conn.command(self.params.database or "admin", {
                "aggregate": 1,
                "pipeline": [stage],
                "cursor": {"batchSize": self.params.batch_rows},
            })
            cursor = out["cursor"]
            cid = cursor.get("id", 0)
            pending = cursor.get("firstBatch", [])
            while not self._stop.is_set():
                if pending:
                    items, token = self._decode_events(pending)
                    if items:
                        sink.async_push(items).result()
                    if token and self.cp is not None:
                        self.cp.set_transfer_state(
                            self.transfer_id, {self.STATE_KEY: token}
                        )
                    pending = []
                if not cid:
                    raise MongoError("change stream cursor closed")
                out = conn.command(self.params.database or "admin", {
                    "getMore": cid, "collection": "$cmd.aggregate",
                    "batchSize": self.params.batch_rows,
                    "maxTimeMS": 500,
                })
                cursor = out["cursor"]
                cid = cursor.get("id", 0)
                pending = cursor.get("nextBatch", [])
        finally:
            conn.close()

    def _decode_events(self, events: list[dict]
                       ) -> tuple[list[ChangeItem], Optional[str]]:
        items: list[ChangeItem] = []
        token = None
        for ev in events:
            token_doc = ev.get("_id") or {}
            token = token_doc.get("_data", token)
            op = ev.get("operationType")
            ns = ev.get("ns") or {}
            tid = TableID(ns.get("db", ""), ns.get("coll", ""))
            key_id = _id_str((ev.get("documentKey") or {}).get("_id"))
            if op in ("insert", "replace", "update"):
                doc = ev.get("fullDocument")
                if doc is None and op == "update":
                    # updateLookup raced a delete: upserting {} would wipe
                    # the target doc; the delete event follows anyway
                    logger.warning(
                        "mongo change stream: update for %s/%s lost its "
                        "fullDocument (deleted before lookup); skipping",
                        tid, key_id,
                    )
                    continue
                doc = doc or {}
                items.append(ChangeItem(
                    kind=Kind.INSERT if op == "insert" else Kind.UPDATE,
                    schema=tid.namespace, table=tid.name,
                    column_names=("_id", "document"),
                    column_values=(key_id, bson.to_jsonish(doc)),
                    table_schema=DOC_SCHEMA,
                    old_keys=OldKeys(("_id",), (key_id,))
                    if op != "insert" else OldKeys(),
                ))
            elif op == "delete":
                items.append(ChangeItem(
                    kind=Kind.DELETE,
                    schema=tid.namespace, table=tid.name,
                    table_schema=DOC_SCHEMA,
                    old_keys=OldKeys(("_id",), (key_id,)),
                ))
            elif op in ("drop", "dropDatabase", "rename", "invalidate"):
                logger.warning("mongo change stream: %s on %s", op, tid)
        return items, token

    def stop(self) -> None:
        self._stop.set()


class MongoSinker(Sinker):
    """Replace/delete bulk operations keyed on _id."""

    def __init__(self, params: MongoTargetParams):
        self.params = params
        self._c: Optional[MongoConnection] = None

    @property
    def conn(self) -> MongoConnection:
        if self._c is None:
            self._c = _conn(self.params)
        return self._c

    def close(self) -> None:
        if self._c is not None:
            self._c.close()
            self._c = None

    @staticmethod
    def _doc_of(it: ChangeItem) -> dict:
        doc = it.value("document")
        if isinstance(doc, dict):
            out = dict(doc)
        else:
            out = {"value": doc}
        out["_id"] = it.value("_id") or _id_str(out.get("_id"))
        return out

    def push(self, batch: Batch) -> None:
        items = batch.to_rows() if is_columnar(batch) else [
            it for it in batch if it.is_row_event()
        ]
        if not items:
            return
        by_coll: dict[tuple[str, str], list[ChangeItem]] = {}
        for it in items:
            db = self.params.database or it.table_id.namespace or "db"
            by_coll.setdefault((db, it.table_id.name), []).append(it)
        for (db, coll), rows in by_coll.items():
            # apply in item order: a delete followed by a re-insert of the
            # same _id must not be reordered into upsert-then-delete
            run_kind: Optional[bool] = None  # True = delete run
            run_ops: list[dict] = []

            def flush_run():
                nonlocal run_ops, run_kind
                if not run_ops:
                    return
                if run_kind:
                    self.conn.command(db, {"delete": coll,
                                           "deletes": run_ops})
                else:
                    self.conn.command(db, {"update": coll,
                                           "updates": run_ops})
                run_ops = []

            for it in rows:
                is_delete = it.kind == Kind.DELETE
                if run_kind is not None and is_delete != run_kind:
                    flush_run()
                run_kind = is_delete
                if is_delete:
                    key = it.effective_key()
                    run_ops.append({
                        "q": {"_id": key[0] if key else None}, "limit": 1,
                    })
                else:
                    doc = self._doc_of(it)
                    run_ops.append({
                        "q": {"_id": doc["_id"]}, "u": doc, "upsert": True,
                    })
            flush_run()


@register_provider
class MongoProvider(Provider):
    NAME = "mongo"

    def storage(self):
        if isinstance(self.transfer.src, MongoSourceParams):
            return MongoStorage(self.transfer.src)
        return None

    def source(self):
        if isinstance(self.transfer.src, MongoSourceParams):
            return MongoChangeStreamSource(
                self.transfer.src, self.transfer.id, self.coordinator
            )
        return None

    def sinker(self):
        if isinstance(self.transfer.dst, MongoTargetParams):
            return MongoSinker(self.transfer.dst)
        return None

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        params = self.transfer.src if isinstance(
            self.transfer.src, MongoSourceParams) else self.transfer.dst
        try:
            conn = _conn(params)
            conn.command("admin", {"ping": 1})
            conn.close()
            result.add("ping")
        except Exception as e:
            result.add("ping", e)
        return result
