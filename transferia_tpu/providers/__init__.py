"""Provider plugin registry (reference: pkg/providers/provider.go).

Providers register under a name and expose optional capability
constructors (Snapshot/Replication/Sinker/...); factories resolve them at
transfer build time.  Built-in providers self-register on import, mirroring
the reference's blank-import dataplane registration
(pkg/dataplane/providers.go:1-23).
"""

from transferia_tpu.providers.registry import (
    Provider,
    get_provider,
    register_provider,
    registered_providers,
)

__all__ = [
    "Provider",
    "get_provider",
    "register_provider",
    "registered_providers",
]


def load_builtin_providers() -> None:
    """Import all built-in providers (idempotent)."""
    from transferia_tpu.providers import (  # noqa: F401
        arrow_ipc,
        file as file_p,
        flight,
        memory,
        mq,
        sample,
        stdout,
    )
    from transferia_tpu.providers import (  # noqa: F401
        airbyte,
        clickhouse,
        elastic,
        eventhub,
        greenplum,
        kafka,
        kinesis,
        logbroker,
        misc_providers,
        mongo,
        mysql,
        oracle,
        postgres,
        s3,
        ydb,
        yds,
        yt,
    )
