"""Native parquet row-group reader: column chunks -> Columns directly.

The snapshot north-star's host decode stage (reference methodology:
docs/benchmarks.md rows/sec on ClickBench `hits`) is bound by parquet
decode on a single core.  This reader pairs pyarrow's *metadata* (footer
parsing, row-group/chunk layout, schema) with the C++ chunk decoder
(native/parquetdec.cpp): snappy + PLAIN/RLE_DICTIONARY pages go straight
into the engine's columnar layout — flat (data, offsets) buffers, or
int32 codes + pool adopted as DictEnc with no dictionary unification or
index materialization.  Anything outside the decoder's envelope
(unsupported codec/encoding/type, nested columns, v2 pages) falls back to
arrow per column, so the reader is never less capable than pyarrow.
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np

from transferia_tpu.abstract.schema import CanonicalType, TableSchema
from transferia_tpu.columnar.batch import Column, DictEnc, DictPool

logger = logging.getLogger(__name__)

# bench/diagnostic visibility: which columns fell out of the native
# envelope (and how often) — silent arrow fallbacks regress the headline
# without this.  Upload workers share a reader across threads, so the
# counter update takes a lock.
_fallback_columns: dict[str, int] = {}
_fallback_lock = __import__("threading").Lock()


def fallback_stats() -> dict[str, int]:
    with _fallback_lock:
        return dict(_fallback_columns)


def reset_fallback_stats() -> None:
    with _fallback_lock:
        _fallback_columns.clear()


_CODECS = {"UNCOMPRESSED": 0, "SNAPPY": 1}
_FIXED_WIDTH = {"INT32": 4, "INT64": 8, "FLOAT": 4, "DOUBLE": 8}

# physical view dtype per canonical type for fixed-width reinterpretation
_VIEW_DTYPES = {
    CanonicalType.INT8: (4, np.int32),
    CanonicalType.INT16: (4, np.int32),
    CanonicalType.INT32: (4, np.int32),
    CanonicalType.INT64: (8, np.int64),
    CanonicalType.UINT8: (4, np.uint32),
    CanonicalType.UINT16: (4, np.uint32),
    CanonicalType.UINT32: (4, np.uint32),
    CanonicalType.UINT64: (8, np.uint64),
    CanonicalType.FLOAT: (4, np.float32),
    CanonicalType.DOUBLE: (8, np.float64),
    CanonicalType.DATE: (4, np.int32),
    CanonicalType.DATETIME: (8, np.int64),
    CanonicalType.TIMESTAMP: (8, np.int64),
}


class NativeParquetReader:
    """Per-file reader; None from open() when the native lib is absent."""

    def __init__(self, path: str, pf, schema: TableSchema, cdll):
        self._pf = pf
        self._meta = pf.metadata
        self._schema = schema
        self._cdll = cdll
        self._mm = np.memmap(path, dtype=np.uint8, mode="r")
        # column index by name (flat schemas only — nested fall back)
        self._col_idx = {}
        for i in range(self._meta.num_columns):
            name = self._meta.row_group(0).column(i).path_in_schema
            self._col_idx[name] = i
        self._pq_schema = pf.schema
        # arrow logical types (timestamp units etc.)
        self._arrow_fields = {f.name: f for f in pf.schema_arrow}

    @classmethod
    def open(cls, path: str, pf,
             schema: TableSchema) -> Optional["NativeParquetReader"]:
        from transferia_tpu.native import lib as native_lib

        import os

        if os.environ.get("TRANSFERIA_TPU_NATIVE_PARQUET", "1") == "0":
            return None
        cdll = native_lib()
        if cdll is None or not hasattr(cdll, "pq_decode_fixed"):
            return None
        if pf.metadata.num_row_groups == 0:
            return None
        try:
            return cls(path, pf, schema, cdll)
        except (OSError, ValueError):
            return None

    # -- per-column decode ---------------------------------------------------
    def _chunk_range(self, col) -> tuple[int, int]:
        start = col.data_page_offset
        if (col.dictionary_page_offset is not None
                and col.dictionary_page_offset >= 0):
            start = min(start, col.dictionary_page_offset)
        return start, col.total_compressed_size

    def _decode_column(self, g: int, cs) -> Optional[Column]:
        """Native decode of one column chunk; None -> caller falls back."""
        idx = self._col_idx.get(cs.name)
        if idx is None:
            return None
        col = self._meta.row_group(g).column(idx)
        codec = _CODECS.get(col.compression)
        if codec is None:
            return None
        sc = self._pq_schema.column(idx)
        max_def = sc.max_definition_level
        max_rep = sc.max_repetition_level
        if max_rep != 0 or max_def > 1:
            return None
        n = col.num_values
        start, length = self._chunk_range(col)
        if start < 0 or start + length > len(self._mm):
            return None
        chunk = self._mm[start:start + length]
        ptype = col.physical_type
        validity = (np.empty(n, dtype=np.uint8) if max_def else None)
        if ptype in _FIXED_WIDTH:
            spec = _VIEW_DTYPES.get(cs.data_type)
            if spec is None:
                return None
            width, view_dt = spec
            if width != _FIXED_WIDTH[ptype]:
                return None
            out = np.empty(n * width, dtype=np.uint8)
            rc = self._cdll.pq_decode_fixed(
                np.ascontiguousarray(chunk), length, codec, width, n,
                max_def, out.ctypes.data,
                validity.ctypes.data if validity is not None else None)
            if rc != n:
                return None
            vals = out.view(view_dt)
            return self._finish_fixed(cs, vals, validity)
        if ptype == "BYTE_ARRAY" and cs.data_type.is_variable_width:
            return self._decode_bytearray(chunk, length, codec, n,
                                          max_def, col, cs, validity)
        return None

    def _finish_fixed(self, cs, vals: np.ndarray,
                      validity: Optional[np.ndarray]) -> Column:
        v = None
        if validity is not None and not validity.all():
            v = validity.astype(np.bool_)
        ct = cs.data_type
        f = self._arrow_fields.get(cs.name)
        if ct in (CanonicalType.DATETIME, CanonicalType.TIMESTAMP) \
                and f is not None:
            import pyarrow.types as pt

            unit = f.type.unit if pt.is_timestamp(f.type) else "us"
            vals = vals.astype(np.int64, copy=False)
            if ct == CanonicalType.DATETIME:
                div = {"s": 1, "ms": 1_000, "us": 1_000_000,
                       "ns": 1_000_000_000}[unit]
                vals = vals // div
            else:
                scale = {"s": 1_000_000, "ms": 1_000, "us": 1, "ns": 1}[unit]
                vals = (vals * scale if unit in ("s", "ms")
                        else vals // (1000 if unit == "ns" else 1))
        elif ct.np_dtype != vals.dtype:
            vals = vals.astype(ct.np_dtype)
        return Column(cs.name, ct, np.ascontiguousarray(vals), None, v)

    def _decode_bytearray(self, chunk, length, codec, n, max_def, col,
                          cs, validity) -> Optional[Column]:
        import ctypes

        cap = max(col.total_uncompressed_size, 4096)
        offsets = np.empty(n + 1, dtype=np.int32)
        codes = np.empty(n, dtype=np.int32)
        for _attempt in range(4):
            data = np.empty(cap, dtype=np.uint8)
            kind = ctypes.c_int32(-1)
            needed = ctypes.c_int64(0)
            rc = self._cdll.pq_decode_bytearray(
                np.ascontiguousarray(chunk), length, codec, n, max_def,
                data, cap, offsets, codes.ctypes.data,
                validity.ctypes.data if validity is not None else None,
                ctypes.byref(kind), ctypes.byref(needed))
            if rc == -2:  # grow
                cap = max(needed.value, cap * 2)
                continue
            if rc < 0:
                return None
            v = None
            if validity is not None and not validity.all():
                v = validity.astype(np.bool_)
            if kind.value == 1:
                # dict result: rc == n_pool; codes hold n_pool for nulls
                n_pool = rc
                pool_off = np.append(offsets[:n_pool + 1],
                                     offsets[n_pool]).astype(np.int32)
                pool_data = data[:offsets[n_pool]].copy()
                dpool = DictPool(pool_data, pool_off, null_code=n_pool)
                return Column(cs.name, cs.data_type, validity=v,
                              dict_enc=DictEnc(codes, pool=dpool))
            return Column(cs.name, cs.data_type, data[:rc].copy(),
                          offsets, v)
        return None

    # -- public --------------------------------------------------------------
    def read_row_group(self, g: int) -> dict[str, Column]:
        """All schema columns for one row group.

        Columns outside the native envelope (unsupported codec/encoding/
        type, nested, >2GiB flat) are filled through an arrow read of just
        those columns — the result is always complete."""
        cols: dict[str, Column] = {}
        fallback: list[str] = []
        for cs in self._schema:
            if cs.name not in self._col_idx:
                continue
            try:
                c = self._decode_column(g, cs)
            except Exception:  # corrupt chunk etc: arrow decides
                logger.debug("native decode failed for %s", cs.name,
                             exc_info=True)
                c = None
            if c is None:
                fallback.append(cs.name)
            else:
                cols[cs.name] = c
        if fallback:
            from transferia_tpu.columnar.batch import _arrow_to_column

            with _fallback_lock:
                for name in fallback:
                    _fallback_columns[name] = (
                        _fallback_columns.get(name, 0) + 1)

            tbl = self._pf.read_row_group(g, columns=fallback,
                                          use_threads=False)
            by_name = {cs.name: cs for cs in self._schema}
            for name in fallback:
                arr = tbl.column(name).combine_chunks()
                cols[name] = _arrow_to_column(by_name[name], arr)
        return cols


def slice_columns(cols: dict[str, Column], lo: int,
                  hi: int) -> dict[str, Column]:
    """Row-range views over decoded columns (no gathers).

    Fixed-width slices are numpy views; var-width rebases offsets (small
    copy); dictionary columns slice codes and share the pool — which is
    what makes per-batch slicing of a decoded row group nearly free."""
    out = {}
    for name, c in cols.items():
        validity = c.validity[lo:hi] if c.validity is not None else None
        if c.is_lazy_dict:
            out[name] = Column(
                name, c.ctype, validity=validity,
                dict_enc=DictEnc(c.dict_enc.indices[lo:hi],
                                 pool=c.dict_enc.pool))
        elif c.offsets is not None:
            base = int(c.offsets[lo])
            off = (c.offsets[lo:hi + 1] - base).astype(np.int32)
            out[name] = Column(name, c.ctype,
                               c.data[base:int(c.offsets[hi])], off,
                               validity)
        else:
            out[name] = Column(name, c.ctype, c.data[lo:hi], None,
                               validity)
    return out
