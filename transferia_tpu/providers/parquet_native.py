"""Native parquet row-group reader: column chunks -> Columns directly.

The snapshot north-star's host decode stage (reference methodology:
docs/benchmarks.md rows/sec on ClickBench `hits`) is bound by parquet
decode on a single core.  This reader pairs pyarrow's *metadata* (footer
parsing, row-group/chunk layout, schema) with the C++ chunk decoder
(native/parquetdec.cpp): pages go straight into the engine's columnar
layout — flat (data, offsets) buffers, or int32 codes + pool adopted as
DictEnc with no dictionary unification or index materialization.

The decode envelope: DataPage v1+v2; UNCOMPRESSED/SNAPPY/GZIP/ZSTD
codecs (GZIP and ZSTD ride dlopen'd system zlib/libzstd); PLAIN,
RLE_DICTIONARY, DELTA_BINARY_PACKED, DELTA_LENGTH_BYTE_ARRAY and
DELTA_BYTE_ARRAY encodings; BOOLEAN/INT32/INT64/FLOAT/DOUBLE/BYTE_ARRAY
physical types; flat schemas (max_def <= 1, no repetition).  Anything
outside falls back to arrow per column, so the reader is never less
capable than pyarrow.

All columns of a row group decode in ONE ctypes call
(pq_decode_rowgroup): the per-column Python + pyarrow-metadata overhead
was ~40% of decode wall on the wide ClickBench-shaped bench.  ctypes
releases the GIL for the call, so upload worker threads overlap decode
with sink pushes.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

import numpy as np

from transferia_tpu.abstract.schema import CanonicalType, TableSchema
from transferia_tpu.columnar.batch import Column, DictEnc, DictPool
from transferia_tpu.runtime import knobs

logger = logging.getLogger(__name__)


# -- per-file footer/metadata + memmap memoization ---------------------------
#
# Multi-part loads open the SAME file once per part: sharding reads the
# footer to enumerate row groups, then every part re-runs
# `ParquetFile.__init__` (a full thrift footer parse — 3.9% of the
# BENCH_r05 profile) and every NativeParquetReader re-creates the file
# memmap (1.6%).  Both are pure functions of (path, mtime_ns, size), so
# they memoize under that key; a rewritten file gets a fresh entry.
# Bounded FIFO; the lock guards the loader's concurrent part threads.

_FOOTER_CACHE: dict = {}     # (path, mtime_ns, size) -> FileMetaData
_MMAP_CACHE: dict = {}       # (path, mtime_ns, size) -> np.memmap
_FILE_CACHE_MAX = 32
_FILE_CACHE_LOCK = threading.Lock()


def _file_key(path: str) -> tuple:
    st = os.stat(path)
    return (os.path.abspath(path), st.st_mtime_ns, st.st_size)


def parquet_file_cached(path: str, read_dictionary=None):
    """A fresh pyarrow ParquetFile whose footer parses at most once per
    (path, mtime, size) — the FileMetaData is memoized and handed back
    to `ParquetFile(metadata=...)`, so each caller still gets its OWN
    reader object (pyarrow readers are not safe to share across part
    threads) without re-running the thrift parse per part.

    `read_dictionary` (a sequence of column names) makes the reader
    surface those columns as arrow DictionaryArrays instead of decoding
    dict pages to flat values — the arrow-path twin of the native
    decoder's DictEnc adoption (the importer then adopts the dictionary
    as a shared DictPool instead of re-encoding downstream)."""
    import pyarrow.parquet as pq

    kw = {}
    if read_dictionary:
        kw["read_dictionary"] = list(read_dictionary)
    key = _file_key(path)
    with _FILE_CACHE_LOCK:
        meta = _FOOTER_CACHE.get(key)
    if meta is not None:
        return pq.ParquetFile(path, metadata=meta, **kw)
    pf = pq.ParquetFile(path, **kw)
    with _FILE_CACHE_LOCK:
        while len(_FOOTER_CACHE) >= _FILE_CACHE_MAX:
            _FOOTER_CACHE.pop(next(iter(_FOOTER_CACHE)), None)
        _FOOTER_CACHE[key] = pf.metadata
    return pf


def parquet_metadata(path: str):
    """Memoized footer metadata only (sharding/row-count callers that
    never read pages skip constructing a reader entirely)."""
    key = _file_key(path)
    with _FILE_CACHE_LOCK:
        meta = _FOOTER_CACHE.get(key)
    if meta is not None:
        return meta
    return parquet_file_cached(path).metadata


def shared_memmap(path: str) -> np.ndarray:
    """One read-only memmap per (path, mtime, size), shared by every
    row-group reader of the file (readers only ever slice it)."""
    key = _file_key(path)
    with _FILE_CACHE_LOCK:
        mm = _MMAP_CACHE.get(key)
        if mm is not None:
            return mm
    mm = np.memmap(path, dtype=np.uint8, mode="r")
    with _FILE_CACHE_LOCK:
        hit = _MMAP_CACHE.get(key)
        if hit is not None:
            return hit
        while len(_MMAP_CACHE) >= _FILE_CACHE_MAX:
            _MMAP_CACHE.pop(next(iter(_MMAP_CACHE)), None)
        _MMAP_CACHE[key] = mm
    return mm


def reset_file_caches() -> None:
    with _FILE_CACHE_LOCK:
        _FOOTER_CACHE.clear()
        _MMAP_CACHE.clear()
        _PAGE_POOL_CACHE.clear()


# -- dict-page pool sharing --------------------------------------------------
#
# One decoded dict page -> one DictPool, shared by every reader of it.
# Two layers:
#  - identity: (path, mtime, size, column, dictionary_page_offset) — a
#    part re-decoding the SAME page (multi-part loads re-open each row
#    group's chunk once per part thread) reuses the pool with no digest;
#  - content (columnar/batch.intern_pool keyed by (path, column)): row
#    groups of one file usually carry byte-identical dict pages at
#    different offsets, so their pools converge on one object and the
#    pool-keyed memos (hexed HMAC pool, rowhash accumulators, device
#    digest matrices) amortize across the whole file, parts included.
_PAGE_POOL_CACHE: dict = {}
_PAGE_POOL_CACHE_MAX = 256

# copy-vs-view economics for the pool slice out of the cap-sized decode
# buffer: keeping a view is free NOW but pins the whole buffer (cap
# covers the code pages too) for as long as the pool lives — which,
# with pool sharing, is the whole transfer.  Keep the view only when
# the pinned remainder is small both relatively AND absolutely; the old
# `pool_bytes * 2 < nbytes` test alone kept views that pinned megabytes
# when the pool sat just under half the buffer.
_POOL_PIN_MAX_WASTE = 256 * 1024

# bench/diagnostic visibility: which columns fell out of the native
# envelope (and how often) — silent arrow fallbacks regress the headline
# without this.  Upload workers share a reader across threads, so the
# counter update takes a lock.
_fallback_columns: dict[str, int] = {}
_fallback_lock = threading.Lock()


def fallback_stats() -> dict[str, int]:
    with _fallback_lock:
        return dict(_fallback_columns)


def reset_fallback_stats() -> None:
    with _fallback_lock:
        _fallback_columns.clear()


# parquet CompressionCodec enum values (GZIP/ZSTD support is probed at
# runtime — they need the system zlib/libzstd)
_CODECS = {"UNCOMPRESSED": 0, "SNAPPY": 1, "GZIP": 2, "ZSTD": 6}
_FIXED_WIDTH = {"INT32": 4, "INT64": 8, "FLOAT": 4, "DOUBLE": 8}

# (physical width, output width, output view dtype) per canonical type.
# Narrow logical ints (int8/16) truncate DURING the native decode
# (little-endian low bytes == two's-complement truncation), so no numpy
# astype pass runs afterwards.
_VIEW_DTYPES = {
    CanonicalType.INT8: (4, 1, np.int8),
    CanonicalType.INT16: (4, 2, np.int16),
    CanonicalType.INT32: (4, 4, np.int32),
    CanonicalType.INT64: (8, 8, np.int64),
    CanonicalType.UINT8: (4, 1, np.uint8),
    CanonicalType.UINT16: (4, 2, np.uint16),
    CanonicalType.UINT32: (4, 4, np.uint32),
    CanonicalType.UINT64: (8, 8, np.uint64),
    CanonicalType.FLOAT: (4, 4, np.float32),
    CanonicalType.DOUBLE: (8, 8, np.float64),
    CanonicalType.DATE: (4, 4, np.int32),
    CanonicalType.DATETIME: (8, 8, np.int64),
    CanonicalType.TIMESTAMP: (8, 8, np.int64),
}

# task-array columns for pq_decode_rowgroup (native/parquetdec.cpp)
_T_OFF, _T_LEN, _T_CODEC, _T_KIND, _T_WIDTH, _T_NVAL, _T_MAXDEF = range(7)
_T_VALUES, _T_CAP, _T_OFFSETS, _T_CODES, _T_VALIDITY = range(7, 12)
_T_RESULT, _T_OUTKIND, _T_NEEDED, _T_NULLS = range(12, 16)
_T_FIELDS = 16

_E_GROW = -2


class NativeParquetReader:
    """Per-file reader; None from open() when the native lib is absent."""

    def __init__(self, path: str, pf, schema: TableSchema, cdll,
                 decode_threads: int = 1):
        self._pf = pf
        self._meta = pf.metadata
        self._schema = schema
        self._cdll = cdll
        self._decode_threads = max(1, int(decode_threads))
        self._mm = shared_memmap(path)
        self._path = path
        self._file_key = _file_key(path)
        self._fb_readers: dict[tuple, object] = {}
        # column index by name (flat schemas only — nested fall back)
        self._col_idx = {}
        for i in range(self._meta.num_columns):
            name = self._meta.row_group(0).column(i).path_in_schema
            self._col_idx[name] = i
        self._pq_schema = pf.schema
        # arrow logical types (timestamp units etc.)
        self._arrow_fields = {f.name: f for f in pf.schema_arrow}
        self._codec_ok_cache: dict[int, bool] = {}
        # (tasks template, specs, static fallback names) per row group
        self._task_cache: dict[int, tuple] = {}
        self._cache_lock = threading.Lock()

    @classmethod
    def open(cls, path: str, pf, schema: TableSchema,
             decode_threads: int = 1
             ) -> Optional["NativeParquetReader"]:
        from transferia_tpu.native import lib as native_lib

        if knobs.env_str("TRANSFERIA_TPU_NATIVE_PARQUET", "1") == "0":
            return None
        cdll = native_lib()
        if cdll is None or not hasattr(cdll, "pq_decode_rowgroup"):
            return None
        if pf.metadata.num_row_groups == 0:
            return None
        try:
            return cls(path, pf, schema, cdll, decode_threads)
        except (OSError, ValueError):
            return None

    def _codec_ok(self, codec: int) -> bool:
        ok = self._codec_ok_cache.get(codec)
        if ok is None:
            ok = bool(self._cdll.pq_codec_supported(codec))
            self._codec_ok_cache[codec] = ok
        return ok

    # -- row-group task preparation -----------------------------------------
    def _chunk_range(self, col) -> tuple[int, int]:
        start = col.data_page_offset
        if (col.dictionary_page_offset is not None
                and col.dictionary_page_offset >= 0):
            start = min(start, col.dictionary_page_offset)
        return start, col.total_compressed_size

    def _rg_tasks(self, g: int) -> tuple:
        with self._cache_lock:
            cached = self._task_cache.get(g)
        if cached is not None:
            return cached
        rg = self._meta.row_group(g)
        specs: list[tuple] = []
        static_fb: list[str] = []
        rows: list[list[int]] = []
        for cs in self._schema:
            idx = self._col_idx.get(cs.name)
            if idx is None:
                continue  # column absent from the file entirely
            col = rg.column(idx)
            codec = _CODECS.get(col.compression)
            sc = self._pq_schema.column(idx)
            kind = width = ow = None
            view_dt = None
            ok = (codec is not None and self._codec_ok(codec)
                  and sc.max_repetition_level == 0
                  and sc.max_definition_level <= 1)
            if ok:
                ptype = col.physical_type
                if ptype in _FIXED_WIDTH:
                    spec = _VIEW_DTYPES.get(cs.data_type)
                    if spec is None or spec[0] != _FIXED_WIDTH[ptype]:
                        ok = False
                    else:
                        kind, (width, ow, view_dt) = 0, spec
                elif (ptype == "BOOLEAN"
                      and cs.data_type == CanonicalType.BOOLEAN):
                    kind, width, ow, view_dt = 2, 1, 1, np.bool_
                elif (ptype == "BYTE_ARRAY"
                      and cs.data_type.is_variable_width):
                    kind, width, ow = 1, 0, 0
                else:
                    ok = False
            if ok:
                start, length = self._chunk_range(col)
                if start < 0 or start + length > len(self._mm):
                    ok = False
            if not ok:
                static_fb.append(cs.name)
                continue
            n = col.num_values
            max_def = sc.max_definition_level
            # field 8: data cap for byte arrays, output width for fixed
            cap = (max(col.total_uncompressed_size, 4096)
                   if kind == 1 else ow)
            rows.append([start, length, codec, kind, width, n, max_def,
                         0, cap, 0, 0, 0, 0, 0, 0, 0])
            dict_off = (col.dictionary_page_offset
                        if col.dictionary_page_offset is not None else -1)
            specs.append((cs, kind, ow, n, max_def, cap, view_dt,
                          dict_off))
        tasks = (np.array(rows, dtype=np.int64)
                 if rows else np.zeros((0, _T_FIELDS), dtype=np.int64))
        out = (tasks, specs, static_fb)
        with self._cache_lock:
            self._task_cache[g] = out
        return out

    # -- per-column post-processing -----------------------------------------
    def _finish_fixed(self, cs, vals: np.ndarray,
                      validity: Optional[np.ndarray]) -> Column:
        v = None
        if validity is not None:
            v = validity.astype(np.bool_)
        ct = cs.data_type
        f = self._arrow_fields.get(cs.name)
        if ct in (CanonicalType.DATETIME, CanonicalType.TIMESTAMP) \
                and f is not None:
            import pyarrow.types as pt

            unit = f.type.unit if pt.is_timestamp(f.type) else "us"
            vals = vals.astype(np.int64, copy=False)
            if ct == CanonicalType.DATETIME:
                div = {"s": 1, "ms": 1_000, "us": 1_000_000,
                       "ns": 1_000_000_000}[unit]
                vals = vals // div
            else:
                scale = {"s": 1_000_000, "ms": 1_000, "us": 1, "ns": 1}[unit]
                vals = (vals * scale if unit in ("s", "ms")
                        else vals // (1000 if unit == "ns" else 1))
        elif ct.np_dtype != vals.dtype:
            vals = vals.astype(ct.np_dtype)
        return Column(cs.name, ct, np.ascontiguousarray(vals), None, v)

    def _finish_bytearray(self, cs, rc: int, out_kind: int, n: int,
                          data: np.ndarray, offsets: np.ndarray,
                          codes: np.ndarray,
                          validity: Optional[np.ndarray],
                          dict_off: int = -1) -> Column:
        v = validity.astype(np.bool_) if validity is not None else None
        if out_kind == 1:
            # dict result: rc == n_pool; codes hold n_pool for nulls
            dpool, remap = self._adopt_dict_page(cs, rc, data, offsets,
                                                 dict_off)
            if remap is not None:
                # order-insensitive sharing: this page carries the
                # canonical pool's values in a different first-
                # occurrence order — rewrite the codes onto it
                codes = remap[codes]
            return Column(cs.name, cs.data_type, validity=v,
                          dict_enc=DictEnc(codes, pool=dpool))
        flat = data[:rc]
        if rc * 2 < data.nbytes:
            flat = flat.copy()
        return Column(cs.name, cs.data_type, flat, offsets, v)

    def _adopt_dict_page(self, cs, n_pool: int, data: np.ndarray,
                         offsets: np.ndarray, dict_off: int
                         ) -> tuple[DictPool, Optional[np.ndarray]]:
        """Decoded dict page -> (shared DictPool, optional code remap).

        Sharing layers (module cache comment): identity by page offset;
        then order-INSENSITIVE value matching against the column's
        canonical pool — parquet writers build each row group's
        dictionary in first-occurrence order, so pages across row
        groups usually carry the same value SET permuted; a remap table
        rewrites this page's codes onto the canonical pool (one
        O(values) lookup per page, O(rows) int32 gather) so the
        pool-keyed memos amortize file-wide; exact-content interning
        covers the first page / changed dictionaries."""
        from transferia_tpu.chaos.failpoints import failpoint
        from transferia_tpu.columnar.batch import intern_peek, intern_pool
        from transferia_tpu.stats import trace
        from transferia_tpu.stats.trace import TELEMETRY

        failpoint("decode.dict_adopt")
        intern_key = self._file_key + (cs.name,)
        page_key = None
        if dict_off >= 0:
            page_key = self._file_key + (cs.name, dict_off)
            with _FILE_CACHE_LOCK:
                hit = _PAGE_POOL_CACHE.get(page_key)
            if hit is not None:
                TELEMETRY.record_pool_share_hit()
                return hit
        pool_off = np.append(offsets[:n_pool + 1],
                             offsets[n_pool]).astype(np.int32)
        pool_bytes = int(offsets[n_pool])
        trace.instant("dict_adopt", col=cs.name, values=n_pool,
                      bytes=pool_bytes)
        canon = intern_peek(intern_key)
        if canon is not None:
            remap = _remap_codes(canon, data, offsets, n_pool)
            if remap is not None:
                TELEMETRY.record_pool_share_hit()
                if np.array_equal(remap,
                                  np.arange(n_pool + 1,
                                            dtype=np.int32)):
                    remap = None  # identical order: skip the gather
                out = (canon, remap)
                self._cache_page_pool(page_key, out)
                return out

        def finalize(pdata, poff):
            # the pool slice views the cap-sized decode buffer (cap
            # covers the code pages too): keeping the view pins the
            # whole buffer for the pool's lifetime, so copy out unless
            # the pinned remainder is small relatively AND absolutely
            waste = int(data.nbytes) - pool_bytes
            if pool_bytes * 2 < data.nbytes \
                    or waste > _POOL_PIN_MAX_WASTE:
                TELEMETRY.record_pool_buffer(copied=pool_bytes)
                return pdata.copy(), poff
            TELEMETRY.record_pool_buffer(pinned=waste)
            return pdata, poff

        dpool = intern_pool(intern_key, data[:pool_bytes], pool_off,
                            null_code=n_pool, finalize=finalize)
        out = (dpool, None)
        self._cache_page_pool(page_key, out)
        return out

    @staticmethod
    def _cache_page_pool(page_key, entry) -> None:
        if page_key is None:
            return
        with _FILE_CACHE_LOCK:
            while len(_PAGE_POOL_CACHE) >= _PAGE_POOL_CACHE_MAX:
                _PAGE_POOL_CACHE.pop(next(iter(_PAGE_POOL_CACHE)), None)
            _PAGE_POOL_CACHE[page_key] = entry

    def _retry_bytearray(self, g: int, cs, cap: int) -> Optional[Column]:
        """GROW retry: single-column decode with an enlarged data cap."""
        import ctypes

        idx = self._col_idx[cs.name]
        col = self._meta.row_group(g).column(idx)
        codec = _CODECS.get(col.compression)
        if codec is None:
            return None
        dict_off = (col.dictionary_page_offset
                    if col.dictionary_page_offset is not None else -1)
        sc = self._pq_schema.column(idx)
        max_def = sc.max_definition_level
        n = col.num_values
        start, length = self._chunk_range(col)
        chunk = np.ascontiguousarray(self._mm[start:start + length])
        # the legacy single-column ABI seeds validity all-defined itself
        validity = np.empty(n, dtype=np.uint8) if max_def else None
        offsets = np.empty(n + 1, dtype=np.int32)
        codes = np.empty(n, dtype=np.int32)
        for _attempt in range(4):
            data = np.empty(cap, dtype=np.uint8)
            kind = ctypes.c_int32(-1)
            needed = ctypes.c_int64(0)
            rc = self._cdll.pq_decode_bytearray(
                chunk, length, codec, n, max_def,
                data, cap, offsets, codes.ctypes.data,
                validity.ctypes.data if validity is not None else None,
                ctypes.byref(kind), ctypes.byref(needed))
            if rc == _E_GROW:
                cap = max(needed.value, cap * 2)
                continue
            if rc < 0:
                return None
            v = validity
            if v is not None and v.all():
                v = None
            return self._finish_bytearray(cs, rc, kind.value, n, data,
                                          offsets, codes, v, dict_off)
        return None

    def _decode_tasks(self, tasks: np.ndarray, n: int) -> None:
        """Run the native decoder over the task rows, column-parallel
        when decode_threads > 1.  Task rows are independent (each
        decodes one column chunk into buffers only it points at) and
        pq_decode_rowgroup releases the GIL, so K threads decode K
        columns genuinely in parallel.  K=1 is today's single batched
        call, byte for byte.

        Work is handed out one column at a time from a largest-
        compressed-chunk-first order (LPT balancing: one 20MB URL
        column must not serialize behind 60 already-claimed int8s);
        the per-call ctypes overhead is microseconds against multi-ms
        chunk decodes, so per-column granularity costs nothing."""
        k = min(self._decode_threads, n)
        if k <= 1:
            if n:
                self._cdll.pq_decode_rowgroup(self._mm, len(self._mm),
                                              tasks, n)
            return
        order = iter(np.argsort(-tasks[:, _T_LEN], kind="stable"))
        errors: list[BaseException] = []

        def run() -> None:
            try:
                while True:
                    # next() on a shared iterator is atomic under the GIL
                    i = next(order, None)
                    if i is None:
                        return
                    self._cdll.pq_decode_rowgroup(
                        self._mm, len(self._mm), tasks[i:i + 1], 1)
            except BaseException as e:  # ctypes arg errors: re-raise below
                errors.append(e)

        threads = [threading.Thread(target=run, name=f"pq-decode-{j}",
                                    daemon=True) for j in range(k - 1)]
        for t in threads:
            t.start()
        run()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

    # -- public --------------------------------------------------------------
    def read_row_group(self, g: int) -> dict[str, Column]:
        """All schema columns for one row group.

        Columns outside the native envelope (unsupported codec/encoding/
        type, nested, >2GiB flat) are filled through an arrow read of just
        those columns — the result is always complete."""
        from transferia_tpu.chaos.failpoints import failpoint

        failpoint("decode.native.rowgroup")
        template, specs, static_fb = self._rg_tasks(g)
        tasks = template.copy()
        holds: list[tuple] = []
        for i, (cs, kind, ow, n, max_def, cap, view_dt,
                _dict_off) in enumerate(specs):
            if kind == 1:
                data = np.empty(cap, dtype=np.uint8)
                offsets = np.empty(n + 1, dtype=np.int32)
                codes = np.empty(n, dtype=np.int32)
                tasks[i, _T_VALUES] = data.ctypes.data
                tasks[i, _T_OFFSETS] = offsets.ctypes.data
                tasks[i, _T_CODES] = codes.ctypes.data
                bufs = (data, offsets, codes)
            else:
                out = np.empty(n, dtype=view_dt)
                tasks[i, _T_VALUES] = out.ctypes.data
                bufs = (out,)
            if max_def:
                val = np.empty(n, dtype=np.uint8)
                tasks[i, _T_VALIDITY] = val.ctypes.data
            else:
                val = None
            holds.append((bufs, val))
        from transferia_tpu.stats import trace

        with trace.span("native_rowgroup_decode", group=g,
                        cols=len(specs)):
            self._decode_tasks(tasks, len(specs))
        cols: dict[str, Column] = {}
        fallback: list[str] = list(static_fb)
        for i, (cs, kind, ow, n, max_def, cap, view_dt,
                dict_off) in enumerate(specs):
            rc = int(tasks[i, _T_RESULT])
            nulls = int(tasks[i, _T_NULLS])
            bufs, val = holds[i]
            validity = val if (max_def and nulls > 0) else None
            try:
                if kind == 1:
                    if rc == _E_GROW:
                        c = self._retry_bytearray(
                            g, cs, max(int(tasks[i, _T_NEEDED]), cap * 2))
                    elif rc < 0:
                        c = None
                    else:
                        c = self._finish_bytearray(
                            cs, rc, int(tasks[i, _T_OUTKIND]), n,
                            bufs[0], bufs[1], bufs[2], validity,
                            dict_off)
                elif rc != n:
                    c = None
                elif kind == 2:
                    c = Column(cs.name, cs.data_type, bufs[0], None,
                               validity.astype(np.bool_)
                               if validity is not None else None)
                else:
                    c = self._finish_fixed(cs, bufs[0], validity)
            except Exception:  # corrupt chunk etc: arrow decides
                logger.debug("native decode failed for %s", cs.name,
                             exc_info=True)
                c = None
            if c is None:
                fallback.append(cs.name)
            else:
                cols[cs.name] = c
        if fallback:
            from transferia_tpu.columnar.batch import _arrow_to_column

            with _fallback_lock:
                for name in fallback:
                    _fallback_columns[name] = (
                        _fallback_columns.get(name, 0) + 1)

            by_name = {cs.name: cs for cs in self._schema}
            # dict pages of var-width fallback columns stay encoded:
            # the dict-preserving reader surfaces DictionaryArrays that
            # _arrow_to_column adopts as shared DictPools — the arrow
            # escape hatch no longer flattens what the rest of the
            # pipeline would immediately re-encode
            pf = self._fallback_reader(dict_encoded_columns(
                self._meta,
                [name for name in fallback
                 if by_name[name].data_type.is_variable_width]))
            tbl = pf.read_row_group(g, columns=fallback,
                                    use_threads=False)
            for name in fallback:
                arr = tbl.column(name).combine_chunks()
                cols[name] = _arrow_to_column(by_name[name], arr)
        return cols

    def _fallback_reader(self, dict_cols: tuple):
        """Memoized arrow reader for fallback reads; dict_cols surface
        as DictionaryArrays (empty tuple -> the plain shared reader)."""
        if not dict_cols:
            return self._pf
        with self._cache_lock:
            pf = self._fb_readers.get(dict_cols)
        if pf is None:
            pf = parquet_file_cached(self._path,
                                     read_dictionary=dict_cols)
            with self._cache_lock:
                pf = self._fb_readers.setdefault(dict_cols, pf)
        return pf


# order-insensitive code remap onto a canonical pool: the guard-chain
# and byte-exact verification live with the intern machinery in
# columnar/batch.py (shared with the arrow dictionary adoption path)
from transferia_tpu.columnar.batch import remap_codes_onto as _remap_codes


def dict_encoded_columns(meta, names) -> tuple:
    """The subset of `names` whose chunks carry a dictionary encoding
    (RLE/PLAIN_DICTIONARY) in EVERY row group — the columns worth
    reading with `read_dictionary`.  The all-groups quantifier matters:
    `read_dictionary` applies file-wide, and a writer whose dictionary
    page overflowed partway (dictionary_pagesize_limit) leaves later
    row groups PLAIN — forcing dictionary reads there would make arrow
    BUILD a dictionary for a high-cardinality column, a pure loss."""
    if meta.num_row_groups == 0:
        return ()
    rg0 = meta.row_group(0)
    by_name = {}
    for i in range(meta.num_columns):
        by_name[rg0.column(i).path_in_schema] = i
    out = []
    for name in names:
        idx = by_name.get(name)
        if idx is None:
            continue
        ok = True
        for g in range(meta.num_row_groups):
            encs = meta.row_group(g).column(idx).encodings
            if "RLE_DICTIONARY" not in encs \
                    and "PLAIN_DICTIONARY" not in encs:
                ok = False
                break
        if ok:
            out.append(name)
    return tuple(sorted(out))


def slice_columns(cols: dict[str, Column], lo: int,
                  hi: int) -> dict[str, Column]:
    """Row-range views over decoded columns (no gathers).

    Fixed-width slices are numpy views; var-width rebases offsets (small
    copy); dictionary columns slice codes and share the pool — which is
    what makes per-batch slicing of a decoded row group nearly free."""
    out = {}
    for name, c in cols.items():
        validity = c.validity[lo:hi] if c.validity is not None else None
        if c.is_lazy_dict:
            out[name] = Column(
                name, c.ctype, validity=validity,
                dict_enc=DictEnc(c.dict_enc.indices[lo:hi],
                                 pool=c.dict_enc.pool))
        elif c.offsets is not None:
            base = int(c.offsets[lo])
            if base == 0 and c.offsets.dtype == np.int32:
                # first batch of every group: offsets are already
                # zero-based — the view costs nothing, the astype copies
                off = c.offsets[lo:hi + 1]
            else:
                off = (c.offsets[lo:hi + 1] - base).astype(np.int32)
            out[name] = Column(name, c.ctype,
                               c.data[base:int(c.offsets[hi])], off,
                               validity)
        else:
            out[name] = Column(name, c.ctype, c.data[lo:hi], None,
                               validity)
    return out
