"""S3 / object-storage provider.

Reference parity: pkg/providers/s3/ — snapshot source with format readers
(parquet/csv/jsonl/line/nginx/proto via providers/s3readers.py, schema
inference per reader/abstract.go:40-52), the snapshot/replication sinks
with file splitting (sink/file_splitter.go), and a replication source
(providers/s3source.py): set `event_source: sqs` (bucket notifications
through an SQS queue, s3/source/ + object_fetcher_sqs.go) or
`event_source: poll` (listing watermark in the coordinator state,
object_fetcher_poller.go).  Storage access goes through fsspec, so the
same provider serves s3://, gs://, and file:// URLs depending on which
backends the environment ships.  Parquet objects stream row-group-parallel
straight into columnar batches — the ClickBench snapshot path.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.abstract.interfaces import (
    AsyncPartDiscovery,
    Batch,
    Pusher,
    ShardingStorage,
    Sinker,
    Storage,
    TableInfo,
    is_columnar,
)
from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.abstract.schema import TableID, TableSchema
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch, arrow_to_table_schema
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)

logger = logging.getLogger(__name__)


@register_endpoint
@dataclass
class S3SourceParams(EndpointParams):
    PROVIDER = "s3"
    IS_SOURCE = True

    url: str = ""              # e.g. s3://bucket/prefix/*.parquet
    format: str = "parquet"    # parquet | jsonl | csv | line | nginx | proto
    table: str = "data"
    namespace: str = "s3"
    batch_rows: int = 65_536
    endpoint_url: str = ""     # custom S3 endpoint (minio etc.)
    anon: bool = True
    storage_options: dict = field(default_factory=dict)
    nginx_format: str = ""     # log_format template (default: combined)
    unparsed_policy: str = "route"   # route | skip | fail
    parser: Optional[dict] = None    # protobuf descriptor config (proto)

    # -- replication (reference pkg/providers/s3/source/) -------------------
    event_source: str = ""     # "" (snapshot-only) | poll | sqs
    poll_interval: float = 5.0
    sqs_queue_url: str = ""
    sqs_region: str = "us-east-1"
    sqs_access_key: str = ""
    sqs_secret_key: str = ""
    sqs_endpoint: str = ""     # custom endpoint (localstack / fakes)
    sqs_wait_seconds: int = 10
    path_pattern: str = ""     # restrict replicated keys (glob)

    def make_reader(self):
        from transferia_tpu.providers.s3readers import make_reader

        return make_reader(
            self.format, nginx_format=self.nginx_format,
            unparsed_policy=self.unparsed_policy,
            parser_config=self.parser,
        )


@register_endpoint
@dataclass
class S3TargetParams(EndpointParams):
    PROVIDER = "s3"
    IS_TARGET = True

    url: str = ""              # output directory URL
    format: str = "parquet"    # parquet | jsonl
    endpoint_url: str = ""
    anon: bool = False
    storage_options: dict = field(default_factory=dict)
    max_rows_per_file: int = 1_000_000   # file splitting (file_splitter.go)


def _fs_for(url: str, params) -> tuple[object, str]:
    """fsspec filesystem + path for a URL."""
    try:
        import fsspec
    except ImportError as e:  # pragma: no cover
        raise CategorizedError(
            CategorizedError.INTERNAL,
            "fsspec is required for the s3 provider",
        ) from e
    opts = dict(params.storage_options or {})
    if url.startswith("s3://"):
        opts.setdefault("anon", params.anon)
        if params.endpoint_url:
            opts.setdefault("client_kwargs",
                            {"endpoint_url": params.endpoint_url})
    try:
        fs, path = fsspec.core.url_to_fs(url, **opts)
    except ImportError as e:
        raise CategorizedError(
            CategorizedError.SOURCE,
            f"no fsspec backend for {url.split('://')[0]}:// "
            f"(install s3fs/gcsfs): {e}",
        ) from e
    return fs, path


class S3Storage(Storage, ShardingStorage, AsyncPartDiscovery):
    def __init__(self, params: S3SourceParams):
        self.params = params
        self.table = TableID(params.namespace, params.table)
        self._schema: Optional[TableSchema] = None
        self._fs = None
        self._files: Optional[list[str]] = None
        self._reader = None

    @property
    def fs(self):
        if self._fs is None:
            self._fs, self._root = _fs_for(self.params.url, self.params)
        return self._fs

    def files(self) -> list[str]:
        if self._files is None:
            fs = self.fs
            if "*" in self._root or "?" in self._root:
                found = sorted(fs.glob(self._root))
            elif fs.isdir(self._root):
                found = sorted(
                    p for p in fs.find(self._root) if not p.endswith("/")
                )
            else:
                found = [self._root] if fs.exists(self._root) else []
            if not found:
                raise FileNotFoundError(
                    f"s3 source: no objects match {self.params.url!r}"
                )
            self._files = found
        return self._files

    @property
    def reader(self):
        if self._reader is None:
            self._reader = self.params.make_reader()
        return self._reader

    # -- schema inference (reader/abstract.go:40-52) ------------------------
    def table_schema(self, table: TableID) -> TableSchema:
        if self._schema is None:
            self._schema = self.reader.infer_schema(
                self.fs, self.files()[0])
        return self._schema

    def table_list(self, include=None):
        if include and not any(
                self.table.include_matches(p) for p in include):
            return {}
        eta = 0
        if self.params.format == "parquet":
            for f in self.files():
                eta += self.reader.estimate_rows(self.fs, f)
        return {self.table: TableInfo(
            eta_rows=eta, schema=self.table_schema(self.table)
        )}

    def estimate_table_rows_count(self, table: TableID) -> int:
        info = self.table_list().get(self.table)
        return info.eta_rows if info else 0

    def shard_table(self, table: TableDescription) -> list[TableDescription]:
        out = []
        for f in self.files():
            eta = 0
            if self.params.format == "parquet":
                eta = self.reader.estimate_rows(self.fs, f)
            out.append(TableDescription(id=table.id, filter=f"obj:{f}",
                                        eta_rows=eta))
        return out

    def iter_table_parts(self, table: TableDescription):
        """Stream per-object parts while upload runs (huge listings must
        not serialize activation — tpp_setter_async.go parity)."""
        for f in self.files():
            eta = 0
            if self.params.format == "parquet":
                eta = self.reader.estimate_rows(self.fs, f)
            yield TableDescription(id=table.id, filter=f"obj:{f}",
                                   eta_rows=eta)

    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        files = [table.filter[4:]] if table.filter.startswith("obj:") \
            else self.files()
        schema = self.table_schema(table.id)
        for f in files:
            self.reader.read(self.fs, f, table.id, schema,
                             self.params.batch_rows, pusher)

    def ping(self) -> None:
        self.files()


class S3Sinker(Sinker):
    """Object sink with size-based file splitting (sink/file_splitter.go)."""

    def __init__(self, params: S3TargetParams):
        import uuid as _uuid

        self.params = params
        self.fs, self.root = _fs_for(params.url, params)
        self.token = _uuid.uuid4().hex[:8]
        self._counters: dict[TableID, int] = {}
        self._rows_in_file: dict[TableID, int] = {}
        self._writers: dict[TableID, object] = {}
        self._handles: dict[TableID, object] = {}

    def _next_path(self, tid: TableID, ext: str) -> str:
        n = self._counters.get(tid, 0)
        return f"{self.root.rstrip('/')}/" \
               f"{tid.namespace}.{tid.name}.{self.token}.{n:06d}.{ext}"

    def push(self, batch: Batch) -> None:
        if not is_columnar(batch):
            for it in batch:
                if it.kind in (Kind.DONE_TABLE_LOAD,
                               Kind.DONE_SHARDED_TABLE_LOAD):
                    self._finish(it.table_id)
            rows = [it for it in batch if it.is_row_event()]
            if not rows:
                return
            batch = ColumnBatch.from_rows(rows)
        tid = batch.table_id
        if self.params.format == "parquet":
            import pyarrow.parquet as pq

            rb = batch.to_arrow()
            w = self._writers.get(tid)
            if w is None:
                fh = self.fs.open(self._next_path(tid, "parquet"), "wb")
                w = pq.ParquetWriter(fh, rb.schema)
                self._writers[tid] = w
                self._handles[tid] = fh
                self._rows_in_file[tid] = 0
            if rb.schema != w.schema:
                # dict-encoded vs flat batches of one table (see the fs
                # sink): cast to the file's schema
                rb = rb.cast(w.schema)
            w.write_batch(rb)
            self._rows_in_file[tid] += batch.n_rows
            if self._rows_in_file[tid] >= self.params.max_rows_per_file:
                self._finish(tid)
        else:
            # object stores have no append: keep one open handle per table
            # and rotate whole objects at the row threshold
            fh = self._handles.get(tid)
            if fh is None:
                fh = self.fs.open(self._next_path(tid, "jsonl"), "wb")
                self._handles[tid] = fh
                self._rows_in_file[tid] = 0
            for row in batch.to_rows():
                fh.write(json.dumps(
                    row.as_dict(), default=str
                ).encode() + b"\n")
            self._rows_in_file[tid] += batch.n_rows
            if self._rows_in_file[tid] >= self.params.max_rows_per_file:
                self._finish(tid)

    def _finish(self, tid: TableID) -> None:
        w = self._writers.pop(tid, None)
        if w is not None:
            w.close()
        fh = self._handles.pop(tid, None)
        if fh is not None:
            fh.close()
        if w is not None or fh is not None:
            self._counters[tid] = self._counters.get(tid, 0) + 1

    def close(self) -> None:
        for tid in set(list(self._writers) + list(self._handles)):
            self._finish(tid)


@register_provider
class S3Provider(Provider):
    NAME = "s3"

    def storage(self):
        if isinstance(self.transfer.src, S3SourceParams):
            return S3Storage(self.transfer.src)
        return None

    def sinker(self):
        if isinstance(self.transfer.dst, S3TargetParams):
            return S3Sinker(self.transfer.dst)
        return None

    def source(self):
        if isinstance(self.transfer.src, S3SourceParams) \
                and self.transfer.src.event_source:
            from transferia_tpu.providers.s3source import (
                S3ReplicationSource,
            )

            return S3ReplicationSource(
                self.transfer.src, self.transfer.id, self.coordinator)
        return None

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        try:
            if isinstance(self.transfer.src, S3SourceParams):
                S3Storage(self.transfer.src).ping()
            result.add("list")
        except Exception as e:
            result.add("list", e)
        return result
