"""S3 / object-storage provider.

Reference parity: pkg/providers/s3/ — snapshot source with format readers
(parquet/csv/jsonl/line/nginx/proto via providers/s3readers.py, schema
inference per reader/abstract.go:40-52), the snapshot/replication sinks
with file splitting (sink/file_splitter.go), and a replication source
(providers/s3source.py): set `event_source: sqs` (bucket notifications
through an SQS queue, s3/source/ + object_fetcher_sqs.go) or
`event_source: poll` (listing watermark in the coordinator state,
object_fetcher_poller.go).  Storage access goes through fsspec, so the
same provider serves s3://, gs://, and file:// URLs depending on which
backends the environment ships.  Parquet objects stream row-group-parallel
straight into columnar batches — the ClickBench snapshot path.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.commit import StagedSinker
from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.abstract.interfaces import (
    AsyncPartDiscovery,
    Batch,
    Pusher,
    ShardingStorage,
    Sinker,
    Storage,
    TableInfo,
    is_columnar,
)
from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.abstract.schema import TableID, TableSchema
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch, arrow_to_table_schema
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)

logger = logging.getLogger(__name__)


@register_endpoint
@dataclass
class S3SourceParams(EndpointParams):
    PROVIDER = "s3"
    IS_SOURCE = True

    url: str = ""              # e.g. s3://bucket/prefix/*.parquet
    format: str = "parquet"    # parquet | jsonl | csv | line | nginx | proto
    table: str = "data"
    namespace: str = "s3"
    batch_rows: int = 65_536
    endpoint_url: str = ""     # custom S3 endpoint (minio etc.)
    anon: bool = True
    storage_options: dict = field(default_factory=dict)
    nginx_format: str = ""     # log_format template (default: combined)
    unparsed_policy: str = "route"   # route | skip | fail
    parser: Optional[dict] = None    # protobuf descriptor config (proto)

    # -- replication (reference pkg/providers/s3/source/) -------------------
    event_source: str = ""     # "" (snapshot-only) | poll | sqs
    poll_interval: float = 5.0
    sqs_queue_url: str = ""
    sqs_region: str = "us-east-1"
    sqs_access_key: str = ""
    sqs_secret_key: str = ""
    sqs_endpoint: str = ""     # custom endpoint (localstack / fakes)
    sqs_wait_seconds: int = 10
    path_pattern: str = ""     # restrict replicated keys (glob)

    def make_reader(self):
        from transferia_tpu.providers.s3readers import make_reader

        return make_reader(
            self.format, nginx_format=self.nginx_format,
            unparsed_policy=self.unparsed_policy,
            parser_config=self.parser,
        )


@register_endpoint
@dataclass
class S3TargetParams(EndpointParams):
    PROVIDER = "s3"
    IS_TARGET = True

    url: str = ""              # output directory URL
    format: str = "parquet"    # parquet | jsonl
    endpoint_url: str = ""
    anon: bool = False
    storage_options: dict = field(default_factory=dict)
    max_rows_per_file: int = 1_000_000   # file splitting (file_splitter.go)
    # -- staged-commit credentials (the exactly-once object path signs
    # its own requests through the SigV4 client; fsspec's anonymous /
    # ambient-credential modes stay on the at-least-once path)
    access_key: str = ""
    secret_key: str = ""
    region: str = "us-east-1"


def _fs_for(url: str, params) -> tuple[object, str]:
    """fsspec filesystem + path for a URL."""
    try:
        import fsspec
    except ImportError as e:  # pragma: no cover
        raise CategorizedError(
            CategorizedError.INTERNAL,
            "fsspec is required for the s3 provider",
        ) from e
    opts = dict(params.storage_options or {})
    if url.startswith("s3://"):
        opts.setdefault("anon", params.anon)
        if params.endpoint_url:
            opts.setdefault("client_kwargs",
                            {"endpoint_url": params.endpoint_url})
    try:
        fs, path = fsspec.core.url_to_fs(url, **opts)
    except ImportError as e:
        raise CategorizedError(
            CategorizedError.SOURCE,
            f"no fsspec backend for {url.split('://')[0]}:// "
            f"(install s3fs/gcsfs): {e}",
        ) from e
    return fs, path


class S3Storage(Storage, ShardingStorage, AsyncPartDiscovery):
    def __init__(self, params: S3SourceParams):
        self.params = params
        self.table = TableID(params.namespace, params.table)
        self._schema: Optional[TableSchema] = None
        self._fs = None
        self._files: Optional[list[str]] = None
        self._reader = None

    @property
    def fs(self):
        if self._fs is None:
            self._fs, self._root = _fs_for(self.params.url, self.params)
        return self._fs

    def files(self) -> list[str]:
        if self._files is None:
            fs = self.fs
            if "*" in self._root or "?" in self._root:
                found = sorted(fs.glob(self._root))
            elif fs.isdir(self._root):
                found = sorted(
                    p for p in fs.find(self._root) if not p.endswith("/")
                )
            else:
                found = [self._root] if fs.exists(self._root) else []
            # the staged-commit sink keeps in-flight parts and publish
            # markers under `.staging/` in the same prefix; readers
            # must never ingest them as table data
            found = [p for p in found if "/.staging/" not in f"/{p}"]
            if not found:
                raise FileNotFoundError(
                    f"s3 source: no objects match {self.params.url!r}"
                )
            self._files = found
        return self._files

    @property
    def reader(self):
        if self._reader is None:
            self._reader = self.params.make_reader()
        return self._reader

    # -- schema inference (reader/abstract.go:40-52) ------------------------
    def table_schema(self, table: TableID) -> TableSchema:
        if self._schema is None:
            self._schema = self.reader.infer_schema(
                self.fs, self.files()[0])
        return self._schema

    def table_list(self, include=None):
        if include and not any(
                self.table.include_matches(p) for p in include):
            return {}
        eta = 0
        if self.params.format == "parquet":
            for f in self.files():
                eta += self.reader.estimate_rows(self.fs, f)
        return {self.table: TableInfo(
            eta_rows=eta, schema=self.table_schema(self.table)
        )}

    def estimate_table_rows_count(self, table: TableID) -> int:
        info = self.table_list().get(self.table)
        return info.eta_rows if info else 0

    def shard_table(self, table: TableDescription) -> list[TableDescription]:
        out = []
        for f in self.files():
            eta = 0
            if self.params.format == "parquet":
                eta = self.reader.estimate_rows(self.fs, f)
            out.append(TableDescription(id=table.id, filter=f"obj:{f}",
                                        eta_rows=eta))
        return out

    def iter_table_parts(self, table: TableDescription):
        """Stream per-object parts while upload runs (huge listings must
        not serialize activation — tpp_setter_async.go parity)."""
        for f in self.files():
            eta = 0
            if self.params.format == "parquet":
                eta = self.reader.estimate_rows(self.fs, f)
            yield TableDescription(id=table.id, filter=f"obj:{f}",
                                   eta_rows=eta)

    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        files = [table.filter[4:]] if table.filter.startswith("obj:") \
            else self.files()
        schema = self.table_schema(table.id)
        for f in files:
            self.reader.read(self.fs, f, table.id, schema,
                             self.params.batch_rows, pusher)

    def ping(self) -> None:
        self.files()


def _s3_stage(key: str, epoch: int, prefix: str):
    """One part's staging state inside the S3 object sink: the shared
    WireStage plus the staging key prefix and an object sequence."""
    from transferia_tpu.providers.staging import WireStage

    stage = WireStage(key, epoch)
    # slug is a path COMPONENT ("/" cannot appear in a slug), so one
    # part's staging prefix can never prefix-match another's even for
    # dotted slugs where "a.t" prefixes "a.t.z"
    stage.dir = f"{prefix}.staging/{stage.slug}/e{epoch}/"
    stage.seq = 0
    return stage


class S3Sinker(Sinker, StagedSinker):
    """Object sink with size-based file splitting (sink/file_splitter.go).

    Staged-commit capable on s3:// targets with explicit credentials
    (abstract/commit.py): with an open part stage each pushed batch
    lands as an object under `.staging/<part slug>.e<epoch>/` —
    invisible to readers, which skip the `.staging/` prefix — and
    publish FIRST advances the persisted
    `.staging/.published.<slug>.json` marker with a CONDITIONAL PUT
    (If-Match on the observed marker ETag / If-None-Match on first
    publish), THEN does the batched copy-to-final (delete the part's
    previous objects under `<prefix><slug>/`, copy the staged keys
    in).  Racing publishers serialize at the store on the marker CAS:
    a zombie raises StaleEpochPublishError before touching any final
    object, and a crash between the marker and the copy is repaired by
    the retried part republishing idempotently under the same epoch."""

    def __init__(self, params: S3TargetParams):
        import uuid as _uuid

        self.params = params
        self._fs = None
        self._root: Optional[str] = None
        self.token = _uuid.uuid4().hex[:8]
        self._counters: dict[TableID, int] = {}
        self._rows_in_file: dict[TableID, int] = {}
        self._writers: dict[TableID, object] = {}
        self._handles: dict[TableID, object] = {}
        self._stage = None  # staging.WireStage (+ dir/seq) when open
        self._client = None

    @property
    def fs(self):
        if self._fs is None:
            self._fs, self._root = _fs_for(self.params.url, self.params)
        return self._fs

    @property
    def root(self) -> str:
        if self._root is None:
            self.fs  # resolves both
        return self._root

    def _next_path(self, tid: TableID, ext: str) -> str:
        n = self._counters.get(tid, 0)
        return f"{self.root.rstrip('/')}/" \
               f"{tid.namespace}.{tid.name}.{self.token}.{n:06d}.{ext}"

    def push(self, batch: Batch) -> None:
        if not is_columnar(batch):
            if self._stage is None:
                for it in batch:
                    if it.kind in (Kind.DONE_TABLE_LOAD,
                                   Kind.DONE_SHARDED_TABLE_LOAD):
                        self._finish(it.table_id)
            rows = [it for it in batch if it.is_row_event()]
            if not rows:
                return
            batch = ColumnBatch.from_rows(rows)
        if self._stage is not None:
            self._stage_push(batch)
            return
        tid = batch.table_id
        if self.params.format == "parquet":
            import pyarrow.parquet as pq

            rb = batch.to_arrow()
            w = self._writers.get(tid)
            if w is None:
                fh = self.fs.open(self._next_path(tid, "parquet"), "wb")
                w = pq.ParquetWriter(fh, rb.schema)
                self._writers[tid] = w
                self._handles[tid] = fh
                self._rows_in_file[tid] = 0
            if rb.schema != w.schema:
                # dict-encoded vs flat batches of one table (see the fs
                # sink): cast to the file's schema
                rb = rb.cast(w.schema)
            w.write_batch(rb)
            self._rows_in_file[tid] += batch.n_rows
            if self._rows_in_file[tid] >= self.params.max_rows_per_file:
                self._finish(tid)
        else:
            # object stores have no append: keep one open handle per table
            # and rotate whole objects at the row threshold
            fh = self._handles.get(tid)
            if fh is None:
                fh = self.fs.open(self._next_path(tid, "jsonl"), "wb")
                self._handles[tid] = fh
                self._rows_in_file[tid] = 0
            for row in batch.to_rows():
                fh.write(json.dumps(
                    row.as_dict(), default=str
                ).encode() + b"\n")
            self._rows_in_file[tid] += batch.n_rows
            if self._rows_in_file[tid] >= self.params.max_rows_per_file:
                self._finish(tid)

    def _finish(self, tid: TableID) -> None:
        w = self._writers.pop(tid, None)
        if w is not None:
            w.close()
        fh = self._handles.pop(tid, None)
        if fh is not None:
            fh.close()
        if w is not None or fh is not None:
            self._counters[tid] = self._counters.get(tid, 0) + 1

    def close(self) -> None:
        for tid in set(list(self._writers) + list(self._handles)):
            self._finish(tid)

    # -- StagedSinker (publish = batched copy behind a marker fence) --------
    def _bucket_prefix(self) -> tuple[str, str]:
        rest = self.params.url[len("s3://"):]
        bucket, _, prefix = rest.partition("/")
        prefix = prefix.strip("/")
        return bucket, (prefix + "/") if prefix else ""

    def staged_commit_available(self) -> bool:
        if not self.params.url.startswith("s3://"):
            return False
        opts = self.params.storage_options or {}
        if not ((self.params.access_key or opts.get("key"))
                and (self.params.secret_key or opts.get("secret"))):
            return False
        if self.params.format == "parquet":
            try:
                import pyarrow  # noqa: F401
            except ImportError:
                return False
        return self.params.format in ("parquet", "jsonl")

    def _staged_client(self):
        if self._client is None:
            from transferia_tpu.coordinator.s3client import S3Client

            opts = self.params.storage_options or {}
            bucket, _ = self._bucket_prefix()
            self._client = S3Client(
                bucket=bucket,
                endpoint=self.params.endpoint_url,
                region=self.params.region,
                access_key=self.params.access_key or opts.get("key", ""),
                secret_key=self.params.secret_key
                or opts.get("secret", ""),
            )
        return self._client

    def _serialize_batch(self, batch: ColumnBatch) -> tuple[str, bytes]:
        if self.params.format == "parquet":
            import io

            import pyarrow as pa
            import pyarrow.parquet as pq

            buf = io.BytesIO()
            rb = batch.to_arrow()
            pq.write_table(pa.Table.from_batches([rb]), buf)
            return "parquet", buf.getvalue()
        lines = [
            json.dumps(row.as_dict(), default=str).encode() + b"\n"
            for row in batch.to_rows()
        ]
        return "jsonl", b"".join(lines)

    def begin_part(self, key: str, epoch: int) -> None:
        _, prefix = self._bucket_prefix()
        stage = _s3_stage(key, epoch, prefix)
        client = self._staged_client()
        # begin replaces — for EVERY epoch of this key: sweep crashed
        # earlier attempts' staged objects too (all epochs live under
        # the part's own `.staging/<slug>/`), or a steal's epoch bump
        # would leak them forever
        for obj in client.list(f"{prefix}.staging/{stage.slug}/"):
            client.delete(obj.key)
        self._stage = stage

    def _stage_push(self, batch: ColumnBatch) -> None:
        stage = self._stage
        staged = stage.state.stage(batch)
        if staged.n_rows == 0:
            return
        ext, body = self._serialize_batch(staged)
        tid = staged.table_id
        stage_key = (f"{stage.dir}{stage.seq:06d}."
                     f"{tid.namespace}.{tid.name}.{ext}")
        stage.seq += 1
        try:
            self._staged_client().put(stage_key, body)
        except BaseException:
            # the staging write died after the dedup window recorded
            # this batch: only a full part restage is safe
            stage.state.mark_failed()
            raise

    def _marker_key(self, slug: str) -> str:
        _, prefix = self._bucket_prefix()
        return f"{prefix}.staging/.published.{slug}.json"

    def _advance_marker(self, key: str, epoch: int, slug: str) -> None:
        """Persist the publish epoch with a conditional write; racing
        publishers serialize at the store, the loser re-checks."""
        from transferia_tpu.abstract.errors import StaleEpochPublishError
        from transferia_tpu.coordinator.s3client import (
            ConditionalUnsupported,
            PreconditionFailed,
        )

        client = self._staged_client()
        body = json.dumps({"epoch": epoch, "key": key}).encode()
        for _ in range(8):
            cur = client.get(self._marker_key(slug))
            if cur is not None:
                prev = int(json.loads(cur[0]).get("epoch", -1))
                if epoch < prev:
                    raise StaleEpochPublishError(key, epoch, prev)
            try:
                if cur is None:
                    client.put(self._marker_key(slug), body,
                               if_none_match=True)
                else:
                    client.put(self._marker_key(slug), body,
                               if_match=cur[1])
                return
            except PreconditionFailed:
                continue  # lost the race: re-read and re-fence
            except ConditionalUnsupported:
                # endpoint without conditional writes: last-writer-wins
                # degrade, same contract as the s3 coordinator backend
                logger.warning(
                    "s3 target lacks conditional writes; publish "
                    "marker for %s written last-writer-wins", key)
                client.put(self._marker_key(slug), body)
                return
        raise CategorizedError(
            CategorizedError.TARGET,
            f"publish marker CAS for {key!r} did not converge")

    def publish_part(self, key: str, epoch: int) -> int:
        from transferia_tpu.chaos.failpoints import failpoint
        from transferia_tpu.providers.staging import publish_guard
        from transferia_tpu.stats import trace

        stage = self._stage
        if stage is None or stage.key != key:
            raise RuntimeError(f"s3 sink: no open stage for {key!r}")
        client = self._staged_client()
        _, prefix = self._bucket_prefix()
        with publish_guard(key, epoch):
            trace.instant("s3_publish_copy", part=key, epoch=epoch,
                          rows=stage.state.rows)
            failpoint("sink.s3.publish")
            # fence FIRST: the conditional marker write must win before
            # any final object is touched, so a zombie raises here with
            # the survivor's objects intact.  A crash after the marker
            # but before the copy is repaired by the retried part
            # republishing idempotently under the same epoch.
            self._advance_marker(key, epoch, stage.slug)
            # replace: drop what an older publish of this part landed.
            # The part's final objects live under their own slug-keyed
            # "directory", so the listing is O(this part) and cannot
            # match another part's keys by substring accident.
            part_prefix = f"{prefix}{stage.slug}/"
            for obj in client.list(part_prefix):
                client.delete(obj.key)
            # batched copy-to-final: staged keys become
            # `<prefix><slug>/<seq>.<ns>.<table>.<ext>` objects
            staged_objs = sorted(client.list(stage.dir),
                                 key=lambda o: o.key)
            for obj in staged_objs:
                got = client.get(obj.key)
                if got is None:
                    continue  # concurrent abort of a superseded stage
                name = obj.key[len(stage.dir):]
                client.put(f"{part_prefix}{name}", got[0])
            for obj in staged_objs:
                client.delete(obj.key)
            self.last_dedup_dropped = stage.state.dedup_dropped
            rows = stage.state.rows
        self._stage = None
        return rows

    def abort_part(self, key: str) -> None:
        stage = self._stage
        if stage is None or stage.key != key:
            return
        self._stage = None
        try:
            client = self._staged_client()
            for obj in client.list(stage.dir):
                client.delete(obj.key)
        except Exception as e:
            logger.warning("s3 staged abort of %s: %s", key, e)

    def note_push_retry(self) -> None:
        if self._stage is not None:
            self._stage.state.note_push_retry()


@register_provider
class S3Provider(Provider):
    NAME = "s3"

    def storage(self):
        if isinstance(self.transfer.src, S3SourceParams):
            return S3Storage(self.transfer.src)
        return None

    def sinker(self):
        if isinstance(self.transfer.dst, S3TargetParams):
            return S3Sinker(self.transfer.dst)
        return None

    def source(self):
        if isinstance(self.transfer.src, S3SourceParams) \
                and self.transfer.src.event_source:
            from transferia_tpu.providers.s3source import (
                S3ReplicationSource,
            )

            return S3ReplicationSource(
                self.transfer.src, self.transfer.id, self.coordinator)
        return None

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        try:
            if isinstance(self.transfer.src, S3SourceParams):
                S3Storage(self.transfer.src).ping()
            result.add("list")
        except Exception as e:
            result.add("list", e)
        return result
