"""S3 / object-storage provider.

Reference parity: pkg/providers/s3/ — snapshot source with format readers
(csv/json/line/parquet via reader/registry/), schema inference
(reader/abstract.go:40-52), and the snapshot/replication sinks with file
splitting (sink/file_splitter.go).  Storage access goes through fsspec, so
the same provider serves s3://, gs://, and file:// URLs depending on which
backends the environment ships (gcsfs is baked into this image; s3fs plugs
in the same way).  Parquet objects stream row-group-parallel straight into
columnar batches — the ClickBench snapshot path.

The reference's SQS-event replication source (s3/source/) needs a queue
feed; wire one by pointing an mq/kafka source at the bucket notification
stream and a `blank` parser at the object keys.
"""

from __future__ import annotations

import io
import json
import logging
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.abstract.interfaces import (
    Batch,
    Pusher,
    ShardingStorage,
    Sinker,
    Storage,
    TableInfo,
    is_columnar,
)
from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.abstract.schema import TableID, TableSchema
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch, arrow_to_table_schema
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)

logger = logging.getLogger(__name__)


@register_endpoint
@dataclass
class S3SourceParams(EndpointParams):
    PROVIDER = "s3"
    IS_SOURCE = True

    url: str = ""              # e.g. s3://bucket/prefix/*.parquet
    format: str = "parquet"    # parquet | jsonl | csv
    table: str = "data"
    namespace: str = "s3"
    batch_rows: int = 65_536
    endpoint_url: str = ""     # custom S3 endpoint (minio etc.)
    anon: bool = True
    storage_options: dict = field(default_factory=dict)


@register_endpoint
@dataclass
class S3TargetParams(EndpointParams):
    PROVIDER = "s3"
    IS_TARGET = True

    url: str = ""              # output directory URL
    format: str = "parquet"    # parquet | jsonl
    endpoint_url: str = ""
    anon: bool = False
    storage_options: dict = field(default_factory=dict)
    max_rows_per_file: int = 1_000_000   # file splitting (file_splitter.go)


def _fs_for(url: str, params) -> tuple[object, str]:
    """fsspec filesystem + path for a URL."""
    try:
        import fsspec
    except ImportError as e:  # pragma: no cover
        raise CategorizedError(
            CategorizedError.INTERNAL,
            "fsspec is required for the s3 provider",
        ) from e
    opts = dict(params.storage_options or {})
    if url.startswith("s3://"):
        opts.setdefault("anon", params.anon)
        if params.endpoint_url:
            opts.setdefault("client_kwargs",
                            {"endpoint_url": params.endpoint_url})
    try:
        fs, path = fsspec.core.url_to_fs(url, **opts)
    except ImportError as e:
        raise CategorizedError(
            CategorizedError.SOURCE,
            f"no fsspec backend for {url.split('://')[0]}:// "
            f"(install s3fs/gcsfs): {e}",
        ) from e
    return fs, path


class S3Storage(Storage, ShardingStorage):
    def __init__(self, params: S3SourceParams):
        self.params = params
        self.table = TableID(params.namespace, params.table)
        self._schema: Optional[TableSchema] = None
        self._fs = None
        self._files: Optional[list[str]] = None

    @property
    def fs(self):
        if self._fs is None:
            self._fs, self._root = _fs_for(self.params.url, self.params)
        return self._fs

    def files(self) -> list[str]:
        if self._files is None:
            fs = self.fs
            if "*" in self._root or "?" in self._root:
                found = sorted(fs.glob(self._root))
            elif fs.isdir(self._root):
                found = sorted(
                    p for p in fs.find(self._root) if not p.endswith("/")
                )
            else:
                found = [self._root] if fs.exists(self._root) else []
            if not found:
                raise FileNotFoundError(
                    f"s3 source: no objects match {self.params.url!r}"
                )
            self._files = found
        return self._files

    # -- schema inference (reader/abstract.go:40-52) ------------------------
    def table_schema(self, table: TableID) -> TableSchema:
        if self._schema is None:
            f = self.files()[0]
            if self.params.format == "parquet":
                import pyarrow.parquet as pq

                with self.fs.open(f, "rb") as fh:
                    self._schema = arrow_to_table_schema(
                        pq.read_schema(fh)
                    )
            elif self.params.format == "csv":
                import pyarrow.csv as pacsv

                with self.fs.open(f, "rb") as fh:
                    head = fh.read(1 << 20)
                with pacsv.open_csv(io.BytesIO(head)) as reader:
                    self._schema = arrow_to_table_schema(reader.schema)
            else:
                import pyarrow as pa

                rows = []
                with self.fs.open(f, "rb") as fh:
                    for i, line in enumerate(fh):
                        if i >= 100:
                            break
                        if line.strip():
                            rows.append(json.loads(line))
                self._schema = arrow_to_table_schema(
                    pa.Table.from_pylist(rows).schema
                )
        return self._schema

    def table_list(self, include=None):
        if include and not any(
                self.table.include_matches(p) for p in include):
            return {}
        eta = 0
        if self.params.format == "parquet":
            import pyarrow.parquet as pq

            for f in self.files():
                with self.fs.open(f, "rb") as fh:
                    eta += pq.ParquetFile(fh).metadata.num_rows
        return {self.table: TableInfo(
            eta_rows=eta, schema=self.table_schema(self.table)
        )}

    def estimate_table_rows_count(self, table: TableID) -> int:
        info = self.table_list().get(self.table)
        return info.eta_rows if info else 0

    def shard_table(self, table: TableDescription) -> list[TableDescription]:
        out = []
        for f in self.files():
            eta = 0
            if self.params.format == "parquet":
                import pyarrow.parquet as pq

                with self.fs.open(f, "rb") as fh:
                    eta = pq.ParquetFile(fh).metadata.num_rows
            out.append(TableDescription(id=table.id, filter=f"obj:{f}",
                                        eta_rows=eta))
        return out

    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        files = [table.filter[4:]] if table.filter.startswith("obj:") \
            else self.files()
        schema = self.table_schema(table.id)
        for f in files:
            self._load_object(f, table.id, schema, pusher)

    def _load_object(self, path: str, tid: TableID, schema: TableSchema,
                     pusher: Pusher) -> None:
        fmt = self.params.format
        if fmt == "parquet":
            import pyarrow.parquet as pq

            with self.fs.open(path, "rb") as fh:
                pf = pq.ParquetFile(fh)
                for rb in pf.iter_batches(
                        batch_size=self.params.batch_rows):
                    if rb.num_rows:
                        batch = ColumnBatch.from_arrow(rb, tid, schema)
                        batch.read_bytes = rb.nbytes
                        pusher(batch)
        elif fmt == "csv":
            import pyarrow.csv as pacsv

            with self.fs.open(path, "rb") as fh:
                data = fh.read()
            with pacsv.open_csv(io.BytesIO(data)) as reader:
                for rb in reader:
                    if rb.num_rows:
                        batch = ColumnBatch.from_arrow(rb, tid, schema)
                        batch.read_bytes = rb.nbytes
                        pusher(batch)
        else:  # jsonl
            rows = []
            nbytes = 0
            with self.fs.open(path, "rb") as fh:
                for line in fh:
                    if not line.strip():
                        continue
                    rows.append(json.loads(line))
                    nbytes += len(line)
                    if len(rows) >= self.params.batch_rows:
                        self._push_rows(rows, nbytes, tid, schema, pusher)
                        rows, nbytes = [], 0
            if rows:
                self._push_rows(rows, nbytes, tid, schema, pusher)

    @staticmethod
    def _push_rows(rows, nbytes, tid, schema, pusher):
        data = {c.name: [r.get(c.name) for r in rows] for c in schema}
        batch = ColumnBatch.from_pydict(tid, schema, data)
        batch.read_bytes = nbytes
        pusher(batch)

    def ping(self) -> None:
        self.files()


class S3Sinker(Sinker):
    """Object sink with size-based file splitting (sink/file_splitter.go)."""

    def __init__(self, params: S3TargetParams):
        import uuid as _uuid

        self.params = params
        self.fs, self.root = _fs_for(params.url, params)
        self.token = _uuid.uuid4().hex[:8]
        self._counters: dict[TableID, int] = {}
        self._rows_in_file: dict[TableID, int] = {}
        self._writers: dict[TableID, object] = {}
        self._handles: dict[TableID, object] = {}

    def _next_path(self, tid: TableID, ext: str) -> str:
        n = self._counters.get(tid, 0)
        return f"{self.root.rstrip('/')}/" \
               f"{tid.namespace}.{tid.name}.{self.token}.{n:06d}.{ext}"

    def push(self, batch: Batch) -> None:
        if not is_columnar(batch):
            for it in batch:
                if it.kind in (Kind.DONE_TABLE_LOAD,
                               Kind.DONE_SHARDED_TABLE_LOAD):
                    self._finish(it.table_id)
            rows = [it for it in batch if it.is_row_event()]
            if not rows:
                return
            batch = ColumnBatch.from_rows(rows)
        tid = batch.table_id
        if self.params.format == "parquet":
            import pyarrow.parquet as pq

            rb = batch.to_arrow()
            w = self._writers.get(tid)
            if w is None:
                fh = self.fs.open(self._next_path(tid, "parquet"), "wb")
                w = pq.ParquetWriter(fh, rb.schema)
                self._writers[tid] = w
                self._handles[tid] = fh
                self._rows_in_file[tid] = 0
            w.write_batch(rb)
            self._rows_in_file[tid] += batch.n_rows
            if self._rows_in_file[tid] >= self.params.max_rows_per_file:
                self._finish(tid)
        else:
            # object stores have no append: keep one open handle per table
            # and rotate whole objects at the row threshold
            fh = self._handles.get(tid)
            if fh is None:
                fh = self.fs.open(self._next_path(tid, "jsonl"), "wb")
                self._handles[tid] = fh
                self._rows_in_file[tid] = 0
            for row in batch.to_rows():
                fh.write(json.dumps(
                    row.as_dict(), default=str
                ).encode() + b"\n")
            self._rows_in_file[tid] += batch.n_rows
            if self._rows_in_file[tid] >= self.params.max_rows_per_file:
                self._finish(tid)

    def _finish(self, tid: TableID) -> None:
        w = self._writers.pop(tid, None)
        if w is not None:
            w.close()
        fh = self._handles.pop(tid, None)
        if fh is not None:
            fh.close()
        if w is not None or fh is not None:
            self._counters[tid] = self._counters.get(tid, 0) + 1

    def close(self) -> None:
        for tid in set(list(self._writers) + list(self._handles)):
            self._finish(tid)


@register_provider
class S3Provider(Provider):
    NAME = "s3"

    def storage(self):
        if isinstance(self.transfer.src, S3SourceParams):
            return S3Storage(self.transfer.src)
        return None

    def sinker(self):
        if isinstance(self.transfer.dst, S3TargetParams):
            return S3Sinker(self.transfer.dst)
        return None

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        try:
            if isinstance(self.transfer.src, S3SourceParams):
                S3Storage(self.transfer.src).ping()
            result.add("list")
        except Exception as e:
            result.add("list", e)
        return result
