"""Arrow IPC stream provider: the interchange plane as a first-class
endpoint (`arrow_ipc`).

Source and sink speak the Arrow IPC *stream* format through
`interchange/ipc.py`: file-backed (a path, directory, or glob; one
stream per table, `<namespace>.<table>.arrows` in directory mode) or
fd-backed (`fd://N`, an inherited pipe — the shard-handoff shape where
a parent process feeds a worker directly).  Batches cross without a row
pivot in either direction: the sink wraps ColumnBatch buffers into IPC
messages and the source hands out ColumnBatches viewing the messages in
place (convert.py), so `arrow_ipc → device` is memcpy-free for
fixed-width columns.

pyarrow is optional: the provider registers unconditionally and raises
an actionable install hint only when a transfer actually exercises it
(interchange/_pyarrow.py).
"""

from __future__ import annotations

import glob as globmod
import os
from dataclasses import dataclass
from typing import IO, Optional

from transferia_tpu.abstract.commit import StagedSinker
from transferia_tpu.abstract.interfaces import (
    Batch,
    Pusher,
    ShardingStorage,
    Sinker,
    Storage,
    TableInfo,
    is_columnar,
)
from transferia_tpu.abstract.schema import TableID, TableSchema
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.chaos.failpoints import failpoint
from transferia_tpu.columnar.batch import ColumnBatch
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.registry import Provider, register_provider

STREAM_SUFFIX = ".arrows"


@register_endpoint
@dataclass
class ArrowIpcSourceParams(EndpointParams):
    PROVIDER = "arrow_ipc"
    IS_SOURCE = True

    path: str = ""          # file, dir, glob, or fd://N
    # identity fallbacks for streams without trtpu metadata and
    # filenames without a `<namespace>.<table>` stem
    table: str = ""
    namespace: str = "arrow"


@register_endpoint
@dataclass
class ArrowIpcTargetParams(EndpointParams):
    PROVIDER = "arrow_ipc"
    IS_TARGET = True

    path: str = ""          # file or fd://N (single table) or directory


def _stem_table(path: str, params: ArrowIpcSourceParams) -> TableID:
    stem = os.path.basename(path)
    if stem.endswith(STREAM_SUFFIX):
        stem = stem[:-len(STREAM_SUFFIX)]
    if "." in stem:
        ns, _, name = stem.rpartition(".")
        return TableID(ns, name)
    return TableID(params.namespace, params.table or stem)


class ArrowIpcStorage(Storage, ShardingStorage):
    """Snapshot storage over IPC streams; each FILE is a shardable part
    (the format streams, so a file part re-read restarts cleanly).
    `fd://N` streams are single-shot: a part retry cannot rewind a pipe,
    so a second read attempt fails loudly instead of silently resuming
    mid-stream with the already-consumed batches missing."""

    def __init__(self, params: ArrowIpcSourceParams):
        from transferia_tpu.interchange import ipc

        self.params = params
        self._ipc = ipc
        self._fd_reader = None  # fd streams are single-shot: open once
        self._fd_consumed = False
        self._headers_cache = None  # immutable input: scan once

    # -- layout -------------------------------------------------------------
    def _files(self) -> list[str]:
        p = self.params.path
        if self._ipc.is_fd_location(p):
            return [p]
        if os.path.isdir(p):
            return sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if f.endswith(STREAM_SUFFIX))
        if any(ch in p for ch in "*?["):
            return sorted(globmod.glob(p))
        return [p] if os.path.exists(p) else []

    def _open_fd(self):
        if self._fd_reader is None:
            from transferia_tpu.interchange._pyarrow import pyarrow

            pa = pyarrow("the arrow_ipc source")
            fobj = self._ipc.open_location(self.params.path, "rb")
            self._fd_reader = pa.ipc.open_stream(fobj)
        return self._fd_reader

    def _identity(self, path: str, pa_schema) -> tuple[TableID, TableSchema]:
        from transferia_tpu.interchange import convert

        md = pa_schema.metadata or {}
        import json

        if convert.TABLE_KEY in md:
            t = json.loads(md[convert.TABLE_KEY])
            tid = TableID(t["namespace"], t["name"])
        else:
            tid = _stem_table(path, self.params)
        if convert.SCHEMA_KEY in md:
            schema = TableSchema.from_json(json.loads(md[convert.SCHEMA_KEY]))
        else:
            from transferia_tpu.columnar.batch import arrow_to_table_schema

            schema = arrow_to_table_schema(pa_schema)
        return tid, schema

    def _headers(self) -> dict[TableID, tuple[TableSchema, list[str]]]:
        if self._headers_cache is not None:
            return self._headers_cache
        out: dict[TableID, tuple[TableSchema, list[str]]] = {}
        for path in self._files():
            if self._ipc.is_fd_location(path):
                pa_schema = self._open_fd().schema
            else:
                with open(path, "rb") as fh:
                    pa_schema = self._ipc.read_schema(fh)
            tid, schema = self._identity(path, pa_schema)
            if tid in out:
                out[tid][1].append(path)
            else:
                out[tid] = (schema, [path])
        self._headers_cache = out
        return out

    # -- Storage ------------------------------------------------------------
    def table_list(self, include=None):
        out = {}
        for tid, (schema, _paths) in self._headers().items():
            if include and not any(tid.include_matches(p) for p in include):
                continue
            out[tid] = TableInfo(eta_rows=0, schema=schema)
        return out

    def table_schema(self, table: TableID) -> TableSchema:
        return self._headers()[table][0]

    def shard_table(self, table: TableDescription) -> list[TableDescription]:
        headers = self._headers()
        if table.id not in headers:
            return [table]
        paths = headers[table.id][1]
        if len(paths) <= 1:
            return [table]
        return [TableDescription(id=table.id, filter=f"file:{p}")
                for p in paths]

    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        headers = self._headers()
        if table.id not in headers:
            raise KeyError(f"arrow_ipc: no stream for table {table.id}")
        schema, paths = headers[table.id]
        if table.filter.startswith("file:"):
            paths = [table.filter[len("file:"):]]
        for path in paths:
            if self._ipc.is_fd_location(path):
                if self._fd_consumed:
                    raise RuntimeError(
                        f"arrow_ipc: {path} is a single-shot pipe "
                        f"already consumed by an earlier read — a part "
                        f"retry cannot rewind it; use a file path for "
                        f"retryable sources")
                self._fd_consumed = True
                self._push_reader(self._open_fd(), table.id, schema, pusher)
                continue
            with open(path, "rb") as fh:
                from transferia_tpu.interchange._pyarrow import pyarrow

                pa = pyarrow("the arrow_ipc source")
                self._push_reader(pa.ipc.open_stream(fh), table.id,
                                  schema, pusher)

    def _push_reader(self, reader, tid: TableID, schema: TableSchema,
                     pusher: Pusher) -> None:
        from transferia_tpu.interchange.convert import arrow_to_batch
        from transferia_tpu.stats import trace

        for rb in reader:
            failpoint("interchange.ipc.read")
            sp = trace.span("source_decode")
            if sp:
                sp.add(rows=rb.num_rows, direction="arrow_ipc")
            with sp:
                batch = arrow_to_batch(rb, table_id=tid, schema=schema)
            pusher(batch)

    def close(self) -> None:
        self._fd_reader = None


class ArrowIpcSinker(Sinker, StagedSinker):
    """IPC stream sink: one writer per table (directory mode) or a
    single-table stream (file / fd mode).  Columnar batches cross with
    wrapped buffers; row batches pivot once here (the row-oriented edge,
    same contract as the parquet sink).

    Staged-commit capable in DIRECTORY mode (abstract/commit.py): a
    part stages its stream files under `<path>/.staging/<part>/` and an
    epoch-fenced `publish_part` renames them into the directory,
    replacing an earlier publish of the same part.  File and fd targets
    cannot stage (a pipe has no invisible staging area) and keep the
    at-least-once path."""

    def __init__(self, params: ArrowIpcTargetParams):
        import uuid

        from transferia_tpu.interchange import ipc

        self.params = params
        self._ipc = ipc
        self._writers: dict[TableID, ipc.StreamWriter] = {}
        self._single: Optional[TableID] = None
        # the snapshot loader builds one sink pipeline per part in
        # parallel: directory-mode file names embed an instance token so
        # concurrent part sinks never clobber one table stream (same
        # contract as the fs sink); stream metadata carries the real
        # table identity, so readers ignore the token
        self._token = uuid.uuid4().hex[:8]
        p = params.path
        self._dir_mode = bool(p) and not ipc.is_fd_location(p) \
            and (os.path.isdir(p) or p.endswith(os.sep))
        self._stage = None  # staging.DirectoryPartStage when open

    # -- StagedSinker -------------------------------------------------------
    def staged_commit_available(self) -> bool:
        return self._dir_mode

    def begin_part(self, key: str, epoch: int) -> None:
        from transferia_tpu.providers.staging import DirectoryPartStage

        if not self._dir_mode:
            raise RuntimeError(
                "arrow_ipc sink: staged commit needs directory mode")
        os.makedirs(self.params.path, exist_ok=True)
        self._stage = DirectoryPartStage(
            self.params.path, key, epoch,
            lambda d: ArrowIpcSinker(ArrowIpcTargetParams(
                path=d + os.sep)))

    def publish_part(self, key: str, epoch: int) -> int:
        if self._stage is None:
            raise RuntimeError(
                f"arrow_ipc sink: no open stage for {key!r}")
        rows = self._stage.publish()
        self.last_dedup_dropped = self._stage.state.dedup_dropped
        self._stage = None
        return rows

    def abort_part(self, key: str) -> None:
        if self._stage is not None:
            self._stage.abort()
            self._stage = None

    def note_push_retry(self) -> None:
        if self._stage is not None:
            self._stage.note_push_retry()

    def _writer(self, tid: TableID):
        w = self._writers.get(tid)
        if w is not None:
            return w
        if self._dir_mode:
            os.makedirs(self.params.path, exist_ok=True)
            loc = os.path.join(
                self.params.path,
                f"{tid.namespace}.{tid.name}.{self._token}"
                f"{STREAM_SUFFIX}")
        else:
            if self._single is not None and self._single != tid:
                raise ValueError(
                    f"arrow_ipc sink {self.params.path!r} is a single "
                    f"stream but got tables {self._single} and {tid}; "
                    f"point `path` at a directory for multi-table "
                    f"transfers")
            self._single = tid
            loc = self.params.path
        w = self._ipc.StreamWriter(self._ipc.open_location(loc, "wb"))
        self._writers[tid] = w
        return w

    def push(self, batch: Batch) -> None:
        from transferia_tpu.stats import trace

        if self._stage is not None:
            self._stage.push(batch)
            return
        if is_columnar(batch):
            blocks = [batch]
        else:
            rows = [it for it in batch if it.is_row_event()]
            if not rows:
                return  # control events don't land in the stream
            by_table: dict[TableID, list] = {}
            for it in rows:
                by_table.setdefault(it.table_id, []).append(it)
            blocks = [ColumnBatch.from_rows(its) for its in
                      by_table.values()]
        for b in blocks:
            sp = trace.span("sink_push")
            if sp:
                sp.add(rows=b.n_rows, direction="arrow_ipc")
            with sp:
                self._writer(b.table_id).write(b)

    def close(self) -> None:
        if self._stage is not None:
            # unpublished stage at close = abandoned attempt: discard
            self._stage.abort()
            self._stage = None
        errs = []
        for w in self._writers.values():
            try:
                w.close()
            except Exception as e:  # close every stream before raising
                errs.append(e)
        self._writers.clear()
        if errs:
            raise errs[0]


@register_provider
class ArrowIpcProvider(Provider):
    NAME = "arrow_ipc"

    def storage(self):
        if isinstance(self.transfer.src, ArrowIpcSourceParams):
            return ArrowIpcStorage(self.transfer.src)
        return None

    def destination_storage(self):
        if isinstance(self.transfer.dst, ArrowIpcTargetParams):
            return ArrowIpcStorage(ArrowIpcSourceParams(
                path=self.transfer.dst.path))
        return None

    def sinker(self):
        if isinstance(self.transfer.dst, ArrowIpcTargetParams):
            return ArrowIpcSinker(self.transfer.dst)
        return None
