"""stdout debug sink (reference: pkg/providers/stdout/)."""

from __future__ import annotations

import sys
from dataclasses import dataclass

from transferia_tpu.abstract.interfaces import Batch, Sinker, is_columnar
from transferia_tpu.middlewares.helpers import batch_len
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.registry import Provider, register_provider


@register_endpoint
@dataclass
class StdoutTargetParams(EndpointParams):
    PROVIDER = "stdout"
    IS_TARGET = True

    verbose: bool = False      # print full rows, not just summaries
    max_rows_printed: int = 20


class StdoutSinker(Sinker):
    def __init__(self, params: StdoutTargetParams):
        self.params = params
        self.total_rows = 0

    def push(self, batch: Batch) -> None:
        n = batch_len(batch)
        self.total_rows += n
        if is_columnar(batch):
            print(f"[stdout sink] {batch.table_id}: columnar batch "
                  f"{n} rows x {len(batch.columns)} cols "
                  f"({batch.nbytes()} bytes)")
            if self.params.verbose:
                for row in batch.slice(0, self.params.max_rows_printed).to_rows():
                    print(f"  {row.kind.value} {row.as_dict()}")
        else:
            for it in batch[:self.params.max_rows_printed]:
                if it.is_row_event() and not self.params.verbose:
                    continue
                print(f"[stdout sink] {it.kind.value} {it.table_id} "
                      f"{it.as_dict() if it.is_row_event() else ''}")
        sys.stdout.flush()


@register_provider
class StdoutProvider(Provider):
    NAME = "stdout"

    def sinker(self):
        return StdoutSinker(self.transfer.dst)


@register_endpoint
@dataclass
class NullTargetParams(EndpointParams):
    """Counting /dev/null sink (benchmarks; reference ErrorsOutput devnull)."""

    PROVIDER = "devnull"
    IS_TARGET = True


class NullSinker(Sinker):
    def __init__(self):
        self.total_rows = 0
        self.total_bytes = 0

    def push(self, batch: Batch) -> None:
        self.total_rows += batch_len(batch)
        if is_columnar(batch):
            self.total_bytes += batch.nbytes()


@register_provider
class NullProvider(Provider):
    NAME = "devnull"

    def sinker(self):
        return NullSinker()
