"""Kafka provider.

Reference parity: pkg/providers/kafka/ — source.go (fetch loop + inflight
throttling + sequencer dedup), sink.go + writer/ (serializer-driven
producer), partition_source.go (queue->S3 per-partition pipelines), mirror
mode.  The client is a dependency-free implementation of the Kafka wire
protocol (this image ships no Kafka client library): ApiVersions, Metadata,
Produce/Fetch with record-batch v2 framing (zigzag varints, CRC32C), and
ListOffsets.  Group membership is intentionally NOT used — offsets commit
through the transfer coordinator like every other source checkpoint
(transfer_state KV), which is exactly how the reference treats queue
positions (at-least-once, commit after confirmed push).
"""

from transferia_tpu.providers.kafka.provider import (
    KafkaProvider,
    KafkaSourceParams,
    KafkaTargetParams,
)

__all__ = ["KafkaProvider", "KafkaSourceParams", "KafkaTargetParams"]
