"""Kafka source/sink over the wire client.

The source composes the shared QueueSource machinery (sequencer +
parsequeue + post-push commits); offsets checkpoint through the transfer
coordinator (kafka/source.go commits after push :251 — at-least-once).
The sink serializes batches and produces per partition, reusing the
column-hash partitioner when configured.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.interfaces import Batch, Sinker, is_columnar
from transferia_tpu.coordinator.interface import Coordinator
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.parsers import Message
from transferia_tpu.providers.kafka.client import KafkaClient, KafkaError
from transferia_tpu.providers.kafka.protocol import Record
from transferia_tpu.providers.queue_common import FetchedBatch, QueueSource
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)
from transferia_tpu.serializers import make_queue_serializer
from transferia_tpu.transform.plugins.sharder import hash_column_to_shards

logger = logging.getLogger(__name__)


@register_endpoint
@dataclass
class KafkaSourceParams(EndpointParams):
    PROVIDER = "kafka"
    IS_SOURCE = True
    # queue sources cannot be re-read from scratch: reupload
    # is forbidden (model/endpoint.go AppendOnlySource)
    is_append_only = True

    brokers: list[str] = field(default_factory=lambda: ["localhost:9092"])
    topic: str = ""
    parser: Optional[dict] = None
    parallelism: int = 4
    max_bytes_per_fetch: int = 8 << 20
    start_from: str = "earliest"   # earliest | latest
    # -- security (reference: franz-go auth in pkg/providers/kafka/writer/)
    tls: bool = False
    tls_ca: str = ""              # CA bundle path (custom/self-signed)
    tls_verify: bool = True
    sasl_mechanism: str = ""      # PLAIN | SCRAM-SHA-256 | SCRAM-SHA-512
    sasl_username: str = ""
    sasl_password: str = ""

    def __post_init__(self):
        if self.start_from not in ("earliest", "latest"):
            # a typo silently meaning "latest" would skip all existing data
            raise ValueError(
                f"kafka start_from must be 'earliest' or 'latest', "
                f"got {self.start_from!r}"
            )

    def parser_config(self):
        return self.parser


@register_endpoint
@dataclass
class KafkaTargetParams(EndpointParams):
    PROVIDER = "kafka"
    IS_TARGET = True

    brokers: list[str] = field(default_factory=lambda: ["localhost:9092"])
    topic: str = ""               # "" -> per-table "<ns>.<name>"
    serializer: str = "json"
    serializer_config: dict = field(default_factory=dict)
    partition_by: str = ""
    compression: str = ""         # "" | gzip
    # -- security (reference: franz-go auth in pkg/providers/kafka/writer/)
    tls: bool = False
    tls_ca: str = ""              # CA bundle path (custom/self-signed)
    tls_verify: bool = True
    sasl_mechanism: str = ""      # PLAIN | SCRAM-SHA-256 | SCRAM-SHA-512
    sasl_username: str = ""
    sasl_password: str = ""


def _make_client(params) -> KafkaClient:
    return KafkaClient(
        params.brokers,
        tls=getattr(params, "tls", False),
        tls_ca=getattr(params, "tls_ca", ""),
        tls_verify=getattr(params, "tls_verify", True),
        sasl_mechanism=getattr(params, "sasl_mechanism", ""),
        sasl_username=getattr(params, "sasl_username", ""),
        sasl_password=getattr(params, "sasl_password", ""),
    )


class _KafkaQueueClient:
    """QueueSource client contract over KafkaClient with coordinator-backed
    offset checkpoints (state key kafka_offsets)."""

    STATE_KEY = "kafka_offsets"

    # one lock for ALL clients of a process: the partitioned strategy runs
    # one client per partition against the same transfer-state blob, and a
    # per-instance lock would let concurrent read-modify-writes lose
    # another partition's committed offset
    _commit_lock = threading.Lock()

    def __init__(self, params: KafkaSourceParams, transfer_id: str,
                 coordinator: Optional[Coordinator],
                 partitions: Optional[list[int]] = None):
        """partitions: restrict to a subset (the partitioned replication
        strategy runs one client per partition)."""
        self.params = params
        self.transfer_id = transfer_id
        self.cp = coordinator
        self.client = _make_client(params)
        meta = self.client.metadata([params.topic])
        all_partitions = meta.get(params.topic)
        if not all_partitions:
            raise KafkaError(f"topic {params.topic!r} not found")
        if partitions is not None:
            all_partitions = [p for p in all_partitions
                              if p in set(partitions)]
        partitions = all_partitions
        saved = {}
        if self.cp is not None:
            saved = self.cp.get_transfer_state(transfer_id).get(
                self.STATE_KEY, {}
            )
        self.positions: dict[int, int] = {}
        for p in partitions:
            key = f"{params.topic}:{p}"
            if key in saved:
                self.positions[p] = int(saved[key]) + 1
            else:
                ts = -2 if params.start_from == "earliest" else -1
                self.positions[p] = self.client.list_offsets(
                    params.topic, p, ts
                )

    def fetch(self, max_messages: int = 1024) -> list[FetchedBatch]:
        # one multi-partition Fetch per leader (not one round-trip per
        # partition: a 64-partition fan-in would pay 64 RTTs per cycle)
        fetched = self.client.fetch_multi(
            self.params.topic, dict(self.positions),
            max_bytes=self.params.max_bytes_per_fetch,
        )
        out = []
        for p in sorted(fetched):
            records, high = fetched[p]
            if not records:
                continue
            records = records[:max_messages]
            self.positions[p] = records[-1].offset + 1
            out.append(FetchedBatch(
                self.params.topic, p,
                [
                    Message(
                        value=r.value or b"", key=r.key or b"",
                        topic=self.params.topic, partition=p,
                        offset=r.offset,
                        write_time_ns=r.timestamp_ms * 1_000_000,
                        headers=tuple(r.headers),
                    )
                    for r in records
                ],
            ))
        return out

    def commit(self, topic: str, partition: int, offset: int) -> None:
        if self.cp is None:
            return
        with _KafkaQueueClient._commit_lock:
            state = self.cp.get_transfer_state(self.transfer_id).get(
                self.STATE_KEY, {}
            )
            state[f"{topic}:{partition}"] = offset
            self.cp.set_transfer_state(
                self.transfer_id, {self.STATE_KEY: state}
            )

    def close(self) -> None:
        self.client.close()


def topic_partitions(params: KafkaSourceParams) -> list[int]:
    """Partition ids of the source topic (partitioned strategy fan-out)."""
    client = _make_client(params)
    try:
        meta = client.metadata([params.topic])
        return sorted(meta.get(params.topic) or [])
    finally:
        client.close()


class KafkaSinker(Sinker):
    def __init__(self, params: KafkaTargetParams):
        self.params = params
        self.client = _make_client(params)
        cfg = dict(params.serializer_config or {})
        if params.serializer == "debezium" and params.topic:
            # single-topic sinks: SR subjects must derive from the real
            # topic (TopicNameStrategy)
            cfg.setdefault("topic", params.topic)
        self.serializer = make_queue_serializer(params.serializer, **cfg)
        self._partitions: dict[str, list[int]] = {}

    def _topic_partitions(self, topic: str) -> list[int]:
        if topic not in self._partitions:
            meta = self.client.metadata([topic])
            self._partitions[topic] = meta.get(topic) or [0]
        return self._partitions[topic]

    @staticmethod
    def _key_partitions(pairs, n_parts: int):
        """crc32c(key) % n_parts per pair, batched through the native lib
        when present."""
        import numpy as np

        from transferia_tpu.native import lib as native_lib

        cdll = native_lib()
        keys = [bytes(k or b"") for k, _ in pairs]
        if cdll is not None and hasattr(cdll, "crc32c_batch"):
            data = np.frombuffer(b"".join(keys), dtype=np.uint8)
            offs = np.zeros(len(keys) + 1, dtype=np.int64)
            np.cumsum([len(k) for k in keys], out=offs[1:])
            out = np.empty(len(keys), dtype=np.uint32)
            cdll.crc32c_batch(
                data if data.size else np.zeros(1, dtype=np.uint8),
                offs, len(keys), out)
            return out % n_parts
        from transferia_tpu.providers.kafka.protocol import crc32c

        return [crc32c(k) % n_parts for k in keys]

    def push(self, batch: Batch) -> None:
        pairs = self.serializer.serialize_messages(batch)
        if not pairs:
            return
        if is_columnar(batch):
            topic = self.params.topic or str(batch.table_id)
        else:
            rows = [it for it in batch if it.is_row_event()]
            topic = self.params.topic or (
                str(rows[0].table_id) if rows else "controls"
            )
        partitions = self._topic_partitions(topic)
        n_parts = len(partitions)
        per_partition: dict[int, list[Record]] = {}
        col_parts = None
        if is_columnar(batch) and self.params.partition_by and \
                self.params.partition_by in batch.columns and \
                len(pairs) == batch.n_rows:
            col_parts = hash_column_to_shards(
                batch.column(self.params.partition_by), n_parts
            )
        if col_parts is not None:
            part_idx = col_parts
        else:
            # deterministic key hash (crc32c): built-in hash() is
            # randomized per process and would break per-key partition
            # affinity across restarts.  One batched native call when
            # available; the per-key fallback is the same function.
            part_idx = self._key_partitions(pairs, n_parts)
        for i, (key, value) in enumerate(pairs):
            p = partitions[int(part_idx[i])]
            per_partition.setdefault(p, []).append(
                Record(key=key, value=value)
            )
        for p, records in per_partition.items():
            self.client.produce(
                topic, p, records,
                compression=getattr(self.params, "compression", ""))

    def close(self) -> None:
        self.client.close()


@register_provider
class KafkaProvider(Provider):
    NAME = "kafka"

    def source(self):
        if isinstance(self.transfer.src, KafkaSourceParams):
            p = self.transfer.src
            client = _KafkaQueueClient(p, self.transfer.id,
                                       self.coordinator)
            return QueueSource(client, p.parser,
                               parallelism=p.parallelism,
                               metrics=self.metrics)
        return None

    def sinker(self):
        if isinstance(self.transfer.dst, KafkaTargetParams):
            return KafkaSinker(self.transfer.dst)
        return None

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        params = self.transfer.src if isinstance(
            self.transfer.src, KafkaSourceParams) else self.transfer.dst
        try:
            client = _make_client(params)
            client.metadata()
            client.close()
            result.add("metadata")
        except Exception as e:
            result.add("metadata", e)
        return result
