"""Kafka source/sink over the wire client.

The source composes the shared QueueSource machinery (sequencer +
parsequeue + post-push commits); offsets checkpoint through the transfer
coordinator (kafka/source.go commits after push :251 — at-least-once).
The sink serializes batches and produces per partition, reusing the
column-hash partitioner when configured.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.commit import StagedSinker
from transferia_tpu.abstract.interfaces import Batch, Sinker, is_columnar
from transferia_tpu.coordinator.interface import Coordinator
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.parsers import Message
from transferia_tpu.providers.kafka.client import KafkaClient, KafkaError
from transferia_tpu.providers.kafka.protocol import Record
from transferia_tpu.providers.queue_common import FetchedBatch, QueueSource
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)
from transferia_tpu.serializers import make_queue_serializer
from transferia_tpu.transform.plugins.sharder import hash_column_to_shards

logger = logging.getLogger(__name__)


@register_endpoint
@dataclass
class KafkaSourceParams(EndpointParams):
    PROVIDER = "kafka"
    IS_SOURCE = True
    # queue sources cannot be re-read from scratch: reupload
    # is forbidden (model/endpoint.go AppendOnlySource)
    is_append_only = True

    brokers: list[str] = field(default_factory=lambda: ["localhost:9092"])
    topic: str = ""
    parser: Optional[dict] = None
    parallelism: int = 4
    max_bytes_per_fetch: int = 8 << 20
    start_from: str = "earliest"   # earliest | latest
    # -- security (reference: franz-go auth in pkg/providers/kafka/writer/)
    tls: bool = False
    tls_ca: str = ""              # CA bundle path (custom/self-signed)
    tls_verify: bool = True
    sasl_mechanism: str = ""      # PLAIN | SCRAM-SHA-256 | SCRAM-SHA-512
    sasl_username: str = ""
    sasl_password: str = ""

    def __post_init__(self):
        if self.start_from not in ("earliest", "latest"):
            # a typo silently meaning "latest" would skip all existing data
            raise ValueError(
                f"kafka start_from must be 'earliest' or 'latest', "
                f"got {self.start_from!r}"
            )

    def parser_config(self):
        return self.parser


@register_endpoint
@dataclass
class KafkaTargetParams(EndpointParams):
    PROVIDER = "kafka"
    IS_TARGET = True

    brokers: list[str] = field(default_factory=lambda: ["localhost:9092"])
    topic: str = ""               # "" -> per-table "<ns>.<name>"
    serializer: str = "json"
    serializer_config: dict = field(default_factory=dict)
    partition_by: str = ""
    compression: str = ""         # "" | gzip
    # -- security (reference: franz-go auth in pkg/providers/kafka/writer/)
    tls: bool = False
    tls_ca: str = ""              # CA bundle path (custom/self-signed)
    tls_verify: bool = True
    sasl_mechanism: str = ""      # PLAIN | SCRAM-SHA-256 | SCRAM-SHA-512
    sasl_username: str = ""
    sasl_password: str = ""


def _make_client(params) -> KafkaClient:
    return KafkaClient(
        params.brokers,
        tls=getattr(params, "tls", False),
        tls_ca=getattr(params, "tls_ca", ""),
        tls_verify=getattr(params, "tls_verify", True),
        sasl_mechanism=getattr(params, "sasl_mechanism", ""),
        sasl_username=getattr(params, "sasl_username", ""),
        sasl_password=getattr(params, "sasl_password", ""),
    )


class _KafkaQueueClient:
    """QueueSource client contract over KafkaClient with coordinator-backed
    offset checkpoints (state key kafka_offsets)."""

    STATE_KEY = "kafka_offsets"

    # one lock for ALL clients of a process: the partitioned strategy runs
    # one client per partition against the same transfer-state blob, and a
    # per-instance lock would let concurrent read-modify-writes lose
    # another partition's committed offset
    _commit_lock = threading.Lock()

    def __init__(self, params: KafkaSourceParams, transfer_id: str,
                 coordinator: Optional[Coordinator],
                 partitions: Optional[list[int]] = None):
        """partitions: restrict to a subset (the partitioned replication
        strategy runs one client per partition)."""
        self.params = params
        self.transfer_id = transfer_id
        self.cp = coordinator
        self.client = _make_client(params)
        meta = self.client.metadata([params.topic])
        all_partitions = meta.get(params.topic)
        if not all_partitions:
            raise KafkaError(f"topic {params.topic!r} not found")
        if partitions is not None:
            all_partitions = [p for p in all_partitions
                              if p in set(partitions)]
        partitions = all_partitions
        saved = {}
        if self.cp is not None:
            saved = self.cp.get_transfer_state(transfer_id).get(
                self.STATE_KEY, {}
            )
        self.positions: dict[int, int] = {}
        for p in partitions:
            key = f"{params.topic}:{p}"
            if key in saved:
                self.positions[p] = int(saved[key]) + 1
            else:
                ts = -2 if params.start_from == "earliest" else -1
                self.positions[p] = self.client.list_offsets(
                    params.topic, p, ts
                )

    def fetch(self, max_messages: int = 1024) -> list[FetchedBatch]:
        # one multi-partition Fetch per leader (not one round-trip per
        # partition: a 64-partition fan-in would pay 64 RTTs per cycle)
        fetched = self.client.fetch_multi(
            self.params.topic, dict(self.positions),
            max_bytes=self.params.max_bytes_per_fetch,
        )
        out = []
        for p in sorted(fetched):
            records, high = fetched[p]
            if not records:
                continue
            records = records[:max_messages]
            self.positions[p] = records[-1].offset + 1
            out.append(FetchedBatch(
                self.params.topic, p,
                [
                    Message(
                        value=r.value or b"", key=r.key or b"",
                        topic=self.params.topic, partition=p,
                        offset=r.offset,
                        write_time_ns=r.timestamp_ms * 1_000_000,
                        headers=tuple(r.headers),
                    )
                    for r in records
                ],
            ))
        return out

    def commit(self, topic: str, partition: int, offset: int) -> None:
        if self.cp is None:
            return
        with _KafkaQueueClient._commit_lock:
            state = self.cp.get_transfer_state(self.transfer_id).get(
                self.STATE_KEY, {}
            )
            state[f"{topic}:{partition}"] = offset
            self.cp.set_transfer_state(
                self.transfer_id, {self.STATE_KEY: state}
            )

    def close(self) -> None:
        self.client.close()


def topic_partitions(params: KafkaSourceParams) -> list[int]:
    """Partition ids of the source topic (partitioned strategy fan-out)."""
    client = _make_client(params)
    try:
        meta = client.metadata([params.topic])
        return sorted(meta.get(params.topic) or [])
    finally:
        client.close()


class KafkaSinker(Sinker, StagedSinker):
    """Produce sink; staged-commit capable (abstract/commit.py): with an
    open part stage the serialized messages buffer sink-side and land in
    the broker through ONE transactional produce tied to the part's
    epoch-keyed transactional id (`trtpu.<part slug>`) — kafka's own
    KIP-98 producer fencing rejects a zombie (its InitProducerId /
    produce with the stale epoch fails PRODUCER_FENCED, surfaced as
    StaleEpochPublishError), and a republish under the same
    transactional id SUPERSEDES the previous publish instead of
    appending duplicates.

    Protocol bound: this speaks the KIP-98 SUBSET the in-repo fake
    broker implements — one transactional Produce request = one
    committed transaction, with broker-side supersede-in-place of the
    id's previous publish.  A full Apache Kafka deployment additionally
    needs AddPartitionsToTxn/EndTxn + commit markers and read_committed
    consumers (its log is append-only: the republish-supersede there
    would ride transaction aborts, not segment rewrite); until then
    the exactly-once claim holds for the fake-backed wire, and real
    brokers should keep the at-least-once path."""

    def __init__(self, params: KafkaTargetParams):
        self.params = params
        self.client = _make_client(params)
        cfg = dict(params.serializer_config or {})
        if params.serializer == "debezium" and params.topic:
            # single-topic sinks: SR subjects must derive from the real
            # topic (TopicNameStrategy)
            cfg.setdefault("topic", params.topic)
        self.serializer = make_queue_serializer(params.serializer, **cfg)
        self._partitions: dict[str, list[int]] = {}
        self._stage = None  # staging.PartStage when open
        self._stage_key = ""
        self._staged: dict[tuple[str, int], list[Record]] = {}

    def _topic_partitions(self, topic: str) -> list[int]:
        if topic not in self._partitions:
            meta = self.client.metadata([topic])
            self._partitions[topic] = meta.get(topic) or [0]
        return self._partitions[topic]

    @staticmethod
    def _key_partitions(pairs, n_parts: int):
        """crc32c(key) % n_parts per pair, batched through the native lib
        when present."""
        import numpy as np

        from transferia_tpu.native import lib as native_lib

        cdll = native_lib()
        keys = [bytes(k or b"") for k, _ in pairs]
        if cdll is not None and hasattr(cdll, "crc32c_batch"):
            data = np.frombuffer(b"".join(keys), dtype=np.uint8)
            offs = np.zeros(len(keys) + 1, dtype=np.int64)
            np.cumsum([len(k) for k in keys], out=offs[1:])
            out = np.empty(len(keys), dtype=np.uint32)
            cdll.crc32c_batch(
                data if data.size else np.zeros(1, dtype=np.uint8),
                offs, len(keys), out)
            return out % n_parts
        from transferia_tpu.providers.kafka.protocol import crc32c

        return [crc32c(k) % n_parts for k in keys]

    def _partitioned_records(self, batch: Batch
                             ) -> dict[tuple[str, int], list[Record]]:
        """Serialize one batch into per-(topic, partition) records."""
        pairs = self.serializer.serialize_messages(batch)
        if not pairs:
            return {}
        if is_columnar(batch):
            topic = self.params.topic or str(batch.table_id)
        else:
            rows = [it for it in batch if it.is_row_event()]
            topic = self.params.topic or (
                str(rows[0].table_id) if rows else "controls"
            )
        partitions = self._topic_partitions(topic)
        n_parts = len(partitions)
        col_parts = None
        if is_columnar(batch) and self.params.partition_by and \
                self.params.partition_by in batch.columns and \
                len(pairs) == batch.n_rows:
            col_parts = hash_column_to_shards(
                batch.column(self.params.partition_by), n_parts
            )
        if col_parts is not None:
            part_idx = col_parts
        else:
            # deterministic key hash (crc32c): built-in hash() is
            # randomized per process and would break per-key partition
            # affinity across restarts.  One batched native call when
            # available; the per-key fallback is the same function.
            part_idx = self._key_partitions(pairs, n_parts)
        out: dict[tuple[str, int], list[Record]] = {}
        for i, (key, value) in enumerate(pairs):
            p = partitions[int(part_idx[i])]
            out.setdefault((topic, p), []).append(
                Record(key=key, value=value)
            )
        return out

    def push(self, batch: Batch) -> None:
        if self._stage is not None:
            batch = self._stage.stage(batch)
            try:
                for tp, records in self._partitioned_records(
                        batch).items():
                    self._staged.setdefault(tp, []).extend(records)
            except BaseException:
                # serialization died after the dedup window recorded
                # the batch: only a full part restage is safe
                self._stage.mark_failed()
                raise
            return
        for (topic, p), records in self._partitioned_records(
                batch).items():
            self.client.produce(
                topic, p, records,
                compression=getattr(self.params, "compression", ""))

    # -- StagedSinker (publish = one kafka transaction) ---------------------
    def begin_part(self, key: str, epoch: int) -> None:
        from transferia_tpu.providers.staging import PartStage

        # hold=False: the serialized record buffer is the stage; the
        # PartStage only runs the dedup window over the pushed batches
        self._stage = PartStage(key, epoch, hold=False)
        self._stage_key = key
        self._staged = {}

    def publish_part(self, key: str, epoch: int) -> int:
        from transferia_tpu.abstract.errors import StaleEpochPublishError
        from transferia_tpu.chaos.failpoints import failpoint
        from transferia_tpu.providers.kafka.client import (
            is_producer_fenced,
        )
        from transferia_tpu.providers.staging import part_slug, \
            publish_guard
        from transferia_tpu.stats import trace

        stage = self._stage
        if stage is None or self._stage_key != key:
            raise RuntimeError(f"kafka sink: no open stage for {key!r}")
        with publish_guard(key, epoch):
            txn_id = f"trtpu.{part_slug(key)}"
            trace.instant("kafka_publish_txn", part=key, epoch=epoch,
                          rows=stage.rows)
            failpoint("sink.kafka.publish")
            try:
                pid, accepted = self.client.init_producer(txn_id, epoch)
                n = self.client.txn_produce(txn_id, pid, accepted,
                                            self._staged)
            except KafkaError as e:
                if is_producer_fenced(e):
                    # KIP-98 zombie fencing IS the sink-side epoch
                    # fence: a newer owner holds the transactional id.
                    # Brokers that don't disclose the winning epoch
                    # (real ones return -1) get the epoch+1 lower bound
                    won = getattr(e, "fence_epoch", None)
                    raise StaleEpochPublishError(
                        key, epoch,
                        won if won is not None else epoch + 1) from e
                raise
            self.last_dedup_dropped = stage.dedup_dropped
            rows = stage.rows
        self._stage = None
        self._stage_key = ""
        self._staged = {}
        return rows

    def abort_part(self, key: str) -> None:
        self._stage = None
        self._stage_key = ""
        self._staged = {}

    def note_push_retry(self) -> None:
        if self._stage is not None:
            self._stage.note_push_retry()

    def close(self) -> None:
        self.client.close()


@register_provider
class KafkaProvider(Provider):
    NAME = "kafka"

    def source(self):
        if isinstance(self.transfer.src, KafkaSourceParams):
            p = self.transfer.src
            client = _KafkaQueueClient(p, self.transfer.id,
                                       self.coordinator)
            return QueueSource(client, p.parser,
                               parallelism=p.parallelism,
                               metrics=self.metrics,
                               transfer_id=self.transfer.id)
        return None

    def sinker(self):
        if isinstance(self.transfer.dst, KafkaTargetParams):
            return KafkaSinker(self.transfer.dst)
        return None

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        params = self.transfer.src if isinstance(
            self.transfer.src, KafkaSourceParams) else self.transfer.dst
        try:
            client = _make_client(params)
            client.metadata()
            client.close()
            result.add("metadata")
        except Exception as e:
            result.add("metadata", e)
        return result
