"""Minimal Kafka broker client: Metadata, Produce, Fetch, ListOffsets.

Request framing: int32 size + apiKey(2) apiVersion(2) correlationId(4)
clientId(STRING) + body.  API versions used are old-but-universally-
supported non-flexible ones (Metadata v1, Produce v3, Fetch v4,
ListOffsets v1) so the codec stays simple and works against any broker
>= 0.11 as well as compatibility layers (Redpanda, the test fake).
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from typing import Optional

from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.providers.kafka.protocol import (
    Reader,
    Record,
    decode_record_batches,
    enc_bytes,
    enc_str,
    encode_record_batch,
)

logger = logging.getLogger(__name__)

API_METADATA = 3
API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2

# Kafka error codes we interpret
ERR_NONE = 0
ERR_UNKNOWN_TOPIC = 3
ERR_OFFSET_OUT_OF_RANGE = 1


class KafkaError(CategorizedError):
    def __init__(self, message: str, code: int = -1):
        super().__init__(CategorizedError.SOURCE, message)
        self.code = code


class KafkaClient:
    def __init__(self, brokers: list[str], client_id: str = "transferia-tpu",
                 timeout: float = 30.0):
        self.brokers = brokers
        self.client_id = client_id
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._corr = 0
        self._lock = threading.Lock()

    # -- connection ---------------------------------------------------------
    def _connect(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        last: Optional[Exception] = None
        for b in self.brokers:
            host, _, port = b.partition(":")
            try:
                s = socket.create_connection(
                    (host, int(port or 9092)), timeout=self.timeout
                )
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._sock = s
                return s
            except OSError as e:
                last = e
        raise KafkaError(f"no kafka broker reachable: {last}")

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def _roundtrip(self, api_key: int, api_version: int,
                   body: bytes) -> Reader:
        with self._lock:
            sock = self._connect()
            self._corr += 1
            corr = self._corr
            header = struct.pack("!hhi", api_key, api_version, corr) \
                + enc_str(self.client_id)
            msg = header + body
            try:
                sock.sendall(struct.pack("!i", len(msg)) + msg)
                size = struct.unpack("!i", self._recv_exact(sock, 4))[0]
                payload = self._recv_exact(sock, size)
            except OSError as e:
                self.close()
                raise KafkaError(f"kafka io error: {e}") from e
        r = Reader(payload)
        got_corr = r.i32()
        if got_corr != corr:
            self.close()
            raise KafkaError(
                f"correlation mismatch: {got_corr} != {corr}"
            )
        return r

    @staticmethod
    def _recv_exact(sock: socket.socket, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = sock.recv(n - len(out))
            if not chunk:
                raise OSError("kafka connection closed")
            out += chunk
        return out

    # -- metadata -----------------------------------------------------------
    def metadata(self, topics: Optional[list[str]] = None) -> dict:
        """topic -> [partition ids] (Metadata v1)."""
        if topics is None:
            body = struct.pack("!i", -1)
        else:
            body = struct.pack("!i", len(topics))
            for t in topics:
                body += enc_str(t)
        r = self._roundtrip(API_METADATA, 1, body)
        n_brokers = r.i32()
        for _ in range(n_brokers):
            r.i32()          # node id
            r.string()       # host
            r.i32()          # port
            r.string()       # rack
        r.i32()              # controller id
        n_topics = r.i32()
        out: dict[str, list[int]] = {}
        for _ in range(n_topics):
            err = r.i16()
            name = r.string()
            r.i8()           # is_internal
            n_parts = r.i32()
            parts = []
            for _ in range(n_parts):
                r.i16()      # partition error
                pid = r.i32()
                r.i32()      # leader
                for _ in range(r.i32()):
                    r.i32()  # replicas
                for _ in range(r.i32()):
                    r.i32()  # isr
                parts.append(pid)
            if err == ERR_NONE and name is not None:
                out[name] = sorted(parts)
        return out

    # -- produce ------------------------------------------------------------
    def produce(self, topic: str, partition: int,
                records: list[Record], acks: int = -1,
                timeout_ms: int = 30_000) -> int:
        """Append records; returns the base offset assigned (Produce v3)."""
        batch = encode_record_batch(records)
        body = enc_str(None)                      # transactional id
        body += struct.pack("!hi", acks, timeout_ms)
        body += struct.pack("!i", 1) + enc_str(topic)
        body += struct.pack("!i", 1) + struct.pack("!i", partition)
        body += enc_bytes(batch)
        r = self._roundtrip(API_PRODUCE, 3, body)
        n_topics = r.i32()
        base_offset = -1
        for _ in range(n_topics):
            r.string()
            for _ in range(r.i32()):
                r.i32()              # partition
                err = r.i16()
                base_offset = r.i64()
                r.i64()              # log append time
                if err != ERR_NONE:
                    raise KafkaError(f"produce failed: error {err}",
                                     code=err)
        r.i32()  # throttle
        return base_offset

    # -- offsets ------------------------------------------------------------
    def list_offsets(self, topic: str, partition: int,
                     timestamp: int = -2) -> int:
        """-2 = earliest, -1 = latest (ListOffsets v1)."""
        body = struct.pack("!i", -1)              # replica id
        body += struct.pack("!i", 1) + enc_str(topic)
        body += struct.pack("!i", 1)
        body += struct.pack("!iq", partition, timestamp)
        r = self._roundtrip(API_LIST_OFFSETS, 1, body)
        offset = 0
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                err = r.i16()
                r.i64()              # timestamp
                offset = r.i64()
                if err != ERR_NONE:
                    raise KafkaError(f"list_offsets failed: {err}",
                                     code=err)
        return offset

    # -- fetch --------------------------------------------------------------
    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 8 << 20,
              max_wait_ms: int = 250) -> tuple[list[Record], int]:
        """Returns (records, high_watermark) from the given offset
        (Fetch v4)."""
        body = struct.pack("!iiii", -1, max_wait_ms, 1, max_bytes)
        body += b"\x00"                           # isolation level
        body += struct.pack("!i", 1) + enc_str(topic)
        body += struct.pack("!i", 1)
        body += struct.pack("!iqi", partition, offset, max_bytes)
        r = self._roundtrip(API_FETCH, 4, body)
        r.i32()  # throttle
        records: list[Record] = []
        high = 0
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()              # partition
                err = r.i16()
                high = r.i64()
                r.i64()              # last stable offset
                for _ in range(r.i32()):
                    r.i64()          # aborted txn producer id
                    r.i64()          # first offset
                blob = r.bytes_() or b""
                if err == ERR_OFFSET_OUT_OF_RANGE:
                    raise KafkaError("offset out of range", code=err)
                if err != ERR_NONE:
                    raise KafkaError(f"fetch failed: error {err}",
                                     code=err)
                records.extend(decode_record_batches(blob))
        # the broker may return records below the requested offset (batch
        # alignment); trim client-side
        return [rec for rec in records if rec.offset >= offset], high
