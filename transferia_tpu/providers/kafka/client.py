"""Minimal Kafka broker client: Metadata, Produce, Fetch, ListOffsets.

Request framing: int32 size + apiKey(2) apiVersion(2) correlationId(4)
clientId(STRING) + body.  API versions used are old-but-universally-
supported non-flexible ones (Metadata v1, Produce v3, Fetch v4,
ListOffsets v1) so the codec stays simple and works against any broker
>= 0.11 as well as compatibility layers (Redpanda, the test fake).

Partition leadership: Metadata responses populate a node table and a
(topic, partition) -> leader map; produce/fetch/list_offsets route to the
partition leader and refresh metadata + retry once on NOT_LEADER or
connection failures, so multi-broker clusters work, not just the
single-broker case.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from typing import Optional

from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.providers.kafka.protocol import (
    Reader,
    Record,
    decode_record_batches,
    enc_bytes,
    enc_str,
    encode_record_batch,
)
from transferia_tpu.utils.net import recv_exact

logger = logging.getLogger(__name__)

API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_INIT_PRODUCER_ID = 22

ERR_NONE = 0
ERR_OFFSET_OUT_OF_RANGE = 1
ERR_UNKNOWN_TOPIC = 3
ERR_LEADER_NOT_AVAILABLE = 5
ERR_NOT_LEADER = 6
ERR_INVALID_PRODUCER_EPOCH = 47
ERR_PRODUCER_FENCED = 90

_RETRIABLE = {ERR_LEADER_NOT_AVAILABLE, ERR_NOT_LEADER}
_FENCED = {ERR_INVALID_PRODUCER_EPOCH, ERR_PRODUCER_FENCED}


def is_producer_fenced(err: "KafkaError") -> bool:
    """True when the broker rejected a transactional operation because
    a NEWER producer epoch owns the transactional id (KIP-98 zombie
    fencing) — the staged-commit publish maps this onto
    StaleEpochPublishError."""
    return err.code in _FENCED


class KafkaError(CategorizedError):
    def __init__(self, message: str, code: int = -1):
        super().__init__(CategorizedError.SOURCE, message)
        self.code = code


class KafkaClient:
    def __init__(self, brokers: list[str], client_id: str = "transferia-tpu",
                 timeout: float = 30.0, tls: bool = False,
                 tls_ca: str = "", tls_verify: bool = True,
                 sasl_mechanism: str = "", sasl_username: str = "",
                 sasl_password: str = ""):
        self.bootstrap = brokers
        self.client_id = client_id
        self.timeout = timeout
        self.tls = tls
        self.tls_ca = tls_ca
        self.tls_verify = tls_verify
        self.sasl_mechanism = sasl_mechanism.upper()
        self.sasl_username = sasl_username
        self.sasl_password = sasl_password
        if self.sasl_mechanism not in (
                "", "PLAIN", "SCRAM-SHA-256", "SCRAM-SHA-512"):
            raise KafkaError(
                f"unsupported sasl mechanism {sasl_mechanism!r}")
        self._conns: dict[object, socket.socket] = {}  # node_id | "boot"
        self._nodes: dict[int, tuple[str, int]] = {}
        self._leaders: dict[tuple[str, int], int] = {}
        self._corr = 0
        self._lock = threading.Lock()

    # -- connections --------------------------------------------------------
    def _dial(self, host: str, port: int) -> socket.socket:
        s = socket.create_connection((host, port), timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self.tls:
            import ssl

            ctx = ssl.create_default_context()
            if self.tls_ca:
                ctx.load_verify_locations(self.tls_ca)
            if not self.tls_verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            s = ctx.wrap_socket(s, server_hostname=host)
        if self.sasl_mechanism:
            self._sasl_authenticate(s)
        return s

    # -- SASL (SaslHandshake v1 + SaslAuthenticate v1 frames) ---------------
    def _raw_roundtrip(self, sock: socket.socket, api_key: int,
                       api_version: int, body: bytes) -> Reader:
        # only reached from _conn_for(), i.e. under self._lock
        self._corr += 1  # trtpu: ignore[LCK001]
        corr = self._corr
        header = struct.pack("!hhi", api_key, api_version, corr) \
            + enc_str(self.client_id)
        msg = header + body
        sock.sendall(struct.pack("!i", len(msg)) + msg)
        size = struct.unpack("!i", recv_exact(sock, 4))[0]
        r = Reader(recv_exact(sock, size))
        if r.i32() != corr:
            raise KafkaError("sasl correlation mismatch")
        return r

    def _sasl_round(self, sock: socket.socket, data: bytes) -> bytes:
        r = self._raw_roundtrip(sock, 36, 1, enc_bytes(data))
        err = r.i16()
        err_msg = r.string()
        auth = r.bytes_()
        if err:
            raise KafkaError(
                f"sasl authentication failed: {err_msg or err}", err)
        return auth or b""

    def _sasl_authenticate(self, sock: socket.socket) -> None:
        r = self._raw_roundtrip(
            sock, 17, 1, enc_str(self.sasl_mechanism))
        err = r.i16()
        if err:
            n = r.i32()
            offered = [r.string() for _ in range(max(0, n))]
            raise KafkaError(
                f"broker rejected mechanism {self.sasl_mechanism} "
                f"(offers {offered})", err)
        if self.sasl_mechanism == "PLAIN":
            token = (b"\x00" + self.sasl_username.encode()
                     + b"\x00" + self.sasl_password.encode())
            self._sasl_round(sock, token)
            return
        from transferia_tpu.utils.scram import ScramError, client_exchange

        try:
            client_exchange(
                self.sasl_mechanism, self.sasl_username,
                self.sasl_password,
                lambda msg: self._sasl_round(sock, msg),
            )
        except ScramError as e:
            raise KafkaError(f"sasl scram failed: {e}") from e

    def _conn_for(self, node) -> socket.socket:
        sock = self._conns.get(node)
        if sock is not None:
            return sock
        if node == "boot":
            last: Optional[Exception] = None
            for b in self.bootstrap:
                host, _, port = b.partition(":")
                try:
                    sock = self._dial(host, int(port or 9092))
                    break
                except OSError as e:
                    last = e
                    sock = None
            if sock is None:
                raise KafkaError(f"no kafka broker reachable: {last}")
        else:
            addr = self._nodes.get(node)
            if addr is None:
                raise KafkaError(f"unknown broker node {node}")
            try:
                sock = self._dial(*addr)
            except OSError as e:
                raise KafkaError(
                    f"broker node {node} {addr} unreachable: {e}"
                ) from e
        self._conns[node] = sock
        return sock

    def _drop_conn(self, node) -> None:
        sock = self._conns.pop(node, None)
        if sock is not None:
            sock.close()

    def close(self) -> None:
        with self._lock:
            for node in list(self._conns):
                self._drop_conn(node)

    def _roundtrip(self, api_key: int, api_version: int, body: bytes,
                   node="boot") -> Reader:
        from transferia_tpu.chaos.failpoints import failpoint
        from transferia_tpu.stats import trace

        failpoint("client.kafka.roundtrip")  # before the lock: may sleep
        with trace.span("kafka_roundtrip", api=api_key), self._lock:
            sock = self._conn_for(node)
            self._corr += 1
            corr = self._corr
            header = struct.pack("!hhi", api_key, api_version, corr) \
                + enc_str(self.client_id)
            msg = header + body
            # I/O under self._lock is the design: the lock serializes
            # request/response framing on the single broker socket
            try:
                sock.sendall(  # trtpu: ignore[LCK001]
                    struct.pack("!i", len(msg)) + msg)
                size = struct.unpack(
                    "!i", recv_exact(sock, 4))[0]  # trtpu: ignore[LCK001]
                payload = recv_exact(sock, size)  # trtpu: ignore[LCK001]
            except (OSError, ConnectionError) as e:
                self._drop_conn(node)
                raise KafkaError(f"kafka io error (node {node}): {e}") from e
        r = Reader(payload)
        got_corr = r.i32()
        if got_corr != corr:
            with self._lock:
                self._drop_conn(node)
            raise KafkaError(
                f"correlation mismatch: {got_corr} != {corr}"
            )
        return r

    # -- metadata -----------------------------------------------------------
    def metadata(self, topics: Optional[list[str]] = None) -> dict:
        """topic -> [partition ids]; refreshes node + leader maps."""
        if topics is None:
            body = struct.pack("!i", -1)
        else:
            body = struct.pack("!i", len(topics))
            for t in topics:
                body += enc_str(t)
        r = self._roundtrip(API_METADATA, 1, body)
        with self._lock:
            for _ in range(r.i32()):
                node_id = r.i32()
                host = r.string()
                port = r.i32()
                r.string()       # rack
                self._nodes[node_id] = (host or "", port)
            r.i32()              # controller id
            n_topics = r.i32()
            out: dict[str, list[int]] = {}
            for _ in range(n_topics):
                err = r.i16()
                name = r.string()
                r.i8()           # is_internal
                parts = []
                for _ in range(r.i32()):
                    r.i16()      # partition error
                    pid = r.i32()
                    leader = r.i32()
                    for _ in range(r.i32()):
                        r.i32()  # replicas
                    for _ in range(r.i32()):
                        r.i32()  # isr
                    parts.append(pid)
                    if name is not None:
                        self._leaders[(name, pid)] = leader
                if err == ERR_NONE and name is not None:
                    out[name] = sorted(parts)
        return out

    def _leader_node(self, topic: str, partition: int):
        leader = self._leaders.get((topic, partition))
        if leader is None or leader not in self._nodes:
            self.metadata([topic])
            leader = self._leaders.get((topic, partition))
        # fall back to bootstrap when metadata gave nothing (test fakes
        # reporting no broker list still answer everything themselves)
        return leader if leader is not None and leader in self._nodes \
            else "boot"

    def _routed(self, topic: str, partition: int, api: int, version: int,
                body: bytes) -> Reader:
        """Round-trip to the partition leader; one metadata-refresh retry
        on routing errors."""
        node = self._leader_node(topic, partition)
        try:
            return self._roundtrip(api, version, body, node)
        except KafkaError:
            self.metadata([topic])
            node = self._leader_node(topic, partition)
            return self._roundtrip(api, version, body, node)

    # -- produce ------------------------------------------------------------
    def produce(self, topic: str, partition: int,
                records: list[Record], acks: int = -1,
                timeout_ms: int = 30_000, compression: str = "") -> int:
        """Append records; returns the base offset assigned (Produce v3)."""
        batch = encode_record_batch(records, compression=compression)
        body = enc_str(None)                      # transactional id
        body += struct.pack("!hi", acks, timeout_ms)
        body += struct.pack("!i", 1) + enc_str(topic)
        body += struct.pack("!i", 1) + struct.pack("!i", partition)
        body += enc_bytes(batch)

        def attempt() -> int:
            r = self._routed(topic, partition, API_PRODUCE, 3, body)
            base_offset = -1
            for _ in range(r.i32()):
                r.string()
                for _ in range(r.i32()):
                    r.i32()              # partition
                    err = r.i16()
                    base_offset = r.i64()
                    r.i64()              # log append time
                    if err != ERR_NONE:
                        raise KafkaError(f"produce failed: error {err}",
                                         code=err)
            r.i32()  # throttle
            return base_offset

        try:
            return attempt()
        except KafkaError as e:
            if e.code not in _RETRIABLE:
                raise
            self.metadata([topic])
            return attempt()

    # -- transactions (KIP-98 subset) ----------------------------------------
    def init_producer(self, transactional_id: str,
                      producer_epoch: int) -> tuple[int, int]:
        """InitProducerId for an epoch-keyed transactional id.

        KIP-360 shape: the client proposes its producer epoch (here the
        part's assignment epoch — monotone per part key) and the broker
        fences a proposal OLDER than the id's current epoch with
        PRODUCER_FENCED, which is exactly the zombie-publish fence.
        Returns (producer_id, accepted_epoch)."""
        body = enc_str(transactional_id)
        body += struct.pack("!i", 60_000)           # txn timeout
        body += struct.pack("!qh", -1, producer_epoch)
        r = self._roundtrip(API_INIT_PRODUCER_ID, 3, body)
        r.i32()  # throttle
        err = r.i16()
        pid = r.i64()
        epoch = r.i16()
        if err != ERR_NONE:
            e = KafkaError(
                f"init_producer({transactional_id!r}) failed: "
                f"error {err}", code=err)
            # a fencing response carries the id's CURRENT epoch when
            # the broker discloses it (the in-repo fake does; real
            # brokers return -1) — the staged-commit publish maps it
            # onto StaleEpochPublishError's published_epoch
            e.fence_epoch = int(epoch) if epoch >= 0 else None
            raise e
        return pid, epoch

    def txn_produce(self, transactional_id: str, producer_id: int,
                    producer_epoch: int,
                    messages: dict[tuple[str, int], list[Record]],
                    acks: int = -1, timeout_ms: int = 30_000) -> int:
        """One transactional produce: every (topic, partition) record
        list lands in a single Produce request carrying the
        transactional id and producer-epoch-stamped batches — the
        broker applies it atomically and fences a stale epoch.
        Returns records produced."""
        by_topic: dict[str, list[tuple[int, list[Record]]]] = {}
        for (topic, partition), records in sorted(messages.items()):
            by_topic.setdefault(topic, []).append((partition, records))
        body = enc_str(transactional_id)
        body += struct.pack("!hi", acks, timeout_ms)
        body += struct.pack("!i", len(by_topic))
        total = 0
        for topic, parts in sorted(by_topic.items()):
            body += enc_str(topic)
            body += struct.pack("!i", len(parts))
            for partition, records in parts:
                batch = encode_record_batch(
                    records, producer_id=producer_id,
                    producer_epoch=producer_epoch)
                body += struct.pack("!i", partition)
                body += enc_bytes(batch)
                total += len(records)
        r = self._roundtrip(API_PRODUCE, 3, body)
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()              # partition
                err = r.i16()
                r.i64()              # base offset
                r.i64()              # log append time
                if err != ERR_NONE:
                    raise KafkaError(
                        f"transactional produce failed: error {err}",
                        code=err)
        r.i32()  # throttle
        return total

    # -- offsets ------------------------------------------------------------
    def list_offsets(self, topic: str, partition: int,
                     timestamp: int = -2) -> int:
        """-2 = earliest, -1 = latest (ListOffsets v1)."""
        body = struct.pack("!i", -1)              # replica id
        body += struct.pack("!i", 1) + enc_str(topic)
        body += struct.pack("!i", 1)
        body += struct.pack("!iq", partition, timestamp)
        r = self._routed(topic, partition, API_LIST_OFFSETS, 1, body)
        offset = 0
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                err = r.i16()
                r.i64()              # timestamp
                offset = r.i64()
                if err != ERR_NONE:
                    raise KafkaError(f"list_offsets failed: {err}",
                                     code=err)
        return offset

    # -- fetch --------------------------------------------------------------
    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 8 << 20,
              max_wait_ms: int = 250) -> tuple[list[Record], int]:
        """Returns (records, high_watermark) from the given offset
        (Fetch v4)."""
        body = struct.pack("!iiii", -1, max_wait_ms, 1, max_bytes)
        body += b"\x00"                           # isolation level
        body += struct.pack("!i", 1) + enc_str(topic)
        body += struct.pack("!i", 1)
        body += struct.pack("!iqi", partition, offset, max_bytes)

        def attempt():
            r = self._routed(topic, partition, API_FETCH, 4, body)
            r.i32()  # throttle
            records: list[Record] = []
            high = 0
            for _ in range(r.i32()):
                r.string()
                for _ in range(r.i32()):
                    r.i32()              # partition
                    err = r.i16()
                    high = r.i64()
                    r.i64()              # last stable offset
                    for _ in range(r.i32()):
                        r.i64()          # aborted txn producer id
                        r.i64()          # first offset
                    blob = r.bytes_() or b""
                    if err == ERR_OFFSET_OUT_OF_RANGE:
                        raise KafkaError("offset out of range", code=err)
                    if err != ERR_NONE:
                        raise KafkaError(f"fetch failed: error {err}",
                                         code=err)
                    records.extend(decode_record_batches(blob))
            return records, high

        try:
            records, high = attempt()
        except KafkaError as e:
            if e.code not in _RETRIABLE:
                raise
            self.metadata([topic])
            records, high = attempt()
        # the broker may return records below the requested offset (batch
        # alignment); trim client-side
        return [rec for rec in records if rec.offset >= offset], high

    def fetch_multi(self, topic: str, offsets: dict[int, int],
                    max_bytes: int = 8 << 20, max_wait_ms: int = 250,
                    ) -> dict[int, tuple[list[Record], int]]:
        """Fetch many partitions in few round-trips: partitions group by
        leader and each leader gets ONE Fetch request carrying all of its
        partitions (the wire format is multi-partition; issuing one
        request per partition costs n_partitions round-trips per poll
        cycle — the 64-partition fan-in killer).  Returns
        {partition: (records, high_watermark)}; per-partition retriable
        errors retry once through the single-partition path."""
        by_node: dict[object, list[int]] = {}
        for p in offsets:
            by_node.setdefault(self._leader_node(topic, p), []).append(p)
        out: dict[int, tuple[list[Record], int]] = {}
        retry: list[int] = []
        self._fetch_rotation = getattr(self, "_fetch_rotation", 0) + 1
        for node, parts in by_node.items():
            # Rotate the partition order per request: brokers fill
            # partitions in request order until max_bytes runs out, so a
            # fixed order lets one backlogged low partition starve the
            # rest indefinitely (the KIP-74 fairness problem).
            parts = sorted(parts)
            rot = self._fetch_rotation % len(parts)
            parts = parts[rot:] + parts[:rot]
            body = struct.pack("!iiii", -1, max_wait_ms, 1, max_bytes)
            body += b"\x00"                       # isolation level
            body += struct.pack("!i", 1) + enc_str(topic)
            body += struct.pack("!i", len(parts))
            for p in parts:
                body += struct.pack("!iqi", p, offsets[p], max_bytes)
            try:
                r = self._roundtrip(API_FETCH, 4, body, node)
            except KafkaError:
                retry.extend(parts)
                continue
            r.i32()  # throttle
            for _ in range(r.i32()):
                r.string()
                for _ in range(r.i32()):
                    p = r.i32()
                    err = r.i16()
                    high = r.i64()
                    r.i64()              # last stable offset
                    for _ in range(r.i32()):
                        r.i64()          # aborted txn producer id
                        r.i64()          # first offset
                    blob = r.bytes_() or b""
                    if err == ERR_OFFSET_OUT_OF_RANGE:
                        raise KafkaError("offset out of range", code=err)
                    if err != ERR_NONE:
                        retry.append(p)
                        continue
                    off = offsets.get(p, 0)
                    recs = [rec for rec in decode_record_batches(blob)
                            if rec.offset >= off]
                    out[p] = (recs, high)
        for p in retry:
            if p in offsets:
                out[p] = self.fetch(topic, p, offsets[p],
                                    max_bytes=max_bytes,
                                    max_wait_ms=max_wait_ms)
        return out
