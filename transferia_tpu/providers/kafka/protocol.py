"""Kafka wire protocol primitives: framing, record batches v2, codecs.

Binary conventions: big-endian fixed ints; STRING = int16 len + utf8
(-1 = null); BYTES = int32 len + data (-1 = null); record-batch internals
use zigzag varints.  CRC32C (Castagnoli) covers the batch from the
attributes field onward.
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field
from typing import Optional

try:
    import google_crc32c

    def crc32c(data: bytes) -> int:
        return google_crc32c.value(data)
except ImportError:
    # native SSE4.2 path (hostops.cpp crc32c_buf) with a pure-python
    # table as the last resort; resolved lazily so importing this module
    # never triggers a native build
    def _make_table():
        poly = 0x82F63B78
        table = []
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        return table

    def _crc_py(data: bytes) -> int:
        crc = 0xFFFFFFFF
        for b in data:
            crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
        return crc ^ 0xFFFFFFFF

    _TABLE = _make_table()
    _crc_impl = None

    def crc32c(data: bytes) -> int:
        global _crc_impl
        if _crc_impl is None:
            _crc_impl = _crc_py
            try:
                from transferia_tpu.native import lib as _native_lib

                cdll = _native_lib()
                if cdll is not None and hasattr(cdll, "crc32c_buf"):
                    import numpy as _np

                    def _crc_native(data: bytes,
                                    _c=cdll.crc32c_buf, _np=_np) -> int:
                        return int(_c(_np.frombuffer(data, _np.uint8),
                                      len(data), 0))

                    _crc_impl = _crc_native
            except Exception as e:  # pragma: no cover - python fallback
                import logging

                logging.getLogger(__name__).debug(
                    "native crc32c unavailable (%s); using python "
                    "fallback", e)
        return _crc_impl(data)


# -- primitive codecs --------------------------------------------------------

def enc_str(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack("!h", -1)
    b = s.encode()
    return struct.pack("!h", len(b)) + b


def enc_bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack("!i", -1)
    return struct.pack("!i", len(b)) + b


def enc_varint(n: int) -> bytes:
    """Zigzag varint."""
    z = (n << 1) ^ (n >> 63)
    out = b""
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


class Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def i8(self) -> int:
        v = struct.unpack_from("!b", self.buf, self.pos)[0]
        self.pos += 1
        return v

    def i16(self) -> int:
        v = struct.unpack_from("!h", self.buf, self.pos)[0]
        self.pos += 2
        return v

    def i32(self) -> int:
        v = struct.unpack_from("!i", self.buf, self.pos)[0]
        self.pos += 4
        return v

    def i64(self) -> int:
        v = struct.unpack_from("!q", self.buf, self.pos)[0]
        self.pos += 8
        return v

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        s = self.buf[self.pos:self.pos + n].decode()
        self.pos += n
        return s

    def bytes_(self) -> Optional[bytes]:
        n = self.i32()
        if n < 0:
            return None
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return bytes(b)

    def varint(self) -> int:
        z = 0
        shift = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            z |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (z >> 1) ^ -(z & 1)

    def remaining(self) -> int:
        return len(self.buf) - self.pos


# -- record batches v2 -------------------------------------------------------

@dataclass
class Record:
    key: Optional[bytes]
    value: Optional[bytes]
    offset: int = 0
    timestamp_ms: int = 0
    headers: list = field(default_factory=list)


_CODEC_GZIP = 1


def _encode_records_native(records: list[Record], now: int,
                           base_ts: int) -> Optional[bytes]:
    """Record section via the C encoder (hostops.cpp); None when out of
    envelope (per-record headers) or the native lib is absent."""
    try:
        from transferia_tpu.native import lib as native_lib

        cdll = native_lib()
    except Exception:  # pragma: no cover
        return None
    if cdll is None or not hasattr(cdll, "kafka_encode_records"):
        return None
    if any(r.headers for r in records):
        return None
    import numpy as np

    n = len(records)
    key_parts = [r.key or b"" for r in records]
    val_parts = [r.value or b"" for r in records]
    key_off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(k) for k in key_parts], out=key_off[1:])
    val_off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(v) for v in val_parts], out=val_off[1:])
    key_null = np.fromiter((r.key is None for r in records),
                           dtype=np.uint8, count=n)
    val_null = np.fromiter((r.value is None for r in records),
                           dtype=np.uint8, count=n)
    ts = [(r.timestamp_ms or now) - base_ts for r in records]
    ts_arr = None
    if any(ts):
        ts_arr = np.asarray(ts, dtype=np.int64)
    key_data = np.frombuffer(b"".join(key_parts), dtype=np.uint8) \
        if key_off[-1] else np.zeros(0, dtype=np.uint8)
    val_data = np.frombuffer(b"".join(val_parts), dtype=np.uint8) \
        if val_off[-1] else np.zeros(0, dtype=np.uint8)
    cap = int(key_off[-1] + val_off[-1]) + 64 * n + 64
    out = np.empty(cap, dtype=np.uint8)
    rc = cdll.kafka_encode_records(
        key_data, key_off,
        key_null.ctypes.data, val_data, val_off,
        val_null.ctypes.data,
        ts_arr.ctypes.data if ts_arr is not None else None,
        n, out, cap)
    if rc < 0:  # pragma: no cover - cap formula guarantees fit
        return None
    return out[:rc].tobytes()


def encode_record_batch(records: list[Record],
                        base_offset: int = 0,
                        compression: str = "",
                        producer_id: int = -1,
                        producer_epoch: int = -1) -> bytes:
    """Records -> one RecordBatch v2 blob (optionally gzip-compressed).

    `producer_id`/`producer_epoch` stamp the batch header for
    transactional produce (the broker fences a batch whose producer
    epoch is older than the transactional id's current one)."""
    now = int(time.time() * 1000)
    base_ts = records[0].timestamp_ms or now if records else now
    native = _encode_records_native(records, now, base_ts) \
        if records else None
    if native is not None:
        return _finish_record_batch(records, native, base_offset,
                                    compression, now, base_ts,
                                    producer_id, producer_epoch)
    # accumulate in a list: += on bytes is O(total^2) and a 20k-record
    # batch would copy gigabytes
    parts: list[bytes] = []
    for i, r in enumerate(records):
        body = [b"\x00"]  # attributes
        body.append(enc_varint((r.timestamp_ms or now) - base_ts))
        body.append(enc_varint(i))  # offset delta
        if r.key is None:
            body.append(enc_varint(-1))
        else:
            body.append(enc_varint(len(r.key)))
            body.append(r.key)
        if r.value is None:
            body.append(enc_varint(-1))
        else:
            body.append(enc_varint(len(r.value)))
            body.append(r.value)
        body.append(enc_varint(len(r.headers)))
        for hk, hv in r.headers:
            body.append(enc_varint(len(hk)))
            body.append(hk)
            body.append(enc_varint(len(hv)))
            body.append(hv)
        blob = b"".join(body)
        parts.append(enc_varint(len(blob)))
        parts.append(blob)
    return _finish_record_batch(records, b"".join(parts), base_offset,
                                compression, now, base_ts,
                                producer_id, producer_epoch)


# attributes bit 4: this batch is part of a transaction
_ATTR_TRANSACTIONAL = 0x10


def _finish_record_batch(records: list[Record], recs: bytes,
                         base_offset: int, compression: str,
                         now: int, base_ts: int,
                         producer_id: int = -1,
                         producer_epoch: int = -1) -> bytes:
    attrs = 0
    if compression == "gzip":
        import gzip as _gzip

        recs = _gzip.compress(recs)
        attrs = _CODEC_GZIP
    elif compression:
        raise ValueError(f"unsupported compression {compression!r} "
                         f"(only gzip ships dependency-free)")
    if producer_id >= 0:
        attrs |= _ATTR_TRANSACTIONAL
    # batch body after the crc field
    after_crc = (
        struct.pack("!h", attrs)                   # attributes
        + struct.pack("!i", max(0, len(records) - 1))  # lastOffsetDelta
        + struct.pack("!q", base_ts)
        + struct.pack("!q", (records[-1].timestamp_ms or now)
                      if records else now)
        + struct.pack("!q", producer_id)           # producerId
        + struct.pack("!h", producer_epoch)        # producerEpoch
        + struct.pack("!i", -1)                    # baseSequence
        + struct.pack("!i", len(records))
        + recs
    )
    header = (
        struct.pack("!i", 0)       # partitionLeaderEpoch
        + b"\x02"                  # magic
        + struct.pack("!I", crc32c(after_crc))
    )
    batch_len = len(header) + len(after_crc)
    return struct.pack("!q", base_offset) + struct.pack("!i", batch_len) \
        + header + after_crc


def _scan_records_native(data: bytes) -> Optional[list[Record]]:
    """C fast path (hostops.cpp kafka_scan_records): zero-copy scan of
    uncompressed, header-less frames; None defers to the Python walk."""
    try:
        from transferia_tpu.native import lib as native_lib

        cdll = native_lib()
    except Exception:  # pragma: no cover
        return None
    if cdll is None or not hasattr(cdll, "kafka_scan_records"):
        return None
    import numpy as np

    # upper bound on records: sum of frame recordCount headers
    max_n = 0
    pos = 0
    n = len(data)
    while pos + 61 <= n:
        batch_len = struct.unpack_from("!i", data, pos + 8)[0]
        count = struct.unpack_from("!i", data, pos + 57)[0]
        if batch_len <= 0 or count < 0 or data[pos + 16] != 2:
            return None  # corrupt/foreign framing: python path decides
        max_n += count
        pos += 12 + batch_len
    if max_n == 0:
        return [] if pos else None
    arr = np.empty(max_n * 6, dtype=np.int64)
    blob = np.frombuffer(data, dtype=np.uint8)
    rc = cdll.kafka_scan_records(blob, len(data), arr, max_n)
    if rc < 0:
        if rc == -1:
            raise ValueError("record batch CRC mismatch or corrupt frame")
        return None  # -2: compression/headers — python path handles
    out = []
    for ks, ke, vs, ve, off, ts in arr[:rc * 6].reshape(-1, 6).tolist():
        out.append(Record(
            key=data[ks:ke] if ks >= 0 else None,
            value=data[vs:ve] if vs >= 0 else None,
            offset=off, timestamp_ms=ts))
    return out


def decode_record_batches(data: bytes) -> list[Record]:
    """RecordBatch v2 blob(s) -> Records with absolute offsets."""
    native = _scan_records_native(data)
    if native is not None:
        return native
    out: list[Record] = []
    pos = 0
    n = len(data)
    while pos + 12 <= n:
        base_offset, batch_len = struct.unpack_from("!qi", data, pos)
        end = pos + 12 + batch_len
        if end > n:
            break  # partial batch at the end of a fetch response
        r = Reader(data, pos + 12)
        r.i32()            # partitionLeaderEpoch
        magic = r.i8()
        if magic != 2:
            raise ValueError(f"unsupported record batch magic {magic}")
        expect_crc = struct.unpack_from("!I", data, r.pos)[0]
        r.pos += 4
        if crc32c(data[r.pos:end]) != expect_crc:
            raise ValueError("record batch CRC mismatch")
        attributes = r.i16()
        codec = attributes & 0x07
        if codec not in (0, _CODEC_GZIP):
            raise ValueError(
                f"compressed record batch codec {codec} not supported "
                f"(gzip=1 is; snappy/lz4/zstd need codecs this "
                f"environment does not ship) — configure the producers "
                f"accordingly"
            )
        if attributes & 0x20:
            # control batch: txn commit/abort markers are broker metadata,
            # never data — skip, but keep offset accounting moving
            pos = end
            continue
        r.i32()            # lastOffsetDelta
        base_ts = r.i64()
        r.i64()            # maxTimestamp
        r.i64()            # producerId
        r.i16()            # producerEpoch
        r.i32()            # baseSequence
        count = r.i32()
        if codec == _CODEC_GZIP:
            import gzip as _gzip

            r = Reader(_gzip.decompress(bytes(r.buf[r.pos:end])))
        for _ in range(count):
            r.varint()                 # record length
            r.i8()                     # attributes
            ts_delta = r.varint()
            off_delta = r.varint()
            klen = r.varint()
            key = None
            if klen >= 0:
                key = bytes(r.buf[r.pos:r.pos + klen])
                r.pos += klen
            vlen = r.varint()
            value = None
            if vlen >= 0:
                value = bytes(r.buf[r.pos:r.pos + vlen])
                r.pos += vlen
            hcount = r.varint()
            headers = []
            for _ in range(hcount):
                hklen = r.varint()
                hk = bytes(r.buf[r.pos:r.pos + hklen])
                r.pos += hklen
                hvlen = r.varint()
                hv = b""
                if hvlen >= 0:
                    hv = bytes(r.buf[r.pos:r.pos + hvlen])
                    r.pos += hvlen
                headers.append((hk, hv))
            out.append(Record(
                key=key, value=value,
                offset=base_offset + off_delta,
                timestamp_ms=base_ts + ts_delta,
                headers=headers,
            ))
        pos = end
    return out
