"""Shared queue-source machinery (reference: pkg/providers/kafka/source.go
fetch/parse/ack loop + sequencer dedup, pkg/parsers wiring via the
endpoint's Parseable capability).

Any broker provider (in-memory mq, kafka, kinesis, eventhub) composes:
  reader (broker client) -> Sequencer -> ParseQueue(parser) -> AsyncSink
                               ^ commit offsets only after confirmed push
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from transferia_tpu.abstract.interfaces import AsyncSink, Source
from transferia_tpu.chaos.failpoints import failpoint
from transferia_tpu.parsequeue import ParseQueue
from transferia_tpu.parsers import Message, Parser, make_parser
from transferia_tpu.stats import trace
from transferia_tpu.stats.registry import Metrics, SourceStats
from transferia_tpu.stats.watermark import POLL_PREFIX, WATERMARKS

logger = logging.getLogger(__name__)


class Sequencer:
    """Tracks in-flight (partition, offset) ranges; yields the highest
    offset safe to commit once pushes confirm (kafka/source.go sequencer:
    out-of-order acks must not commit past an unacked message)."""

    def __init__(self):
        self._lock = threading.Lock()
        # partition -> sorted list of [offset, acked]
        self._inflight: dict[tuple[str, int], list[list]] = {}

    def start_processing(self, topic: str, partition: int,
                         offsets: Sequence[int]) -> None:
        with self._lock:
            lst = self._inflight.setdefault((topic, partition), [])
            for o in offsets:
                lst.append([o, False])

    def ack(self, topic: str, partition: int,
            offsets: Sequence[int]) -> Optional[int]:
        """Mark offsets done; return new committable high-water mark (the
        largest offset with no unacked predecessors), or None."""
        with self._lock:
            lst = self._inflight.get((topic, partition), [])
            offset_set = set(offsets)
            for entry in lst:
                if entry[0] in offset_set:
                    entry[1] = True
            commit = None
            while lst and lst[0][1]:
                commit = lst.pop(0)[0]
            return commit


@dataclass
class FetchedBatch:
    topic: str
    partition: int
    messages: list[Message]

    def offsets(self) -> list[int]:
        return [m.offset for m in self.messages]


def pump_checkpoint(fb: FetchedBatch,
                    stats: Optional[SourceStats] = None,
                    transfer_id: str = "") -> None:
    """Per-fetched-batch pump bookkeeping, shared by every replication
    pump over a fetch/commit client (the QueueSource below and the
    MVCC activation pump, mvcc/pump.py): the `replication.pump`
    failpoint — a kill between fetch and enqueue, which the resuming
    pump must absorb by restarting from its last committed/admitted
    offset — plus the trace instant and source counters."""
    failpoint("replication.pump")
    trace.instant("replication_pump", topic=fb.topic,
                  partition=fb.partition,
                  messages=len(fb.messages))
    if stats is not None:
        stats.changeitems.inc(len(fb.messages))
        stats.read_bytes.inc(sum(len(m.value) for m in fb.messages))
    if transfer_id:
        # poll watermark: the newest broker write time seen for this
        # partition — the stand-in event time for batches whose
        # parser drops it
        wm = max((m.write_time_ns for m in fb.messages), default=0)
        if wm:
            WATERMARKS.advance(
                transfer_id, f"{POLL_PREFIX}{fb.topic}:{fb.partition}",
                event_ns=wm, origin="poll")


class QueueSource(Source):
    """Generic replication source over a fetch/commit client.

    client contract:
      fetch(max_messages) -> list[FetchedBatch] (blocking up to poll timeout)
      commit(topic, partition, offset) -> None
      close() -> None
    """

    def __init__(self, client, parser_config, parallelism: int = 4,
                 metrics: Optional[Metrics] = None,
                 stop_poll: float = 0.2, transfer_id: str = ""):
        self.client = client
        self.parser: Parser = make_parser(parser_config) \
            if parser_config else make_parser({"blank": {}})
        self.parallelism = parallelism
        self.stats = SourceStats(metrics or Metrics())
        self.sequencer = Sequencer()
        self._stop = threading.Event()
        self.stop_poll = stop_poll
        self.transfer_id = transfer_id

    def run(self, sink: AsyncSink) -> None:
        def parse(fb: FetchedBatch):
            t0 = time.monotonic()
            result = self.parser.do_batch(fb.messages)
            self.stats.decode_time.observe(time.monotonic() - t0)
            self.stats.parsed_rows.inc(result.row_count())
            if result.unparsed is not None:
                self.stats.unparsed_rows.inc(result.unparsed.n_rows)
            batches = list(result.batches)
            if result.unparsed is not None:
                batches.append(result.unparsed)
            return batches

        def ack(fb: FetchedBatch, err: Optional[BaseException]):
            if err is not None:
                return  # failure latches in the parsequeue; no commit
            commit = self.sequencer.ack(fb.topic, fb.partition,
                                        fb.offsets())
            if commit is not None:
                self.client.commit(fb.topic, fb.partition, commit)

        pq = ParseQueue(self.parallelism, sink, parse, ack)
        try:
            while not self._stop.is_set():
                if pq.failure is not None:
                    raise pq.failure
                fetched = self.client.fetch(max_messages=1024)
                if not fetched:
                    self._stop.wait(self.stop_poll)
                    continue
                for fb in fetched:
                    pump_checkpoint(fb, self.stats, self.transfer_id)
                    self.sequencer.start_processing(
                        fb.topic, fb.partition, fb.offsets()
                    )
                    pq.add(fb)
            pq.wait()
            if pq.failure is not None:
                raise pq.failure
        finally:
            pq.close()
            self.client.close()

    def stop(self) -> None:
        self._stop.set()
