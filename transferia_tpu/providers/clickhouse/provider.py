"""ClickHouse provider: sharded sink, snapshot storage, DDL builder.

Reference parity: providers/clickhouse/sink.go:24-100 (sharder -> per-shard
lazy sinks), schema/ (DDL from canonical types), storage (SELECT-based
snapshot).  Typesystem target rules registered for "ch".
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from transferia_tpu.abstract.commit import StagedSinker
from transferia_tpu.abstract.interfaces import (
    Batch,
    Pusher,
    SampleableStorage,
    Sinker,
    Storage,
    TableInfo,
    is_columnar,
)
from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.abstract.schema import (
    CanonicalType,
    ColSchema,
    TableID,
    TableSchema,
)
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch
from transferia_tpu.models.endpoint import (
    CleanupPolicy,
    EndpointParams,
    register_endpoint,
)
from transferia_tpu.providers.clickhouse.client import CHClient, CHError
from transferia_tpu.providers.clickhouse.rowbinary import encode_rowbinary
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)
from transferia_tpu.transform.plugins.sharder import hash_column_to_shards
from transferia_tpu.typesystem.rules import (
    register_source_rules,
    register_target_rules,
)

logger = logging.getLogger(__name__)

register_target_rules("ch", {
    CanonicalType.INT8: "Int8", CanonicalType.INT16: "Int16",
    CanonicalType.INT32: "Int32", CanonicalType.INT64: "Int64",
    CanonicalType.UINT8: "UInt8", CanonicalType.UINT16: "UInt16",
    CanonicalType.UINT32: "UInt32", CanonicalType.UINT64: "UInt64",
    CanonicalType.FLOAT: "Float32", CanonicalType.DOUBLE: "Float64",
    CanonicalType.BOOLEAN: "Bool", CanonicalType.STRING: "String",
    CanonicalType.UTF8: "String", CanonicalType.DATE: "Date32",
    CanonicalType.DATETIME: "DateTime",
    CanonicalType.TIMESTAMP: "DateTime64(6)",
    CanonicalType.INTERVAL: "Int64", CanonicalType.DECIMAL: "String",
    CanonicalType.ANY: "String",
})

register_source_rules("ch", {
    "int8": CanonicalType.INT8, "int16": CanonicalType.INT16,
    "int32": CanonicalType.INT32, "int64": CanonicalType.INT64,
    "uint8": CanonicalType.UINT8, "uint16": CanonicalType.UINT16,
    "uint32": CanonicalType.UINT32, "uint64": CanonicalType.UINT64,
    "float32": CanonicalType.FLOAT, "float64": CanonicalType.DOUBLE,
    "bool": CanonicalType.BOOLEAN, "string": CanonicalType.STRING,
    "date": CanonicalType.DATE, "date32": CanonicalType.DATE,
    "datetime": CanonicalType.DATETIME,
    "datetime64": CanonicalType.TIMESTAMP,
    "*": CanonicalType.ANY,
})


@dataclass
class CHShard:
    name: str
    hosts: list[str] = field(default_factory=list)


@register_endpoint
@dataclass
class CHTargetParams(EndpointParams):
    PROVIDER = "ch"
    IS_TARGET = True

    host: str = "localhost"
    port: int = 8123
    database: str = "default"
    user: str = "default"
    password: str = ""
    secure: bool = False
    shards: dict = field(default_factory=dict)   # name -> [host:port,...]
    cluster: str = ""   # discover shards from system.clusters instead
    shard_by: str = ""                           # column; "" = first PK
    engine: str = ""                             # override table engine
    insert_settings: dict = field(default_factory=dict)
    is_shardeable: bool = True
    bufferer: Optional[dict] = field(
        default_factory=lambda: {"trigger_rows": 100_000,
                                 "trigger_interval": 1.0}
    )

    def bufferer_config(self):
        return self.bufferer

    def shard_list(self) -> list[CHShard]:
        if not self.shards and self.cluster:
            return discover_cluster_shards(self)
        if not self.shards:
            return [CHShard("default", [f"{self.host}:{self.port}"])]
        return [CHShard(n, list(h)) for n, h in self.shards.items()]


def discover_cluster_shards(params: "CHTargetParams") -> list["CHShard"]:
    """Topology discovery (reference clickhouse/topology/): read the
    cluster's shard/replica layout from system.clusters on the seed host.
    Replicas within a shard become the shard's failover host list."""
    from transferia_tpu.providers.clickhouse.client import CHClient

    client = CHClient(host=params.host, port=params.port,
                      database=params.database, user=params.user,
                      password=params.password, secure=params.secure)
    rows = client.query_json(
        "SELECT shard_num, host_name, host_address, port "
        "FROM system.clusters "
        f"WHERE cluster = '{params.cluster}' "
        "ORDER BY shard_num, replica_num"
    )
    if not rows:
        raise ValueError(
            f"cluster {params.cluster!r} not found in system.clusters "
            f"on {params.host}:{params.port}"
        )
    by_shard: dict[int, list[str]] = {}
    for r in rows:
        host = r.get("host_address") or r.get("host_name")
        # system.clusters reports the NATIVE port; this provider speaks
        # HTTP, and cluster nodes conventionally share one HTTP port —
        # reuse the seed's (override with explicit `shards` otherwise)
        by_shard.setdefault(int(r["shard_num"]), []).append(
            f"{host}:{params.port}")
    out = [CHShard(f"shard{num}", hosts)
           for num, hosts in sorted(by_shard.items())]
    logger.info("discovered cluster %r: %d shards", params.cluster,
                len(out))
    return out


@register_endpoint
@dataclass
class CHSourceParams(EndpointParams):
    PROVIDER = "ch"
    IS_SOURCE = True

    host: str = "localhost"
    port: int = 8123
    database: str = "default"
    user: str = "default"
    password: str = ""
    secure: bool = False
    batch_rows: int = 131_072


def ddl_for_schema(table: TableID, schema: TableSchema,
                   engine: str = "", extra_cols: Optional[list] = None,
                   partition_by: str = "") -> str:
    """CREATE TABLE DDL from canonical schema (clickhouse/schema/).

    `extra_cols` ([(name, ch type)]) and `partition_by` serve the
    staged-commit sink: the final table carries the hidden
    `__trtpu_part` column and partitions by it, so a part publish maps
    onto ClickHouse's own atomic partition primitive
    (REPLACE/DROP PARTITION)."""
    from transferia_tpu.typesystem.rules import map_target_type

    cols = []
    for c in schema:
        ch_type = map_target_type("ch", c.data_type)
        if not c.required and not c.primary_key:
            ch_type = f"Nullable({ch_type})"
        cols.append(f"`{c.name}` {ch_type}")
    for name_, ch_type in extra_cols or []:
        cols.append(f"`{name_}` {ch_type}")
    keys = [f"`{c.name}`" for c in schema.key_columns()]
    order = ", ".join(keys) if keys else "tuple()"
    eng = engine or "MergeTree()"
    part = f" PARTITION BY `{partition_by}`" if partition_by else ""
    name = f"`{table.name}`" if not table.namespace \
        else f"`{table.namespace}__{table.name}`"
    return (
        f"CREATE TABLE IF NOT EXISTS {name} ({', '.join(cols)}) "
        f"ENGINE = {eng}{part} ORDER BY ({order})"
    )


def ch_table_name(table: TableID) -> str:
    return table.name if not table.namespace \
        else f"{table.namespace}__{table.name}"


class CHSinker(Sinker, StagedSinker):
    """Sharded insert sink (sink.go:24-100): rows fan out to shards by key
    hash; per-shard clients are lazy.  Deletes/updates collapse into
    ReplacingMergeTree semantics upstream (collapse middleware) — the sink
    itself inserts.

    Staged-commit capable on SINGLE-shard targets (abstract/commit.py):
    batches land in a per-(part, epoch) staging table and publish maps
    onto ClickHouse's atomic partition primitive — the final table is
    `PARTITION BY` the hidden `__trtpu_part` column and the publish is
    one `ALTER TABLE ... REPLACE PARTITION ID '<slug>' FROM <staging>`
    (empty restage: `DROP PARTITION ID`), fenced by the persisted
    max-epoch row per part in `__trtpu_commits`.  Multi-shard targets
    keep the at-least-once path: a part's rows span shards and there is
    no cross-shard atomic flip to map the publish onto.

    Migration bound: a final table created by the at-least-once path
    has no partition key, and ClickHouse cannot retrofit PARTITION BY
    onto an existing MergeTree — the first staged publish against such
    a table fails loudly at REPLACE PARTITION.  Recreate the table
    (CleanupPolicy.DROP does this at activation) before switching a
    pre-existing CH target to staged commits."""

    def __init__(self, params: CHTargetParams):
        self.params = params
        self.shards = params.shard_list()
        self._clients: dict[int, CHClient] = {}
        self._created: set[str] = set()
        self._stage = None  # staging.WireStage when open
        self._fence_ready = False

    def _client(self, shard_idx: int) -> CHClient:
        if shard_idx not in self._clients:
            host = self.shards[shard_idx].hosts[0]
            h, _, p = host.partition(":")
            self._clients[shard_idx] = CHClient(
                host=h, port=int(p or 8123),
                database=self.params.database, user=self.params.user,
                password=self.params.password, secure=self.params.secure,
                settings=self.params.insert_settings,
            )
        return self._clients[shard_idx]

    def close(self) -> None:
        # keep-alive pools hold sockets until released
        for client in self._clients.values():
            client.close()

    def _ensure_table(self, shard_idx: int, batch: ColumnBatch) -> None:
        self.ensure_table(shard_idx, batch.table_id, batch.schema)

    def ensure_table(self, shard_idx: int, table_id: TableID,
                     schema: TableSchema) -> None:
        """Create the target table on a shard once (also the a2 target's
        Init-event DDL path — one key scheme, one DDL builder)."""
        name = ch_table_name(table_id)
        key = f"{shard_idx}/{name}"
        if key in self._created:
            return
        ddl = ddl_for_schema(table_id, schema, self.params.engine)
        self._client(shard_idx).execute(ddl)
        self._created.add(key)

    def _shard_of(self, batch: ColumnBatch) -> np.ndarray:
        n_shards = len(self.shards)
        if n_shards == 1:
            return np.zeros(batch.n_rows, dtype=np.int32)
        col_name = self.params.shard_by
        if not col_name:
            keys = batch.schema.key_columns()
            col_name = keys[0].name if keys else next(iter(batch.columns))
        return hash_column_to_shards(batch.column(col_name), n_shards)

    def push(self, batch: Batch) -> None:
        if not is_columnar(batch):
            rows = [it for it in batch if it.is_row_event()]
            for it in batch:
                if it.kind in (Kind.TRUNCATE, Kind.DROP):
                    self._apply_cleanup(it.table_id, it.kind)
            if not rows:
                return
            batch = ColumnBatch.from_rows(rows)
        if batch.kinds is not None:
            raise ValueError(
                "CH sink is insert-only; collapse updates/deletes upstream "
                "or use a ReplacingMergeTree flow with version columns"
            )
        if self._stage is not None:
            self._stage_push(batch)
            return
        shards = self._shard_of(batch)
        nullable = {
            c.name: (not c.required and not c.primary_key)
            for c in batch.schema
        }
        for shard_idx in np.unique(shards):
            part = batch.filter(shards == shard_idx) \
                if len(self.shards) > 1 else batch
            self._ensure_table(int(shard_idx), part)
            payload = encode_rowbinary(part, nullable)
            self._client(int(shard_idx)).insert_rowbinary(
                ch_table_name(part.table_id), list(part.columns), payload
            )

    def _apply_cleanup(self, table: TableID, kind: Kind) -> None:
        stmt = "TRUNCATE TABLE IF EXISTS" if kind == Kind.TRUNCATE \
            else "DROP TABLE IF EXISTS"
        for i in range(len(self.shards)):
            self._client(i).execute(f"{stmt} `{ch_table_name(table)}`")

    # -- StagedSinker (publish = atomic partition swap) ---------------------
    def staged_commit_available(self) -> bool:
        # a part's rows span shards on a sharded target: no single
        # atomic partition flip exists to map the publish onto
        return len(self.shards) == 1

    def _ensure_fence_table(self) -> None:
        from transferia_tpu.providers.staging import COMMITS_TABLE

        if self._fence_ready:
            return
        self._client(0).execute(
            f"CREATE TABLE IF NOT EXISTS `{COMMITS_TABLE}` "
            f"(`part_key` String, `epoch` Int64) "
            f"ENGINE = MergeTree() ORDER BY (`part_key`)")
        self._fence_ready = True

    def begin_part(self, key: str, epoch: int) -> None:
        from transferia_tpu.providers.staging import (
            WireStage,
            stage_ident_prefix,
        )

        stage = WireStage(key, epoch)
        # begin replaces — for EVERY epoch of this key (a crashed
        # earlier owner's staging table would otherwise leak forever)
        pfx = stage_ident_prefix(key)
        for r in self._client(0).query_json(
                "SELECT name, total_rows FROM system.tables "
                f"WHERE database = '{self.params.database}'"):
            if str(r.get("name", "")).startswith(pfx):
                self._client(0).execute(
                    f"DROP TABLE IF EXISTS `{r['name']}`")
        self._ensure_fence_table()
        self._stage = stage

    def _stage_push(self, batch: ColumnBatch) -> None:
        from transferia_tpu.providers.staging import META_COLUMN

        stage = self._stage
        staged = stage.state.stage(batch)
        if stage.schema is None:
            stage.tid = batch.table_id
            stage.schema = batch.schema
            # SAME structure + partition key as the final table
            # (REPLACE PARTITION requires it); the part column
            # DEFAULTs to this part's slug so inserts that omit it
            # land the whole staging table in partition <slug>
            self._client(0).execute(ddl_for_schema(
                TableID("", stage.table), batch.schema,
                self.params.engine,
                extra_cols=[(META_COLUMN,
                             f"String DEFAULT '{stage.slug}'")],
                partition_by=META_COLUMN))
        if staged.n_rows == 0:
            return
        nullable = {
            c.name: (not c.required and not c.primary_key)
            for c in staged.schema
        }
        try:
            payload = encode_rowbinary(staged, nullable)
            self._client(0).insert_rowbinary(
                stage.table, list(staged.columns), payload)
        except BaseException:
            # the staging write died after the dedup window recorded
            # this batch: only a full part restage is safe
            stage.state.mark_failed()
            raise

    def _fence_epoch(self, slug: str):
        from transferia_tpu.providers.staging import COMMITS_TABLE

        v = self._client(0).scalar(
            f"SELECT max(`epoch`) FROM `{COMMITS_TABLE}` "
            f"WHERE `part_key` = '{slug}'")
        return int(v) if v is not None else None

    @staticmethod
    def _fence_row(slug: str, epoch: int) -> bytes:
        import struct

        raw = slug.encode()
        out = b""
        n = len(raw)
        while True:
            b7 = n & 0x7F
            n >>= 7
            if n:
                out += bytes([b7 | 0x80])
            else:
                out += bytes([b7])
                break
        return out + raw + struct.pack("<q", epoch)

    def publish_part(self, key: str, epoch: int) -> int:
        from transferia_tpu.abstract.errors import StaleEpochPublishError
        from transferia_tpu.chaos.failpoints import failpoint
        from transferia_tpu.providers.staging import (
            COMMITS_TABLE,
            META_COLUMN,
            publish_guard,
        )
        from transferia_tpu.stats import trace

        stage = self._stage
        if stage is None or stage.key != key:
            raise RuntimeError(f"ch sink: no open stage for {key!r}")
        with publish_guard(key, epoch):
            prev = self._fence_epoch(stage.slug)
            if prev is not None and epoch < prev:
                raise StaleEpochPublishError(key, epoch, prev)
            trace.instant("ch_publish_partition", part=key, epoch=epoch,
                          rows=stage.state.rows)
            failpoint("sink.ch.publish")
            client = self._client(0)
            if stage.schema is not None:
                final = ch_table_name(stage.tid)
                client.execute(ddl_for_schema(
                    stage.tid, stage.schema, self.params.engine,
                    extra_cols=[(META_COLUMN, "String")],
                    partition_by=META_COLUMN))
                # the atomic flip: this part's partition of the final
                # table becomes exactly the staged rows
                client.execute(
                    f"ALTER TABLE `{final}` REPLACE PARTITION ID "
                    f"'{stage.slug}' FROM `{stage.table}`")
            # persist the fence AFTER visibility: a crash in between
            # republishes idempotently (REPLACE swaps the same rows in)
            client.insert_rowbinary(
                COMMITS_TABLE, ["part_key", "epoch"],
                self._fence_row(stage.slug, epoch))
            client.execute(f"DROP TABLE IF EXISTS `{stage.table}`")
            self.last_dedup_dropped = stage.state.dedup_dropped
            rows = stage.state.rows
        self._stage = None
        return rows

    def abort_part(self, key: str) -> None:
        stage = self._stage
        if stage is None or stage.key != key:
            return
        self._stage = None
        try:
            self._client(0).execute(
                f"DROP TABLE IF EXISTS `{stage.table}`")
        except CHError as e:
            logger.warning("ch staged abort of %s: %s", key, e)

    def note_push_retry(self) -> None:
        if self._stage is not None:
            self._stage.state.note_push_retry()


class CHStorage(Storage, SampleableStorage):
    """Snapshot source over SELECT (storage + storage_sharding.go)."""

    def __init__(self, params: CHSourceParams):
        self.params = params
        self.client = CHClient(
            host=params.host, port=params.port, database=params.database,
            user=params.user, password=params.password,
            secure=params.secure,
        )
        self._name_cache: dict[TableID, str] = {}

    def close(self) -> None:
        self.client.close()

    def table_list(self, include=None):
        from transferia_tpu.providers.staging import is_meta_name

        rows = self.client.query_json(
            f"SELECT name, total_rows FROM system.tables "
            f"WHERE database = '{self.params.database}'"
        )
        out = {}
        for r in rows:
            if is_meta_name(r["name"]):
                continue  # staging/fence tables are not user data
            tid = TableID(self.params.database, r["name"])
            if include and not any(tid.include_matches(p) for p in include):
                continue
            out[tid] = TableInfo(eta_rows=int(r.get("total_rows") or 0))
        return out

    def _resolve_name(self, table: TableID) -> str:
        """Resolve a foreign TableID to this database's table name.

        The CH sink flattens "ns"."t" into `ns__t` (ch_table_name); a
        checksum against a CH target must find rows under that name when
        the bare name is absent."""
        name = table.name
        if not table.namespace or table.namespace == self.params.database:
            return name
        cached = self._name_cache.get(table)
        if cached is not None:
            return cached
        flat = f"{table.namespace}__{table.name}"
        n = self.client.scalar(
            "SELECT count() FROM system.tables "
            f"WHERE database = '{self.params.database}' "
            f"AND name = '{flat}'"
        )
        resolved = flat if int(n or 0) else name
        self._name_cache[table] = resolved
        return resolved

    def table_schema(self, table: TableID) -> TableSchema:
        from transferia_tpu.typesystem.rules import map_source_type

        rows = self.client.query_json(
            f"SELECT name, type, is_in_primary_key FROM system.columns "
            f"WHERE database = '{self.params.database}' "
            f"AND table = '{self._resolve_name(table)}'"
        )
        from transferia_tpu.providers.staging import is_meta_name

        cols = []
        for r in rows:
            if is_meta_name(r["name"]):
                continue  # hidden staged-commit part column
            ch_type = r["type"]
            nullable = ch_type.startswith("Nullable(")
            base = ch_type[9:-1] if nullable else ch_type
            cols.append(ColSchema(
                name=r["name"],
                data_type=map_source_type("ch", base.lower()),
                primary_key=bool(int(r.get("is_in_primary_key") or 0)),
                required=not nullable,
                original_type=f"ch:{ch_type}",
            ))
        return TableSchema(cols)

    def exact_table_rows_count(self, table: TableID) -> int:
        return int(self.client.scalar(
            f"SELECT count() FROM `{self._resolve_name(table)}`"
        ) or 0)

    def estimate_table_rows_count(self, table: TableID) -> int:
        return self.exact_table_rows_count(table)

    @staticmethod
    def _select_expr(c: ColSchema) -> str:
        """Types this decoder can't take off the wire (Decimal, UUID, Array,
        anything mapped to ANY/DECIMAL) are cast server-side to String."""
        if c.data_type in (CanonicalType.ANY, CanonicalType.DECIMAL):
            return f"toString(`{c.name}`) AS `{c.name}`"
        return f"`{c.name}`"

    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        where = f" WHERE {table.filter}" if table.filter else ""
        self._load_select(table.id, where_order_limit=where, pusher=pusher)

    def _load_select(self, tid: TableID, where_order_limit: str,
                     pusher: Pusher) -> None:
        from transferia_tpu.providers.clickhouse.rowbinary import (
            decode_rowbinary_stream,
        )

        schema = self.table_schema(tid)
        nullable = {c.name: not c.required for c in schema}
        cols = ", ".join(self._select_expr(c) for c in schema)
        read_fn, close_fn = self.client.execute_stream(
            f"SELECT {cols} FROM `{self._resolve_name(tid)}`"
            f"{where_order_limit} FORMAT RowBinary"
        )
        try:
            for batch in decode_rowbinary_stream(
                    read_fn, schema, nullable,
                    batch_rows=self.params.batch_rows):
                out = ColumnBatch(tid, schema, batch.columns)
                out.read_bytes = out.nbytes()
                pusher(out)
        finally:
            close_fn()

    # -- checksum sampling (clickhouse/storage_sampleable.go) ---------------
    RANDOM_SAMPLE_LIMIT = 2000
    TOP_BOTTOM_LIMIT = 1000

    def table_size_in_bytes(self, table: TableID) -> int:
        v = self.client.scalar(
            "SELECT sum(bytes_on_disk) FROM system.parts "
            f"WHERE database = '{self.params.database}' "
            f"AND table = '{self._resolve_name(table)}' AND active"
        )
        try:
            return int(v or 0)
        except (TypeError, ValueError):
            return 0

    def _order_cols(self, tid: TableID) -> list[str]:
        schema = self.table_schema(tid)
        return [c.name for c in schema.key_columns()]

    def load_random_sample(self, table: TableDescription,
                           pusher: Pusher) -> None:
        order = self._order_cols(table.id)
        by = " ORDER BY " + ", ".join(f"`{c}`" for c in order) if order \
            else ""
        # rand() is uniform over UInt32; 0.05 of the range
        cutoff = int(0.05 * 0xFFFFFFFF)
        self._load_select(
            table.id,
            f" WHERE rand() <= {cutoff}{by} "
            f"LIMIT {self.RANDOM_SAMPLE_LIMIT}",
            pusher,
        )

    def load_top_bottom_sample(self, table: TableDescription,
                               pusher: Pusher) -> None:
        order = self._order_cols(table.id)
        if not order:
            raise CHError(f"no sorting key on {table.id.name}; "
                          "cannot take top/bottom sample")
        asc = ", ".join(f"`{c}`" for c in order)
        desc = ", ".join(f"`{c}` DESC" for c in order)
        n = self.TOP_BOTTOM_LIMIT
        self._load_select(
            table.id, f" ORDER BY {asc} LIMIT {n}", pusher)
        self._load_select(
            table.id, f" ORDER BY {desc} LIMIT {n}", pusher)

    @staticmethod
    def _ch_literal(v) -> str:
        if v is None:
            return "NULL"
        if isinstance(v, bool):
            return "1" if v else "0"
        if isinstance(v, (int, float)):
            return str(v)
        if isinstance(v, bytes):
            v = v.decode("utf-8", "replace")
        s = str(v).replace("\\", "\\\\").replace("'", "\\'")
        return f"'{s}'"

    def load_sample_by_set(self, table: TableDescription, key_set,
                           pusher: Pusher) -> None:
        conds = [
            "(" + " AND ".join(
                f"`{name}` = {self._ch_literal(val)}"
                for name, val in key.items()) + ")"
            for key in key_set
        ]
        where = " OR ".join(conds) if conds else "0"
        self._load_select(table.id, f" WHERE {where}", pusher)

    def ping(self) -> None:
        self.client.ping()


@register_provider
class ClickHouseProvider(Provider):
    NAME = "ch"

    def storage(self):
        if isinstance(self.transfer.src, CHSourceParams):
            return CHStorage(self.transfer.src)
        return None

    def destination_storage(self):
        dst = self.transfer.dst
        if isinstance(dst, CHTargetParams):
            return CHStorage(CHSourceParams(
                host=dst.host, port=dst.port, database=dst.database,
                user=dst.user, password=dst.password, secure=dst.secure,
            ))
        return None

    def event_target(self):
        if isinstance(self.transfer.dst, CHTargetParams):
            from transferia_tpu.providers.clickhouse.a2 import CHEventTarget

            return CHEventTarget(self.transfer.dst)
        return None

    def sinker(self):
        if isinstance(self.transfer.dst, CHTargetParams):
            return CHSinker(self.transfer.dst)
        return None

    def cleanup(self, tables: list) -> None:
        params = self.transfer.dst
        sinker = CHSinker(params)
        kind = Kind.DROP if params.cleanup_policy == CleanupPolicy.DROP \
            else Kind.TRUNCATE
        for td in tables or []:
            tid = td.id if hasattr(td, "id") else td
            sinker._apply_cleanup(tid, kind)

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        params = self.transfer.dst or self.transfer.src
        client = CHClient(host=params.host, port=params.port,
                          database=params.database, user=params.user,
                          password=params.password, secure=params.secure)
        try:
            client.ping()
            result.add("ping")
        except Exception as e:
            result.add("ping", e)
        return result
