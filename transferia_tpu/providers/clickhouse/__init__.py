"""ClickHouse provider — the primary analytics target.

Reference parity: pkg/providers/clickhouse/ (sink.go sharded fan-out,
async/marshaller.go RowBinary encoding, schema/ DDL builder, conn/
HTTP interface).  Re-designed columnar: batches encode to RowBinary with
vectorized per-column scatters (no per-row loop — the reference's
marshaller is its CPU hot loop #3), and shard fan-out reuses the
hash_column_to_shards kernel.
"""

from transferia_tpu.providers.clickhouse.provider import (
    CHSourceParams,
    CHTargetParams,
    ClickHouseProvider,
)

__all__ = ["CHSourceParams", "CHTargetParams", "ClickHouseProvider"]
