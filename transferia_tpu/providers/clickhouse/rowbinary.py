"""Vectorized RowBinary encoder/decoder.

RowBinary is row-major (per row: each column's fixed-width value or
varint-length-prefixed bytes), which fights columnar layouts; the encoder
here never loops over rows in Python — per column it computes each row's
field byte-length, derives global row offsets with cumsums, and scatters
column bytes into the output with flat numpy gathers (the same
repeat/arange pattern the SHA kernel prep uses).  The decoder is the
inverse and powers the CH snapshot source.

Type wire formats (ClickHouse RowBinary):
  ints/floats: little-endian fixed width
  String:      LEB128 varint length + bytes
  Date:        uint16 days since epoch; Date32: int32 days
  DateTime:    uint32 seconds; DateTime64(6): int64 microseconds
  Bool:        uint8
  Nullable(T): 0x00 value-follows / 0x01 null (no value)
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from transferia_tpu.abstract.schema import CanonicalType
from transferia_tpu.columnar.batch import Column, ColumnBatch, _offsets_from_lengths


def _leb128_lengths(values: np.ndarray) -> np.ndarray:
    """Byte count of each value's LEB128 varint."""
    out = np.ones(len(values), dtype=np.int64)
    v = values.astype(np.int64)
    thresh = 128
    while (v >= thresh).any():
        out += v >= thresh
        thresh <<= 7
    return out


def _encode_varints(values: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """values -> (flat varint bytes, per-value byte length).

    Uses the native hostops kernel when available (single pass, no
    temporaries); numpy multi-pass otherwise.
    """
    from transferia_tpu.native import lib

    n = len(values)
    cdll = lib()
    if cdll is not None and n:
        out = np.empty(n * 10, dtype=np.uint8)
        lens = np.empty(n, dtype=np.int32)
        total = cdll.leb128_encode(
            np.ascontiguousarray(values, dtype=np.uint64), n, out, lens
        )
        return out[:total].copy(), lens.astype(np.int64)
    vlens = _leb128_lengths(values)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(vlens, out=offsets[1:])
    out = np.zeros(int(offsets[-1]), dtype=np.uint8)
    v = values.astype(np.uint64).copy()
    max_bytes = int(vlens.max()) if n else 0
    for b in range(max_bytes):
        active = vlens > b
        last = vlens == b + 1
        byte = (v & 0x7F).astype(np.uint8)
        byte = np.where(last, byte, byte | 0x80)
        idx = (offsets[:-1] + b)[active]
        out[idx] = byte[active]
        v >>= np.uint64(7)
    return out, vlens


def _fixed_width(ctype: CanonicalType) -> Optional[tuple[np.dtype, int]]:
    """Wire dtype for fixed-width canonical types."""
    table = {
        CanonicalType.INT8: np.dtype("<i1"),
        CanonicalType.INT16: np.dtype("<i2"),
        CanonicalType.INT32: np.dtype("<i4"),
        CanonicalType.INT64: np.dtype("<i8"),
        CanonicalType.UINT8: np.dtype("<u1"),
        CanonicalType.UINT16: np.dtype("<u2"),
        CanonicalType.UINT32: np.dtype("<u4"),
        CanonicalType.UINT64: np.dtype("<u8"),
        CanonicalType.FLOAT: np.dtype("<f4"),
        CanonicalType.DOUBLE: np.dtype("<f8"),
        CanonicalType.BOOLEAN: np.dtype("<u1"),
        CanonicalType.DATE: np.dtype("<i4"),      # as Date32
        CanonicalType.DATETIME: np.dtype("<u4"),
        CanonicalType.TIMESTAMP: np.dtype("<i8"),  # DateTime64(6)
        CanonicalType.INTERVAL: np.dtype("<i8"),
    }
    dt = table.get(ctype)
    return (dt, dt.itemsize) if dt is not None else None


class _EncodedColumn:
    """Per-row encoded field bytes for one column."""

    __slots__ = ("data", "lens")

    def __init__(self, data: np.ndarray, lens: np.ndarray):
        self.data = data   # flat uint8
        self.lens = lens   # (n,) int64 per-row field length


def _encode_column(col: Column, nullable: bool) -> _EncodedColumn:
    n = col.n_rows
    null_mask = None
    if col.validity is not None:
        null_mask = ~col.validity
    fixed = _fixed_width(col.ctype)
    if fixed is not None:
        dt, width = fixed
        vals = col.data.astype(dt.base, copy=False).astype(dt)
        body = np.ascontiguousarray(vals).view(np.uint8).reshape(n, width)
        if nullable:
            prefix = np.zeros((n, 1), dtype=np.uint8)
            if null_mask is not None:
                prefix[null_mask, 0] = 1
                body = body.copy()
                body[null_mask] = 0
                data = np.concatenate([prefix, body], axis=1)
                lens = np.where(null_mask, 1, 1 + width).astype(np.int64)
                # null rows carry only the prefix byte: compact via gather
                flat = data.reshape(-1)
                keep = np.ones((n, 1 + width), dtype=bool)
                keep[null_mask, 1:] = False
                return _EncodedColumn(flat[keep.reshape(-1)], lens)
            data = np.concatenate([prefix, body], axis=1)
            return _EncodedColumn(
                data.reshape(-1), np.full(n, 1 + width, dtype=np.int64)
            )
        if null_mask is not None and null_mask.any():
            body = body.copy()
            body[null_mask] = 0  # non-nullable target: nulls become zero
        return _EncodedColumn(
            body.reshape(-1), np.full(n, width, dtype=np.int64)
        )
    # var-width: varint(len) + bytes
    lens = (col.offsets[1:] - col.offsets[:-1]).astype(np.int64)
    if null_mask is not None:
        lens = np.where(null_mask, 0, lens)
    varint_bytes, varint_lens = _encode_varints(lens)
    field_lens = varint_lens + lens
    prefix_len = 0
    if nullable:
        field_lens = field_lens + 1
        prefix_len = 1
        if null_mask is not None:
            field_lens = np.where(null_mask, 1, field_lens)
    out_offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(field_lens, out=out_offsets[1:])
    out = np.zeros(int(out_offsets[-1]), dtype=np.uint8)
    pos = out_offsets[:-1]
    if nullable:
        if null_mask is not None:
            out[pos[null_mask]] = 1
        pos = pos + prefix_len
        if null_mask is not None:
            # null rows: only the prefix byte, stop here for them
            active = ~null_mask
        else:
            active = np.ones(n, dtype=bool)
    else:
        active = np.ones(n, dtype=bool) if null_mask is None else ~null_mask
        if null_mask is not None and null_mask.any():
            # non-nullable target: null strings encode as empty
            pass
    # scatter varints
    vo = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(varint_lens, out=vo[1:])
    if nullable and null_mask is not None:
        write_varint = active
    else:
        write_varint = np.ones(n, dtype=bool)
    sel = np.nonzero(write_varint)[0]
    if len(sel):
        vl = varint_lens[sel]
        total_v = int(vl.sum())
        dst = np.repeat(pos[sel], vl) + (
            np.arange(total_v) - np.repeat(
                np.concatenate([[0], np.cumsum(vl)[:-1]]), vl
            )
        )
        src = np.repeat(vo[:-1][sel], vl) + (
            np.arange(total_v) - np.repeat(
                np.concatenate([[0], np.cumsum(vl)[:-1]]), vl
            )
        )
        out[dst] = varint_bytes[src]
    # scatter string bodies
    body_sel = np.nonzero(active & (lens > 0))[0]
    if len(body_sel):
        bl = lens[body_sel]
        total_b = int(bl.sum())
        inner = np.arange(total_b) - np.repeat(
            np.concatenate([[0], np.cumsum(bl)[:-1]]), bl
        )
        dst = np.repeat(pos[body_sel] + varint_lens[body_sel], bl) + inner
        src = np.repeat(col.offsets[:-1][body_sel].astype(np.int64), bl) \
            + inner
        out[dst] = col.data[src]
    return _EncodedColumn(out, field_lens)


def encode_rowbinary(batch: ColumnBatch,
                     nullable: Optional[dict[str, bool]] = None) -> bytes:
    """ColumnBatch -> RowBinary bytes (column order = batch.columns order)."""
    n = batch.n_rows
    if n == 0:
        return b""
    nullable = nullable or {}
    encoded = [
        _encode_column(col, nullable.get(name,
                                         col.validity is not None))
        for name, col in batch.columns.items()
    ]
    row_lens = np.zeros(n, dtype=np.int64)
    for e in encoded:
        row_lens += e.lens
    row_offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(row_lens, out=row_offsets[1:])
    out = np.zeros(int(row_offsets[-1]), dtype=np.uint8)
    field_start = row_offsets[:-1].copy()
    from transferia_tpu.native import lib

    cdll = lib()
    for e in encoded:
        lens = e.lens
        total = int(lens.sum())
        if total:
            src_off = np.zeros(n, dtype=np.int64)
            np.cumsum(lens[:-1], out=src_off[1:])
            if cdll is not None:
                cdll.scatter_bytes(
                    np.ascontiguousarray(e.data),
                    src_off, np.ascontiguousarray(field_start),
                    np.ascontiguousarray(lens), n, out,
                )
            else:
                inner = np.arange(total) - np.repeat(src_off, lens)
                dst = np.repeat(field_start, lens) + inner
                out[dst] = e.data
        field_start += lens
    return out.tobytes()


# ---------------------------------------------------------------------------
# Decoder (CH snapshot source + tests)
# ---------------------------------------------------------------------------

class _NeedMore(Exception):
    """Row parse ran off the end of the buffer (partial network chunk)."""


def _wire_fixed(cs) -> Optional[tuple[np.dtype, int]]:
    """Per-column wire format, honoring the CH-native original type: a
    ClickHouse `Date` column is uint16 days on the wire while our canonical
    DATE encodes as Date32 (int32)."""
    if cs.original_type == "ch:Date":
        return np.dtype("<u2"), 2
    return _fixed_width(cs.data_type)


def _parse_row(buf: memoryview, pos: int, schema, nullable: dict,
               fixed: dict, out: dict) -> int:
    n = len(buf)
    for c in schema:
        if nullable.get(c.name, False):
            if pos >= n:
                raise _NeedMore()
            flag = buf[pos]
            pos += 1
            if flag == 1:
                out[c.name].append(None)
                continue
        fx = fixed[c.name]
        if fx is not None:
            dt, width = fx
            if pos + width > n:
                raise _NeedMore()
            v = np.frombuffer(buf[pos:pos + width], dtype=dt)[0]
            if c.data_type == CanonicalType.BOOLEAN:
                out[c.name].append(bool(v))
            elif c.data_type.is_float:
                out[c.name].append(float(v))
            else:
                out[c.name].append(int(v))
            pos += width
        else:
            ln = 0
            shift = 0
            while True:
                if pos >= n:
                    raise _NeedMore()
                b = buf[pos]
                pos += 1
                ln |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            if pos + ln > n:
                raise _NeedMore()
            raw = bytes(buf[pos:pos + ln])
            pos += ln
            if c.data_type == CanonicalType.STRING:
                out[c.name].append(raw)
            else:
                out[c.name].append(raw.decode("utf-8", "replace"))
    return pos


def decode_rowbinary(data: bytes, schema,
                     nullable: Optional[dict[str, bool]] = None
                     ) -> ColumnBatch:
    """RowBinary bytes -> ColumnBatch (whole buffer; tests + small reads)."""
    from transferia_tpu.abstract.schema import TableID

    nullable = nullable or {}
    buf = memoryview(data)
    pos = 0
    cols: dict[str, list] = {c.name: [] for c in schema}
    fixed = {c.name: _wire_fixed(c) for c in schema}
    while pos < len(buf):
        pos = _parse_row(buf, pos, schema, nullable, fixed, cols)
    return ColumnBatch.from_pydict(TableID("", "decoded"), schema, cols)


def decode_rowbinary_stream(read_fn, schema,
                            nullable: Optional[dict[str, bool]] = None,
                            batch_rows: int = 131_072,
                            chunk_bytes: int = 8 << 20):
    """Incremental decode: read_fn(n) -> bytes ('' = EOF).  Yields
    ColumnBatches of up to batch_rows rows in constant memory — partial
    rows at chunk boundaries carry over to the next chunk."""
    from transferia_tpu.abstract.schema import TableID

    nullable = nullable or {}
    fixed = {c.name: _wire_fixed(c) for c in schema}
    leftover = b""
    cols: dict[str, list] = {c.name: [] for c in schema}
    rows = 0
    eof = False
    while not eof:
        chunk = read_fn(chunk_bytes)
        if not chunk:
            eof = True
        data = leftover + chunk if leftover else chunk
        buf = memoryview(data)
        pos = 0
        while pos < len(buf):
            row_start = pos
            try:
                pos = _parse_row(buf, pos, schema, nullable, fixed, cols)
            except _NeedMore:
                if eof:
                    raise ValueError(
                        "rowbinary stream truncated mid-row"
                    ) from None
                pos = row_start
                break
            rows += 1
            if rows >= batch_rows:
                yield ColumnBatch.from_pydict(
                    TableID("", "decoded"), schema, cols
                )
                cols = {c.name: [] for c in schema}
                rows = 0
        leftover = bytes(buf[pos:])
    if rows:
        yield ColumnBatch.from_pydict(TableID("", "decoded"), schema, cols)
