"""ClickHouse HTTP interface client (reference: providers/clickhouse/conn/).

Pure stdlib http.client: POST queries, stream INSERT bodies, basic auth,
per-query settings.  The HTTP interface (port 8123) is the most portable CH
surface and keeps the client dependency-free.
"""

from __future__ import annotations

import http.client
import logging
import threading
import time
import urllib.parse
from typing import Iterator, Optional

from transferia_tpu.abstract.errors import CategorizedError

logger = logging.getLogger(__name__)


class CHError(CategorizedError):
    def __init__(self, message: str, code: Optional[int] = None):
        super().__init__(CategorizedError.TARGET, message)
        self.code = code


class CHClient:
    # retire pooled sockets idle longer than this before sending.  The
    # common stale-keep-alive failure mode is request() writing into a
    # half-closed socket successfully and getresponse() failing — a path
    # that can never be retried safely (the body may have executed), so
    # it always surfaced a CHError to the outer retrier.  Proactively
    # reconnecting under the server's keep_alive_timeout (3s on older
    # ClickHouse releases, 10s on newer) avoids ever entering that race
    # while keeping the conservative no-retry-after-send policy.
    KEEP_ALIVE_IDLE = 2.5

    def __init__(self, host: str = "localhost", port: int = 8123,
                 database: str = "default", user: str = "default",
                 password: str = "", secure: bool = False,
                 timeout: float = 300.0,
                 settings: Optional[dict] = None,
                 keep_alive_idle: Optional[float] = None):
        self.host = host
        self.port = port
        self.database = database
        self.user = user
        self.password = password
        self.secure = secure
        self.timeout = timeout
        self.settings = settings or {}
        self.keep_alive_idle = (self.KEEP_ALIVE_IDLE
                                if keep_alive_idle is None
                                else keep_alive_idle)
        # keep-alive: one persistent connection per thread (sink workers
        # push concurrently) — a connect+teardown per INSERT dominated the
        # small-batch replication profile.  All pooled connections are
        # tracked so close() can release them regardless of which thread
        # created them.
        self._local = threading.local()
        self._pool_lock = threading.Lock()
        self._all_conns: list = []

    def _connect(self) -> http.client.HTTPConnection:
        cls = http.client.HTTPSConnection if self.secure \
            else http.client.HTTPConnection
        return cls(self.host, self.port, timeout=self.timeout)

    def _pooled(self) -> tuple[http.client.HTTPConnection, bool]:
        """(connection, reused): reused reflects the RETURNED socket —
        a proactively retired idle connection hands back a fresh one,
        which must not qualify for the stale-keep-alive retry."""
        conn = getattr(self._local, "conn", None)
        if conn is not None and self.keep_alive_idle > 0 and \
                time.monotonic() - getattr(conn, "_last_use", 0.0) \
                > self.keep_alive_idle:
            # idle past the server keep-alive window: the socket may be
            # half-closed server-side; drop it before sending
            self._drop_pooled()
            conn = None
        reused = conn is not None
        if conn is None:
            conn = self._connect()
            conn._last_use = time.monotonic()
            self._local.conn = conn
            with self._pool_lock:
                self._all_conns.append(conn)
        return conn, reused

    def _drop_pooled(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
            self._local.conn = None
            with self._pool_lock:
                try:
                    self._all_conns.remove(conn)
                except ValueError:
                    pass

    def close(self) -> None:
        """Release every pooled connection (all threads)."""
        with self._pool_lock:
            conns, self._all_conns = self._all_conns, []
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        self._local = threading.local()

    def _params(self, query: str, extra: Optional[dict] = None) -> str:
        params = {
            "database": self.database,
            "query": query,
            **{f"{k}": str(v) for k, v in self.settings.items()},
            **(extra or {}),
        }
        return urllib.parse.urlencode(params)

    def execute(self, query: str, body: bytes = b"",
                extra_params: Optional[dict] = None) -> bytes:
        """Run a query; body carries INSERT payload bytes.

        Rides the thread's keep-alive connection; a dead/half-closed
        connection (server restart, idle timeout) gets one transparent
        retry on a fresh socket before the error surfaces."""
        headers = {"Content-Type": "application/octet-stream"}
        if self.user:
            import base64

            cred = base64.b64encode(
                f"{self.user}:{self.password}".encode()
            ).decode()
            headers["Authorization"] = f"Basic {cred}"
        path = "/?" + self._params(query, extra_params)
        for attempt in (0, 1):
            conn, reused = self._pooled()
            sent = False
            try:
                conn.request("POST", path, body=body, headers=headers)
                sent = True
                resp = conn.getresponse()
                data = resp.read()
            except (ConnectionError, OSError,
                    http.client.HTTPException) as e:
                self._drop_pooled()
                # Retry ONLY the stale-keep-alive race: a REUSED socket
                # failing before the request went out (server closed the
                # idle connection).  Once the body was sent the server
                # may have executed a non-idempotent INSERT — resending
                # would duplicate rows, so the error surfaces instead
                # (the sink's retry policy owns that decision).
                if attempt == 0 and reused and not sent:
                    continue
                raise CHError(f"clickhouse connection failed: {e}") from e
            if resp.status != 200:
                # responses may close the stream on error statuses
                if resp.will_close:
                    self._drop_pooled()
                raise CHError(
                    f"clickhouse HTTP {resp.status}: "
                    f"{data[:500].decode('utf-8', 'replace')}",
                    code=resp.status,
                )
            if resp.will_close:
                self._drop_pooled()
            else:
                conn._last_use = time.monotonic()
            return data
        raise CHError("clickhouse connection failed")  # unreachable

    def execute_stream(self, query: str):
        """Run a query and return (read_fn, close_fn) streaming the response
        body in chunks — snapshot reads must not buffer whole tables."""
        conn = self._connect()
        headers = {"Content-Type": "application/octet-stream"}
        if self.user:
            import base64

            cred = base64.b64encode(
                f"{self.user}:{self.password}".encode()
            ).decode()
            headers["Authorization"] = f"Basic {cred}"
        try:
            conn.request("POST", "/?" + self._params(query),
                         body=b"", headers=headers)
            resp = conn.getresponse()
            if resp.status != 200:
                data = resp.read()
                conn.close()
                raise CHError(
                    f"clickhouse HTTP {resp.status}: "
                    f"{data[:500].decode('utf-8', 'replace')}",
                    code=resp.status,
                )
        except (ConnectionError, OSError, http.client.HTTPException) as e:
            conn.close()
            raise CHError(f"clickhouse connection failed: {e}") from e
        return resp.read, conn.close

    def ping(self) -> None:
        out = self.execute("SELECT 1")
        if out.strip() != b"1":
            raise CHError(f"unexpected ping response {out[:50]!r}")

    def insert_rowbinary(self, table: str, columns: list[str],
                         payload: bytes) -> None:
        cols = ", ".join(f"`{c}`" for c in columns)
        self.execute(
            f"INSERT INTO {table} ({cols}) FORMAT RowBinary", payload
        )

    def query_json(self, query: str) -> list[dict]:
        import json

        raw = self.execute(query + " FORMAT JSON")
        return json.loads(raw).get("data", [])

    def query_rows(self, query: str) -> list[list]:
        import json

        raw = self.execute(query + " FORMAT JSONCompact")
        return json.loads(raw).get("data", [])

    def scalar(self, query: str):
        rows = self.query_rows(query)
        return rows[0][0] if rows and rows[0] else None
