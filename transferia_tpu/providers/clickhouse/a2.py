"""Native ClickHouse event-model-v2 target.

Reference parity: pkg/providers/clickhouse/a2_*.go (the a2 sink that
consumes typed events).  InsertBatchEvent columnar blocks drive the
sharded RowBinary writer directly — no detour through v1 row items — and
Init TableLoadEvents create the table from the schema they carry before
the first block arrives, so wide inserts never race DDL.
"""

from __future__ import annotations

import concurrent.futures
import logging
from typing import Sequence

from transferia_tpu.events.model import (
    Event,
    InsertBatchEvent,
    RawItems,
    RowEvents,
    TableLoadEvent,
)
from transferia_tpu.events.pipeline import EventTarget
from transferia_tpu.providers.clickhouse.provider import (
    CHSinker,
    CHTargetParams,
)

logger = logging.getLogger(__name__)


class CHEventTarget(EventTarget):
    def __init__(self, params: CHTargetParams):
        self.sinker = CHSinker(params)

    def _precreate(self, ev: TableLoadEvent) -> None:
        if ev.schema is None:
            return
        for shard_idx in range(len(self.sinker.shards)):
            self.sinker.ensure_table(shard_idx, ev.table_id, ev.schema)

    def async_push(self, events: Sequence[Event]
                   ) -> "concurrent.futures.Future[None]":
        fut: concurrent.futures.Future = concurrent.futures.Future()
        try:
            for ev in events:
                if isinstance(ev, TableLoadEvent):
                    if not ev.is_done:
                        self._precreate(ev)
                elif isinstance(ev, InsertBatchEvent):
                    self.sinker.push(ev.batch)
                elif isinstance(ev, (RowEvents, RawItems)):
                    self.sinker.push(ev.items)
                else:
                    raise TypeError(
                        f"CH a2 target: unknown event "
                        f"{type(ev).__name__}")
            fut.set_result(None)
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            fut.set_exception(e)
        return fut

    def close(self) -> None:
        self.sinker.close()
