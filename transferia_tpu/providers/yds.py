"""Yandex Data Streams (YDS) replication source.

Reference: pkg/providers/yds/source/model_source.go:14-44 (Endpoint /
Database / Stream / Consumer + parser config) — there it rides the
persqueue SDK.  YDS also exposes an AWS-Kinesis-compatible HTTP surface
(streams are addressed as "<database>/<stream>"), so this provider is a
thin specialization of the framework's dependency-free Kinesis client
(providers/kinesis.py): shards map to partitions, sequence numbers are
the checkpoint tokens, parsers and the at-least-once ack discipline come
from the shared QueueSource machinery.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional

from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.kinesis import (
    KinesisClient,
    KinesisSourceParams,
    _KinesisQueueClient,
)
from transferia_tpu.providers.queue_common import QueueSource
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)

logger = logging.getLogger(__name__)

# the public Kinesis-compatible YDS frontend
DEFAULT_ENDPOINT = "https://yds.serverless.yandexcloud.net"


@register_endpoint
@dataclass
class YDSSourceParams(EndpointParams):
    PROVIDER = "yds"
    IS_SOURCE = True
    # queue sources cannot be re-read from scratch: reupload
    # is forbidden (model/endpoint.go AppendOnlySource)
    is_append_only = True

    database: str = ""    # /region/folder/db path
    stream: str = ""
    endpoint: str = DEFAULT_ENDPOINT
    consumer: str = ""    # kept for reference-API parity (unused on the
    #                       Kinesis surface: position is client-side)
    access_key: str = ""  # YC static access key for the AWS-compat API
    secret_key: str = ""
    parser: Optional[dict] = None
    parallelism: int = 4
    start_from: str = "earliest"

    @property
    def qualified_stream(self) -> str:
        """Kinesis StreamName for a YDS stream: '<database>/<stream>'."""
        if self.database:
            return f"{self.database.rstrip('/')}/{self.stream}"
        return self.stream

    def parser_config(self):
        return self.parser

    def to_kinesis_params(self) -> KinesisSourceParams:
        return KinesisSourceParams(
            stream=self.qualified_stream,
            region="ru-central1",
            access_key=self.access_key,
            secret_key=self.secret_key,
            endpoint=self.endpoint,
            parser=self.parser,
            parallelism=self.parallelism,
            start_from=self.start_from,
        )


class _YDSQueueClient(_KinesisQueueClient):
    STATE_KEY = "yds_sequences"


@register_provider
class YDSProvider(Provider):
    NAME = "yds"

    def source(self):
        if not isinstance(self.transfer.src, YDSSourceParams):
            return None
        p = self.transfer.src
        client = _YDSQueueClient(p.to_kinesis_params(), self.transfer.id,
                                 self.coordinator)
        return QueueSource(client, p.parser_config(),
                           parallelism=p.parallelism,
                           metrics=self.metrics,
                           transfer_id=self.transfer.id)

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        p = self.transfer.src
        try:
            kp = p.to_kinesis_params()
            KinesisClient(
                region=kp.region, access_key=kp.access_key,
                secret_key=kp.secret_key, endpoint=kp.endpoint,
            ).list_shards(kp.stream)
            result.add("list_shards")
        except Exception as e:
            result.add("list_shards", e)
        return result
