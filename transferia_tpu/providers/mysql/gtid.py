"""MySQL GTID set model (executed-GTID tracking + dump encoding).

Reference parity: pkg/providers/mysql/sync_binlog_position.go + the
coordinator's MysqlGtidState (pkg/abstract/coordinator/transfer_state.go:
17-25) — replication resumes from an executed-GTID set instead of a
binlog file+position, surviving source failovers where file names change.

Format: the standard "uuid:1-5:7,uuid2:1-3" executed-set string; the
binary encoding is COM_BINLOG_DUMP_GTID's SID block (n_sids u64le, then
per sid: 16 raw uuid bytes, n_intervals u64le, and start/end u64le pairs
with EXCLUSIVE end).
"""

from __future__ import annotations

import struct
import uuid as uuid_mod


class GtidSet:
    def __init__(self) -> None:
        # uuid(str, dashed lowercase) -> sorted list of [start, end]
        # intervals, end INCLUSIVE in this in-memory form
        self.sids: dict[str, list[list[int]]] = {}

    # -- construction -------------------------------------------------------
    @classmethod
    def parse(cls, text: str) -> "GtidSet":
        out = cls()
        for part in (text or "").replace("\n", "").split(","):
            part = part.strip()
            if not part:
                continue
            chunks = part.split(":")
            try:
                sid = str(uuid_mod.UUID(chunks[0].strip()))
            except ValueError:
                continue  # not a GTID sid (malformed server output)
            for rng in chunks[1:]:
                if "-" in rng:
                    a, b = rng.split("-", 1)
                    out._add_interval(sid, int(a), int(b))
                else:
                    out._add_interval(sid, int(rng), int(rng))
        return out

    def copy(self) -> "GtidSet":
        out = GtidSet()
        out.sids = {k: [iv[:] for iv in v] for k, v in self.sids.items()}
        return out

    # -- mutation -----------------------------------------------------------
    def add(self, sid: str, gno: int) -> None:
        self._add_interval(sid.lower(), gno, gno)

    def _add_interval(self, sid: str, start: int, end: int) -> None:
        ivs = self.sids.setdefault(sid, [])
        ivs.append([start, end])
        ivs.sort()
        merged: list[list[int]] = []
        for iv in ivs:
            if merged and iv[0] <= merged[-1][1] + 1:
                merged[-1][1] = max(merged[-1][1], iv[1])
            else:
                merged.append(iv)
        self.sids[sid] = merged

    def update(self, other: "GtidSet") -> None:
        for sid, ivs in other.sids.items():
            for a, b in ivs:
                self._add_interval(sid, a, b)

    # -- queries ------------------------------------------------------------
    def contains(self, sid: str, gno: int) -> bool:
        for a, b in self.sids.get(sid.lower(), []):
            if a <= gno <= b:
                return True
        return False

    def __bool__(self) -> bool:
        return bool(self.sids)

    def __eq__(self, other) -> bool:
        return isinstance(other, GtidSet) and self.sids == other.sids

    # -- formats ------------------------------------------------------------
    def __str__(self) -> str:
        parts = []
        for sid in sorted(self.sids):
            rngs = ":".join(
                f"{a}-{b}" if a != b else str(a)
                for a, b in self.sids[sid]
            )
            parts.append(f"{sid}:{rngs}")
        return ",".join(parts)

    def encode(self) -> bytes:
        """COM_BINLOG_DUMP_GTID SID-block encoding (end exclusive)."""
        out = struct.pack("<Q", len(self.sids))
        for sid in sorted(self.sids):
            out += uuid_mod.UUID(sid).bytes
            ivs = self.sids[sid]
            out += struct.pack("<Q", len(ivs))
            for a, b in ivs:
                out += struct.pack("<QQ", a, b + 1)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "GtidSet":
        out = cls()
        (n_sids,) = struct.unpack_from("<Q", data, 0)
        pos = 8
        for _ in range(n_sids):
            sid = str(uuid_mod.UUID(bytes=data[pos:pos + 16]))
            pos += 16
            (n_ivs,) = struct.unpack_from("<Q", data, pos)
            pos += 8
            for _ in range(n_ivs):
                a, b = struct.unpack_from("<QQ", data, pos)
                pos += 16
                out._add_interval(sid, a, b - 1)
        return out
