"""MySQL client/server protocol (pure stdlib).

Packets: 3-byte little-endian length + 1-byte sequence id.  Implements the
handshake (v10), mysql_native_password and the caching_sha2_password fast
path, COM_QUERY with text-protocol resultsets (EOF framing — the
DEPRECATE_EOF capability is deliberately not negotiated), and COM_PING.
"""

from __future__ import annotations

import hashlib
import socket
import struct
from typing import Optional

from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.utils.net import BufferedSock, recv_exact

CLIENT_LONG_PASSWORD = 0x1
CLIENT_PROTOCOL_41 = 0x200
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 0x80000
CLIENT_CONNECT_WITH_DB = 0x8

COM_QUIT = 0x01
COM_QUERY = 0x03
COM_PING = 0x0E


class MySQLError(CategorizedError):
    def __init__(self, message: str, errno: int = 0):
        super().__init__(CategorizedError.SOURCE, message)
        self.errno = errno


def _native_password_token(password: str, nonce: bytes) -> bytes:
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(nonce + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def _caching_sha2_token(password: str, nonce: bytes) -> bytes:
    if not password:
        return b""
    h1 = hashlib.sha256(password.encode()).digest()
    h2 = hashlib.sha256(hashlib.sha256(h1).digest() + nonce).digest()
    return bytes(a ^ b for a, b in zip(h1, h2))


class MySQLConnection:
    def __init__(self, host: str = "localhost", port: int = 3306,
                 database: str = "", user: str = "root",
                 password: str = "", timeout: float = 60.0):
        self.host = host
        self.port = port
        self.database = database
        self.user = user
        self.password = password
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self._seq = 0

    # -- framing ------------------------------------------------------------
    def _recv_exact(self, n: int) -> bytes:
        try:
            return recv_exact(self.sock, n)
        except ConnectionError as e:
            raise MySQLError(str(e)) from e

    _MAX_PACKET = 0xFFFFFF

    def _read_packet(self) -> bytes:
        """Read one logical packet, rejoining 16MB-split frames."""
        out = b""
        while True:
            header = self._recv_exact(4)
            length = header[0] | (header[1] << 8) | (header[2] << 16)
            self._seq = (header[3] + 1) & 0xFF
            out += self._recv_exact(length)
            if length < self._MAX_PACKET:
                return out

    def _send_packet(self, payload: bytes) -> None:
        """Send one logical packet, splitting at the 16MB frame limit."""
        pos = 0
        while True:
            chunk = payload[pos:pos + self._MAX_PACKET]
            header = struct.pack("<I", len(chunk))[:3] + bytes([self._seq])
            self._seq = (self._seq + 1) & 0xFF
            self.sock.sendall(header + chunk)
            pos += len(chunk)
            if len(chunk) < self._MAX_PACKET:
                return

    @staticmethod
    def _err(payload: bytes) -> MySQLError:
        errno = struct.unpack_from("<H", payload, 1)[0]
        msg = payload[3:]
        if msg[:1] == b"#":
            msg = msg[6:]
        return MySQLError(msg.decode("utf-8", "replace"), errno)

    # -- handshake ----------------------------------------------------------
    def connect(self) -> "MySQLConnection":
        raw = socket.create_connection((self.host, self.port),
                                       timeout=self.timeout)
        raw.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # MySQL frames rows as individual packets: raw per-packet recv is
        # 2+ syscalls per ROW during snapshots.  Buffered reads refill in
        # 256KiB chunks (binlog.py probes pending() before select so
        # buffered frames never stall the stream)
        self.sock = BufferedSock(raw)
        self._seq = 0
        greeting = self._read_packet()
        if greeting[:1] == b"\xff":
            raise self._err(greeting)
        pos = 1
        end = greeting.index(b"\x00", pos)
        pos = end + 1
        pos += 4  # thread id
        nonce = greeting[pos:pos + 8]
        pos += 9  # auth part1 + filler
        pos += 2  # cap low
        plugin = "mysql_native_password"
        if len(greeting) > pos:
            pos += 1 + 2 + 2  # charset, status, cap high
            auth_len = greeting[pos]
            pos += 1 + 10     # auth len + reserved
            extra = max(13, auth_len - 8)
            part2 = greeting[pos:pos + extra]
            if part2.endswith(b"\x00"):
                # exactly ONE protocol NUL terminator: a scramble byte
                # that happens to be 0x00 must survive (real servers send
                # ASCII scrambles, but rstrip would eat it)
                part2 = part2[:-1]
            nonce += part2
            pos += extra
            nul = greeting.find(b"\x00", pos)
            if nul > pos:
                plugin = greeting[pos:nul].decode()
        token = (_caching_sha2_token(self.password, nonce)
                 if plugin == "caching_sha2_password"
                 else _native_password_token(self.password, nonce[:20]))
        caps = (CLIENT_LONG_PASSWORD | CLIENT_PROTOCOL_41
                | CLIENT_SECURE_CONNECTION | CLIENT_PLUGIN_AUTH)
        if self.database:
            caps |= CLIENT_CONNECT_WITH_DB
        resp = struct.pack("<IIB23x", caps, 1 << 24, 33)
        resp += self.user.encode() + b"\x00"
        resp += bytes([len(token)]) + token
        if self.database:
            resp += self.database.encode() + b"\x00"
        resp += plugin.encode() + b"\x00"
        self._send_packet(resp)
        self._auth_finish(nonce)
        return self

    def _auth_finish(self, nonce: bytes) -> None:
        while True:
            pkt = self._read_packet()
            head = pkt[:1]
            if head == b"\x00":
                return  # OK
            if head == b"\xff":
                raise self._err(pkt)
            if head == b"\xfe":  # AuthSwitchRequest
                nul = pkt.index(b"\x00", 1)
                plugin = pkt[1:nul].decode()
                new_nonce = pkt[nul + 1:]
                if new_nonce.endswith(b"\x00"):
                    # exactly ONE protocol terminator (same rule as the
                    # greeting scramble: a 0x00 scramble byte survives)
                    new_nonce = new_nonce[:-1]
                if plugin == "mysql_native_password":
                    self._send_packet(
                        _native_password_token(self.password, new_nonce)
                    )
                elif plugin == "caching_sha2_password":
                    self._send_packet(
                        _caching_sha2_token(self.password, new_nonce)
                    )
                else:
                    raise MySQLError(
                        f"unsupported auth plugin {plugin!r}"
                    )
            elif head == b"\x01":  # caching_sha2 extra data
                if pkt[1:2] == b"\x03":
                    continue  # fast-auth success; OK follows
                raise MySQLError(
                    "caching_sha2_password full auth requires TLS; "
                    "use mysql_native_password for this user"
                )
            else:
                raise MySQLError(f"unexpected auth packet {pkt[:2]!r}")

    def close(self) -> None:
        if self.sock is not None:
            try:
                self._seq = 0
                self._send_packet(bytes([COM_QUIT]))
            except OSError:
                pass
            self.sock.close()
            self.sock = None

    # -- lenenc helpers -----------------------------------------------------
    @staticmethod
    def _lenenc(payload: bytes, pos: int) -> tuple[Optional[int], int]:
        first = payload[pos]
        if first < 0xFB:
            return first, pos + 1
        if first == 0xFB:
            return None, pos + 1  # NULL
        if first == 0xFC:
            return struct.unpack_from("<H", payload, pos + 1)[0], pos + 3
        if first == 0xFD:
            v = payload[pos + 1] | (payload[pos + 2] << 8) \
                | (payload[pos + 3] << 16)
            return v, pos + 4
        return struct.unpack_from("<Q", payload, pos + 1)[0], pos + 9

    # -- queries ------------------------------------------------------------
    def query(self, sql: str) -> list[dict]:
        """COM_QUERY; text-protocol rows as dicts (None = NULL)."""
        self._seq = 0
        self._send_packet(bytes([COM_QUERY]) + sql.encode())
        first = self._read_packet()
        if first[:1] == b"\xff":
            raise self._err(first)
        if first[:1] == b"\x00":
            return []  # OK (DML/DDL)
        n_cols, _ = self._lenenc(first, 0)
        columns = []
        for _ in range(n_cols):
            defn = self._read_packet()
            columns.append(self._parse_column_name(defn))
        eof = self._read_packet()  # EOF after column defs
        rows = []
        while True:
            pkt = self._read_packet()
            if pkt[:1] == b"\xfe" and len(pkt) < 9:
                return rows  # EOF
            if pkt[:1] == b"\xff":
                raise self._err(pkt)
            pos = 0
            vals = []
            for _ in range(n_cols):
                ln, pos = self._lenenc(pkt, pos)
                if ln is None:
                    vals.append(None)
                else:
                    vals.append(
                        pkt[pos:pos + ln].decode("utf-8", "replace")
                    )
                    pos += ln
            rows.append(dict(zip(columns, vals)))

    @staticmethod
    def _parse_column_name(defn: bytes) -> str:
        """Column definition packet: catalog/schema/table/org_table/name."""
        pos = 0
        name = ""
        for i in range(5):
            first = defn[pos]
            ln = first
            pos += 1
            if first == 0xFC:
                ln = struct.unpack_from("<H", defn, pos)[0]
                pos += 2
            field_val = defn[pos:pos + ln]
            pos += ln
            if i == 4:
                name = field_val.decode("utf-8", "replace")
        return name

    def scalar(self, sql: str):
        rows = self.query(sql)
        if not rows:
            return None
        return next(iter(rows[0].values()))

    def ping(self) -> None:
        self._seq = 0
        self._send_packet(bytes([COM_PING]))
        pkt = self._read_packet()
        if pkt[:1] != b"\x00":
            raise MySQLError(f"ping failed: {pkt[:2]!r}")
