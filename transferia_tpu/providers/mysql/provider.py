"""MySQL storage/sink (providers/mysql/storage.go, schema discovery,
typesystem.go rules; sharded reads via key-range splitting)."""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.interfaces import (
    SampleableStorage,
    Batch,
    IncrementalStorage,
    PositionalStorage,
    Pusher,
    Sinker,
    Storage,
    TableInfo,
    is_columnar,
)
from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.abstract.schema import (
    CanonicalType,
    ColSchema,
    TableID,
    TableSchema,
)
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch
from transferia_tpu.models.endpoint import (
    CleanupPolicy,
    EndpointParams,
    register_endpoint,
)
from transferia_tpu.providers.mysql.wire import MySQLConnection, MySQLError
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)
from transferia_tpu.typesystem.rules import (
    register_source_rules,
    register_target_rules,
)

logger = logging.getLogger(__name__)

register_source_rules("mysql", {
    "tinyint": CanonicalType.INT8, "smallint": CanonicalType.INT16,
    "mediumint": CanonicalType.INT32, "int": CanonicalType.INT32,
    "bigint": CanonicalType.INT64,
    "tinyint unsigned": CanonicalType.UINT8,
    "smallint unsigned": CanonicalType.UINT16,
    "int unsigned": CanonicalType.UINT32,
    "bigint unsigned": CanonicalType.UINT64,
    "float": CanonicalType.FLOAT, "double": CanonicalType.DOUBLE,
    "decimal": CanonicalType.DECIMAL,
    "bit": CanonicalType.UINT64, "bool": CanonicalType.BOOLEAN,
    "char": CanonicalType.UTF8, "varchar": CanonicalType.UTF8,
    "text": CanonicalType.UTF8, "tinytext": CanonicalType.UTF8,
    "mediumtext": CanonicalType.UTF8, "longtext": CanonicalType.UTF8,
    "binary": CanonicalType.STRING, "varbinary": CanonicalType.STRING,
    "blob": CanonicalType.STRING, "tinyblob": CanonicalType.STRING,
    "mediumblob": CanonicalType.STRING, "longblob": CanonicalType.STRING,
    "date": CanonicalType.DATE, "datetime": CanonicalType.TIMESTAMP,
    "timestamp": CanonicalType.TIMESTAMP, "time": CanonicalType.UTF8,
    "year": CanonicalType.INT32, "json": CanonicalType.ANY,
    "enum": CanonicalType.UTF8, "set": CanonicalType.UTF8,
    "*": CanonicalType.ANY,
})

register_target_rules("mysql", {
    CanonicalType.INT8: "tinyint", CanonicalType.INT16: "smallint",
    CanonicalType.INT32: "int", CanonicalType.INT64: "bigint",
    CanonicalType.UINT8: "tinyint unsigned",
    CanonicalType.UINT16: "smallint unsigned",
    CanonicalType.UINT32: "int unsigned",
    CanonicalType.UINT64: "bigint unsigned",
    CanonicalType.FLOAT: "float", CanonicalType.DOUBLE: "double",
    CanonicalType.BOOLEAN: "tinyint(1)", CanonicalType.STRING: "longblob",
    CanonicalType.UTF8: "longtext", CanonicalType.DATE: "date",
    CanonicalType.DATETIME: "datetime", CanonicalType.TIMESTAMP: "datetime(6)",
    CanonicalType.INTERVAL: "bigint", CanonicalType.DECIMAL: "decimal(65,30)",
    CanonicalType.ANY: "json",
})


@register_endpoint
@dataclass
class MySQLSourceParams(EndpointParams):
    PROVIDER = "mysql"
    IS_SOURCE = True

    host: str = "localhost"
    port: int = 3306
    database: str = ""
    user: str = "root"
    password: str = ""
    batch_rows: int = 65_536


@register_endpoint
@dataclass
class MySQLTargetParams(EndpointParams):
    PROVIDER = "mysql"
    IS_TARGET = True

    host: str = "localhost"
    port: int = 3306
    database: str = ""
    user: str = "root"
    password: str = ""


def _conn(params) -> MySQLConnection:
    return MySQLConnection(
        host=params.host, port=params.port, database=params.database,
        user=params.user, password=params.password,
    ).connect()


def _sql_literal(v) -> str:
    """Escaped SQL literal (shared by cursor filters and the sink)."""
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, bytes):
        return "x'" + v.hex() + "'"
    s = str(v).replace("\\", "\\\\").replace("'", "''")
    return f"'{s}'"


def _coerce(cs: ColSchema, v: Optional[str]):
    if v is None:
        return None
    t = cs.data_type
    if t.is_integer:
        try:
            return int(v)
        except ValueError:
            return v
    if t.is_float:
        try:
            return float(v)
        except ValueError:
            return v
    if t == CanonicalType.BOOLEAN:
        return v not in ("0", "", "false")
    if t == CanonicalType.STRING:
        return v.encode("utf-8", "surrogateescape")
    return v


class MySQLStorage(Storage, PositionalStorage, IncrementalStorage,
                   SampleableStorage):
    def __init__(self, params: MySQLSourceParams):
        self.params = params
        self._c: Optional[MySQLConnection] = None

    @property
    def conn(self) -> MySQLConnection:
        if self._c is None:
            self._c = _conn(self.params)
        return self._c

    def close(self) -> None:
        if self._c is not None:
            self._c.close()
            self._c = None

    def ping(self) -> None:
        self.conn.ping()

    def table_list(self, include=None):
        rows = self.conn.query(
            "SELECT TABLE_NAME AS name, TABLE_ROWS AS eta "
            "FROM information_schema.TABLES "
            f"WHERE TABLE_SCHEMA = '{self.params.database}' "
            "AND TABLE_TYPE = 'BASE TABLE'"
        )
        out = {}
        for r in rows:
            tid = TableID(self.params.database, r["name"])
            if include and not any(tid.include_matches(p) for p in include):
                continue
            out[tid] = TableInfo(eta_rows=int(r["eta"] or 0))
        return out

    def table_schema(self, table: TableID) -> TableSchema:
        from transferia_tpu.typesystem.rules import map_source_type

        rows = self.conn.query(
            "SELECT COLUMN_NAME AS name, DATA_TYPE AS typ, "
            "COLUMN_TYPE AS full_typ, IS_NULLABLE AS nullable, "
            "COLUMN_KEY AS ckey "
            "FROM information_schema.COLUMNS "
            f"WHERE TABLE_SCHEMA = '{table.namespace}' "
            f"AND TABLE_NAME = '{table.name}' ORDER BY ORDINAL_POSITION"
        )
        cols = []
        for r in rows:
            typ = r["typ"].lower()
            if "unsigned" in (r["full_typ"] or "").lower():
                typ = f"{typ} unsigned"
            cols.append(ColSchema(
                name=r["name"],
                data_type=map_source_type("mysql", typ),
                primary_key=r["ckey"] == "PRI",
                required=r["nullable"] == "NO",
                original_type=f"mysql:{r['full_typ']}",
            ))
        return TableSchema(cols)

    def exact_table_rows_count(self, table: TableID) -> int:
        return int(self.conn.scalar(
            f"SELECT COUNT(*) FROM `{table.namespace}`.`{table.name}`"
        ) or 0)

    def position(self) -> dict:
        """Binlog/gtid position (MysqlGtidState parity).

        MySQL 8.4 removed SHOW MASTER STATUS in favor of SHOW BINARY LOG
        STATUS; try both, and never silently checkpoint an empty position.
        """
        last_err = None
        for stmt in ("SHOW MASTER STATUS", "SHOW BINARY LOG STATUS"):
            try:
                rows = self.conn.query(stmt)
            except MySQLError as e:
                last_err = e
                continue
            if rows:
                r = rows[0]
                return {
                    "binlog_file": r.get("File"),
                    "binlog_pos": r.get("Position"),
                    "gtid_set": r.get("Executed_Gtid_Set", ""),
                }
        logger.warning(
            "could not read binlog position (binary logging off, "
            "insufficient privileges, or unsupported server): %s", last_err,
        )
        return {}

    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        schema = self.table_schema(table.id)
        cols = ", ".join(f"`{c.name}`" for c in schema)
        conn = _conn(self.params)
        keys = schema.key_columns()
        ref = f"`{table.id.namespace}`.`{table.id.name}`"
        bs = self.params.batch_rows
        try:
            if len(keys) == 1:
                # keyset pagination: stable under concurrent writes and
                # O(N) server-side, unlike OFFSET scans
                key = keys[0].name
                last = None
                while True:
                    conds = []
                    if table.filter:
                        conds.append(f"({table.filter})")
                    if last is not None:
                        conds.append(f"`{key}` > {_sql_literal(last)}")
                    where = f" WHERE {' AND '.join(conds)}" if conds else ""
                    rows = conn.query(
                        f"SELECT {cols} FROM {ref}{where} "
                        f"ORDER BY `{key}` LIMIT {bs}"
                    )
                    if not rows:
                        return
                    self._push_rows(rows, schema, table.id, pusher)
                    last_raw = rows[-1].get(key)
                    last = _coerce(schema.find(key), last_raw)
                    if len(rows) < bs:
                        return
            else:
                # multi/no-PK fallback: OFFSET paging over a fixed ORDER BY
                # (full pk list) so the scan order is at least deterministic
                order = ", ".join(f"`{k.name}`" for k in keys) if keys \
                    else ""
                order_sql = f" ORDER BY {order}" if order else ""
                where = f" WHERE {table.filter}" if table.filter else ""
                offset = 0
                while True:
                    rows = conn.query(
                        f"SELECT {cols} FROM {ref}{where}{order_sql} "
                        f"LIMIT {bs} OFFSET {offset}"
                    )
                    if not rows:
                        return
                    self._push_rows(rows, schema, table.id, pusher)
                    if len(rows) < bs:
                        return
                    offset += bs
        finally:
            conn.close()

    @staticmethod
    def _push_rows(rows, schema, tid, pusher: Pusher) -> None:
        data = {
            c.name: [_coerce(c, r.get(c.name)) for r in rows]
            for c in schema
        }
        pusher(ColumnBatch.from_pydict(tid, schema, data))

    # -- checksum sampling (mysql/sampleable_storage.go) --------------------
    RANDOM_SAMPLE_LIMIT = 2000
    TOP_BOTTOM_LIMIT = 1000

    def table_size_in_bytes(self, table: TableID) -> int:
        v = self.conn.scalar(
            "SELECT DATA_LENGTH + INDEX_LENGTH "
            "FROM information_schema.TABLES "
            f"WHERE TABLE_SCHEMA = '{table.namespace}' "
            f"AND TABLE_NAME = '{table.name}'"
        )
        return int(v or 0)

    def _sample_query(self, tid: TableID, schema: TableSchema, sql: str,
                      pusher: Pusher) -> None:
        rows = self.conn.query(sql)
        if rows:
            self._push_rows(rows, schema, tid, pusher)

    def _sample_parts(self, tid: TableID):
        schema = self.table_schema(tid)
        cols = ", ".join(f"`{c.name}`" for c in schema)
        order = ", ".join(f"`{c.name}`" for c in schema.key_columns())
        ref = f"`{tid.namespace}`.`{tid.name}`"
        return schema, cols, order, ref

    def load_random_sample(self, table: TableDescription,
                           pusher: Pusher) -> None:
        schema, cols, order, ref = self._sample_parts(table.id)
        by = f" ORDER BY {order}" if order else ""
        self._sample_query(
            table.id, schema,
            f"SELECT {cols} FROM {ref} WHERE RAND() <= 0.05{by} "
            f"LIMIT {self.RANDOM_SAMPLE_LIMIT}",
            pusher,
        )

    def load_top_bottom_sample(self, table: TableDescription,
                               pusher: Pusher) -> None:
        schema, cols, order, ref = self._sample_parts(table.id)
        if not order:
            raise MySQLError(f"no primary key on {ref}; "
                             "cannot take top/bottom sample")
        desc = ", ".join(f"{c} DESC" for c in order.split(", "))
        n = self.TOP_BOTTOM_LIMIT
        self._sample_query(
            table.id, schema,
            f"(SELECT {cols} FROM {ref} ORDER BY {order} LIMIT {n}) "
            f"UNION ALL "
            f"(SELECT {cols} FROM {ref} ORDER BY {desc} LIMIT {n})",
            pusher,
        )

    def load_sample_by_set(self, table: TableDescription, key_set,
                           pusher: Pusher) -> None:
        schema, cols, _, ref = self._sample_parts(table.id)
        conds = [
            "(" + " AND ".join(
                f"`{name}` = {_sql_literal(val)}"
                for name, val in key.items()) + ")"
            for key in key_set
        ]
        where = " OR ".join(conds) if conds else "FALSE"
        self._sample_query(
            table.id, schema,
            f"SELECT {cols} FROM {ref} WHERE {where}", pusher)

    # -- IncrementalStorage -------------------------------------------------
    def get_increment_state(self, tables, state):
        out = []
        for t in tables:
            cursor = state.get(str(t.table), t.initial_state or None)
            if cursor in (None, ""):
                out.append(TableDescription(id=t.table))
            else:
                out.append(TableDescription(
                    id=t.table,
                    filter=f"`{t.cursor_field}` > {_sql_literal(cursor)}",
                ))
        return out

    def next_increment_state(self, tables):
        out = {}
        for t in tables:
            v = self.conn.scalar(
                f"SELECT MAX(`{t.cursor_field}`) FROM "
                f"`{t.table.namespace}`.`{t.table.name}`"
            )
            if v is not None:
                out[str(t.table)] = v
        return out


class MySQLSinker(Sinker):
    def __init__(self, params: MySQLTargetParams):
        self.params = params
        self._c: Optional[MySQLConnection] = None
        self._created: set[TableID] = set()

    @property
    def conn(self) -> MySQLConnection:
        if self._c is None:
            self._c = _conn(self.params)
        return self._c

    def close(self) -> None:
        if self._c is not None:
            self._c.close()
            self._c = None

    _literal = staticmethod(_sql_literal)

    def _table_ref(self, tid: TableID) -> str:
        ns = tid.namespace or self.params.database
        return f"`{ns}`.`{tid.name}`"

    def _ensure_table(self, tid: TableID, schema: TableSchema) -> None:
        if tid in self._created:
            return
        from transferia_tpu.typesystem.rules import map_target_type

        cols = []
        for c in schema:
            typ = map_target_type("mysql", c.data_type)
            # TEXT/BLOB key columns need a length-limited index type
            if c.primary_key and typ in ("longtext", "longblob"):
                typ = "varchar(255)" if typ == "longtext" \
                    else "varbinary(255)"
            nn = " NOT NULL" if (c.required or c.primary_key) else ""
            cols.append(f"`{c.name}` {typ}{nn}")
        keys = ", ".join(f"`{c.name}`" for c in schema.key_columns())
        pk = f", PRIMARY KEY ({keys})" if keys else ""
        self.conn.query(
            f"CREATE TABLE IF NOT EXISTS {self._table_ref(tid)} "
            f"({', '.join(cols)}{pk})"
        )
        self._created.add(tid)

    def push(self, batch: Batch) -> None:
        if not is_columnar(batch):
            rows = [it for it in batch if it.is_row_event()]
            if not rows:
                return
            batch = ColumnBatch.from_rows(rows)
        self._ensure_table(batch.table_id, batch.schema)
        if batch.kinds is None:
            self._insert(batch, upsert=batch.schema.has_primary_key())
        else:
            for it in batch.to_rows():
                self._apply_row(it)

    def _insert(self, batch: ColumnBatch, upsert: bool) -> None:
        names = list(batch.columns)
        cols = ", ".join(f"`{n}`" for n in names)
        data = batch.to_pydict()
        # multi-row VALUES in chunks to bound statement size
        chunk = 500
        for start in range(0, batch.n_rows, chunk):
            rows_sql = []
            for i in range(start, min(batch.n_rows, start + chunk)):
                rows_sql.append(
                    "(" + ", ".join(
                        self._literal(data[n][i]) for n in names
                    ) + ")"
                )
            sql = f"INSERT INTO {self._table_ref(batch.table_id)} " \
                  f"({cols}) VALUES {', '.join(rows_sql)}"
            if upsert:
                keys = {c.name for c in batch.schema.key_columns()}
                sets = ", ".join(
                    f"`{n}` = VALUES(`{n}`)" for n in names
                    if n not in keys
                )
                if sets:
                    sql += f" ON DUPLICATE KEY UPDATE {sets}"
            self.conn.query(sql)

    def _apply_row(self, it) -> None:
        ref = self._table_ref(it.table_id)
        if it.kind == Kind.INSERT:
            cols = ", ".join(f"`{n}`" for n in it.column_names)
            vals = ", ".join(self._literal(v) for v in it.column_values)
            self.conn.query(
                f"REPLACE INTO {ref} ({cols}) VALUES ({vals})"
            )
        elif it.kind == Kind.UPDATE:
            sets = ", ".join(
                f"`{n}` = {self._literal(v)}"
                for n, v in zip(it.column_names, it.column_values)
            )
            self.conn.query(
                f"UPDATE {ref} SET {sets} WHERE {self._key_where(it)}"
            )
        elif it.kind == Kind.DELETE:
            self.conn.query(
                f"DELETE FROM {ref} WHERE {self._key_where(it)}"
            )

    def _key_where(self, it) -> str:
        names = [c.name for c in it.table_schema.key_columns()]
        return " AND ".join(
            f"`{n}` = {self._literal(v)}"
            for n, v in zip(names, it.effective_key())
        )


@register_provider
class MySQLProvider(Provider):
    NAME = "mysql"

    def storage(self):
        if isinstance(self.transfer.src, MySQLSourceParams):
            return MySQLStorage(self.transfer.src)
        return None

    def destination_storage(self):
        dst = self.transfer.dst
        if isinstance(dst, MySQLTargetParams):
            return MySQLStorage(MySQLSourceParams(
                host=dst.host, port=dst.port, database=dst.database,
                user=dst.user, password=dst.password,
            ))
        return None

    def source(self):
        """Binlog ROW replication (canal.go)."""
        if isinstance(self.transfer.src, MySQLSourceParams):
            from transferia_tpu.providers.mysql.binlog import (
                MySQLBinlogSource,
            )

            return MySQLBinlogSource(
                self.transfer.src, self.transfer.id, self.coordinator
            )
        return None

    def sinker(self):
        if isinstance(self.transfer.dst, MySQLTargetParams):
            return MySQLSinker(self.transfer.dst)
        return None

    def cleanup(self, tables: list) -> None:
        params = self.transfer.dst
        conn = _conn(params)
        try:
            stmt = "DROP TABLE IF EXISTS" \
                if params.cleanup_policy == CleanupPolicy.DROP \
                else "TRUNCATE TABLE"
            for td in tables or []:
                tid = td.id if hasattr(td, "id") else td
                ns = tid.namespace or params.database
                try:
                    conn.query(f"{stmt} `{ns}`.`{tid.name}`")
                except MySQLError as e:
                    if e.errno == 1146:  # table doesn't exist
                        continue
                    raise
        finally:
            conn.close()

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        params = self.transfer.src if isinstance(
            self.transfer.src, MySQLSourceParams) else self.transfer.dst
        try:
            conn = _conn(params)
            conn.ping()
            conn.close()
            result.add("connect")
        except Exception as e:
            result.add("connect", e)
        return result
