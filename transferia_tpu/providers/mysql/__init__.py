"""MySQL provider.

Reference parity: pkg/providers/mysql/ — snapshot storage (storage.go,
sharded reads), schema discovery, typesystem rules; binlog replication
(canal.go) tracks gtid/binlog positions in the coordinator
(coordinator/transfer_state.go:17-25 MysqlGtidState).  The client speaks
the MySQL client/server protocol directly (handshake v10,
mysql_native_password + caching_sha2_password fast path, COM_QUERY text
resultsets).  Binlog ROW-event decoding is the remaining CDC gap — the
position plumbing (gtid state keys) is already in place for it.
"""

from transferia_tpu.providers.mysql.provider import (
    MySQLProvider,
    MySQLSourceParams,
    MySQLTargetParams,
)

__all__ = ["MySQLProvider", "MySQLSourceParams", "MySQLTargetParams"]
