"""MySQL binlog replication source (ROW format).

Reference parity: pkg/providers/mysql/canal.go — binlog tailing with
position/gtid checkpointing (coordinator MysqlGtidState parity keys).

Protocol: COM_BINLOG_DUMP (no replica registration — the server streams
to any authenticated connection); events arrive as OK-prefixed packets
(v4 framing: timestamp(4) type(1) server_id(4) event_size(4) log_pos(4)
flags(2) + body, plus a CRC32 trailer when binlog_checksum is on, which
is negotiated and stripped).  Decoded events:
FORMAT_DESCRIPTION, ROTATE, TABLE_MAP, WRITE/UPDATE/DELETE_ROWS v1/v2,
QUERY (DDL passthrough), XID.  Row images decode per the TABLE_MAP column
types; schemas come from the catalog (information_schema) since binlog
carries no column names.
"""

from __future__ import annotations

import logging
import struct
import threading
import time
from typing import Optional

from transferia_tpu.abstract.change_item import ChangeItem, OldKeys
from transferia_tpu.abstract.interfaces import AsyncSink, Source
from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.abstract.schema import TableID, TableSchema
from transferia_tpu.columnar.batch import ColumnBatch
from transferia_tpu.coordinator.interface import Coordinator
from transferia_tpu.providers.mysql.gtid import GtidSet
from transferia_tpu.providers.mysql.wire import MySQLConnection, MySQLError

logger = logging.getLogger(__name__)

# event types
EV_QUERY = 2
EV_ROTATE = 4
EV_FORMAT_DESCRIPTION = 15
EV_XID = 16
EV_TABLE_MAP = 19
EV_WRITE_ROWS_V1 = 23
EV_UPDATE_ROWS_V1 = 24
EV_DELETE_ROWS_V1 = 25
EV_WRITE_ROWS_V2 = 30
EV_UPDATE_ROWS_V2 = 31
EV_GTID = 33  # GTID_LOG_EVENT: flags(1) + sid(16) + gno(8 le)
EV_DELETE_ROWS_V2 = 32

COM_BINLOG_DUMP = 0x12

# column types (subset)
T_DECIMAL = 0
T_TINY = 1
T_SHORT = 2
T_LONG = 3
T_FLOAT = 4
T_DOUBLE = 5
T_NULL = 6
T_TIMESTAMP = 7
T_LONGLONG = 8
T_INT24 = 9
T_DATE = 10
T_TIME = 11
T_DATETIME = 12
T_YEAR = 13
T_VARCHAR = 15
T_BIT = 16
T_TIMESTAMP2 = 17
T_DATETIME2 = 18
T_TIME2 = 19
T_JSON = 245
T_NEWDECIMAL = 246
T_ENUM = 247
T_SET = 248
T_TINY_BLOB = 249
T_MEDIUM_BLOB = 250
T_LONG_BLOB = 251
T_BLOB = 252
T_VAR_STRING = 253
T_STRING = 254


class TableMap:
    __slots__ = ("schema", "table", "col_types", "col_meta", "null_bits")

    def __init__(self, schema: str, table: str, col_types: bytes,
                 col_meta: list[int]):
        self.schema = schema
        self.table = table
        self.col_types = col_types
        self.col_meta = col_meta


def _read_lenenc(data: bytes, pos: int) -> tuple[int, int]:
    first = data[pos]
    if first < 0xFB:
        return first, pos + 1
    if first == 0xFC:
        return struct.unpack_from("<H", data, pos + 1)[0], pos + 3
    if first == 0xFD:
        v = data[pos + 1] | (data[pos + 2] << 8) | (data[pos + 3] << 16)
        return v, pos + 4
    return struct.unpack_from("<Q", data, pos + 1)[0], pos + 9


def _parse_table_map(body: bytes) -> tuple[int, TableMap]:
    table_id = int.from_bytes(body[0:6], "little")
    pos = 8  # table id(6) + flags(2)
    slen = body[pos]
    schema = body[pos + 1:pos + 1 + slen].decode()
    pos += 1 + slen + 1
    tlen = body[pos]
    table = body[pos + 1:pos + 1 + tlen].decode()
    pos += 1 + tlen + 1
    n_cols, pos = _read_lenenc(body, pos)
    col_types = body[pos:pos + n_cols]
    pos += n_cols
    meta_len, pos = _read_lenenc(body, pos)
    meta_block = body[pos:pos + meta_len]
    pos += meta_len
    col_meta = _parse_col_meta(col_types, meta_block)
    return table_id, TableMap(schema, table, col_types, col_meta)


def _parse_col_meta(col_types: bytes, meta: bytes) -> list[int]:
    out = []
    mp = 0
    for t in col_types:
        if t in (T_FLOAT, T_DOUBLE, T_BLOB, T_TINY_BLOB, T_MEDIUM_BLOB,
                 T_LONG_BLOB, T_JSON, T_TIMESTAMP2, T_DATETIME2, T_TIME2):
            out.append(meta[mp])
            mp += 1
        elif t in (T_VARCHAR, T_VAR_STRING, T_BIT):
            out.append(struct.unpack_from("<H", meta, mp)[0])
            mp += 2
        elif t in (T_STRING, T_ENUM, T_SET, T_NEWDECIMAL, T_DECIMAL):
            out.append((meta[mp] << 8) | meta[mp + 1])
            mp += 2
        else:
            out.append(0)
    return out


def _decode_value(t: int, meta: int, data: bytes, pos: int):
    """One column value from a row image; returns (value, new_pos)."""
    if t == T_TINY:
        return struct.unpack_from("<b", data, pos)[0], pos + 1
    if t == T_SHORT:
        return struct.unpack_from("<h", data, pos)[0], pos + 2
    if t == T_INT24:
        v = int.from_bytes(data[pos:pos + 3], "little", signed=True)
        return v, pos + 3
    if t == T_LONG:
        return struct.unpack_from("<i", data, pos)[0], pos + 4
    if t == T_LONGLONG:
        return struct.unpack_from("<q", data, pos)[0], pos + 8
    if t == T_FLOAT:
        return struct.unpack_from("<f", data, pos)[0], pos + 4
    if t == T_DOUBLE:
        return struct.unpack_from("<d", data, pos)[0], pos + 8
    if t == T_YEAR:
        return 1900 + data[pos], pos + 1
    if t == T_DATE:
        # canonical DATE = int32 days since epoch
        import datetime as _dt

        v = int.from_bytes(data[pos:pos + 3], "little")
        year, month, day = v >> 9, (v >> 5) & 0x0F, v & 0x1F
        if year == 0 or month == 0 or day == 0:  # zero-date
            return None, pos + 3
        days = _dt.date(year, month, day).toordinal() \
            - _dt.date(1970, 1, 1).toordinal()
        return days, pos + 3
    if t == T_DATETIME2:
        # canonical TIMESTAMP = int64 microseconds since epoch
        import calendar

        raw = int.from_bytes(data[pos:pos + 5], "big")
        frac_bytes = (meta + 1) // 2
        micros = _read_fraction(data, pos + 5, frac_bytes)
        ym = (raw >> 22) & 0x1FFFF
        year, month = ym // 13, ym % 13
        day = (raw >> 17) & 0x1F
        hour = (raw >> 12) & 0x1F
        minute = (raw >> 6) & 0x3F
        second = raw & 0x3F
        if year == 0 or month == 0 or day == 0:
            return None, pos + 5 + frac_bytes
        secs = calendar.timegm(
            (year, month, day, hour, minute, second, 0, 0, 0)
        )
        return secs * 1_000_000 + micros, pos + 5 + frac_bytes
    if t == T_TIMESTAMP2:
        secs = int.from_bytes(data[pos:pos + 4], "big")
        frac_bytes = (meta + 1) // 2
        micros = _read_fraction(data, pos + 4, frac_bytes)
        return secs * 1_000_000 + micros, pos + 4 + frac_bytes
    if t == T_TIME2:
        raw = int.from_bytes(data[pos:pos + 3], "big")
        frac_bytes = (meta + 1) // 2
        sign = 1 if raw & 0x800000 else -1
        if sign < 0:
            raw = 0x1000000 - raw
        hours = (raw >> 12) & 0x3FF
        minutes = (raw >> 6) & 0x3F
        seconds = raw & 0x3F
        text = f"{'-' if sign < 0 else ''}" \
               f"{hours:02d}:{minutes:02d}:{seconds:02d}"
        return text, pos + 3 + frac_bytes
    if t in (T_VARCHAR, T_VAR_STRING):
        if meta > 255:
            ln = struct.unpack_from("<H", data, pos)[0]
            pos += 2
        else:
            ln = data[pos]
            pos += 1
        return data[pos:pos + ln].decode("utf-8", "replace"), pos + ln
    if t == T_STRING:
        real_type = meta >> 8
        if real_type in (T_ENUM, T_SET):
            ln = meta & 0xFF
            v = int.from_bytes(data[pos:pos + ln], "little")
            return v, pos + ln
        max_len = meta & 0x3FF
        if max_len > 255:
            ln = struct.unpack_from("<H", data, pos)[0]
            pos += 2
        else:
            ln = data[pos]
            pos += 1
        return data[pos:pos + ln].decode("utf-8", "replace"), pos + ln
    if t in (T_BLOB, T_TINY_BLOB, T_MEDIUM_BLOB, T_LONG_BLOB, T_JSON):
        ln = int.from_bytes(data[pos:pos + meta], "little")
        pos += meta
        raw = bytes(data[pos:pos + ln])
        return raw, pos + ln
    if t == T_NEWDECIMAL:
        precision, scale = meta >> 8, meta & 0xFF
        return _decode_decimal(data, pos, precision, scale)
    if t == T_BIT:
        nbits = ((meta >> 8) * 8) + (meta & 0xFF)
        nbytes = (nbits + 7) // 8
        return int.from_bytes(data[pos:pos + nbytes], "big"), pos + nbytes
    raise MySQLError(f"binlog: unsupported column type {t}")


def _read_fraction(data: bytes, pos: int, frac_bytes: int) -> int:
    """Big-endian fractional seconds -> microseconds."""
    if frac_bytes == 0:
        return 0
    frac = int.from_bytes(data[pos:pos + frac_bytes], "big")
    return frac * (10 ** (6 - 2 * frac_bytes))


_DIG2BYTES = [0, 1, 1, 2, 2, 3, 3, 4, 4, 4]


def _decode_decimal(data: bytes, pos: int, precision: int,
                    scale: int) -> tuple[str, int]:
    """MySQL packed decimal -> string."""
    intg = precision - scale
    intg0, frac0 = intg // 9, scale // 9
    intg0x, frac0x = intg - intg0 * 9, scale - frac0 * 9
    size = intg0 * 4 + _DIG2BYTES[intg0x] + frac0 * 4 + _DIG2BYTES[frac0x]
    buf = bytearray(data[pos:pos + size])
    negative = not (buf[0] & 0x80)
    buf[0] ^= 0x80
    if negative:
        for i in range(len(buf)):
            buf[i] = (~buf[i]) & 0xFF
    p = 0
    int_part = 0
    if intg0x:
        n = _DIG2BYTES[intg0x]
        int_part = int.from_bytes(buf[p:p + n], "big")
        p += n
    for _ in range(intg0):
        int_part = int_part * 10**9 + int.from_bytes(buf[p:p + 4], "big")
        p += 4
    frac_part = ""
    for _ in range(frac0):
        frac_part += f"{int.from_bytes(buf[p:p + 4], 'big'):09d}"
        p += 4
    if frac0x:
        n = _DIG2BYTES[frac0x]
        frac_part += \
            f"{int.from_bytes(buf[p:p + n], 'big'):0{frac0x}d}"
        p += n
    sign = "-" if negative else ""
    out = f"{sign}{int_part}.{frac_part}" if scale else f"{sign}{int_part}"
    return out, pos + size


def _decode_row_image(data: bytes, pos: int, tmap: TableMap,
                      present: list[bool]) -> tuple[list, int]:
    n_present = sum(present)
    null_bytes = (n_present + 7) // 8
    null_bits = data[pos:pos + null_bytes]
    pos += null_bytes
    values: list = []
    null_idx = 0
    for i, is_present in enumerate(present):
        if not is_present:
            values.append(None)
            continue
        is_null = (null_bits[null_idx // 8] >> (null_idx % 8)) & 1
        null_idx += 1
        if is_null:
            values.append(None)
            continue
        v, pos = _decode_value(tmap.col_types[i], tmap.col_meta[i],
                               data, pos)
        values.append(v)
    return values, pos


class BinlogReader:
    """Parses the binlog event stream into row events.

    table_filter(schema, table) gates which tables are decoded at all —
    events for foreign databases are skipped before row decoding, so an
    exotic column type in an unrelated table can never kill the stream.
    """

    def __init__(self, table_filter=None):
        self.table_maps: dict[int, TableMap] = {}
        self.binlog_file = ""
        self.table_filter = table_filter or (lambda s, t: True)

    def parse_event(self, body: bytes):
        """One event (after the OK byte).  Returns a list of tuples:
        ('row', schema, table, kind, values, old_values) |
        ('ddl', schema, query) | ('rotate', file, position) |
        ('pos', log_pos)."""
        ts, etype = struct.unpack_from("<IB", body, 0)
        log_pos = struct.unpack_from("<I", body, 13)[0]
        payload = body[19:]
        out = []
        if etype == EV_ROTATE:
            # rotate resets positions: pair the NEW file with ITS position.
            # binlog_file is NOT updated here — the consumer flushes pending
            # rows against the OLD file first, then applies the rotate
            new_pos = struct.unpack_from("<Q", payload, 0)[0]
            new_file = payload[8:].rstrip(b"\x00").decode()
            out.append(("rotate", new_file, new_pos))
            return out
        out.append(("pos", log_pos, ts))
        if etype == EV_GTID:
            import uuid as _uuid

            sid = str(_uuid.UUID(bytes=payload[1:17]))
            gno = struct.unpack_from("<Q", payload, 17)[0]
            out.append(("gtid", sid, gno))
        elif etype == EV_XID:
            out.append(("commit",))
        elif etype == EV_TABLE_MAP:
            tid, tmap = _parse_table_map(payload)
            self.table_maps[tid] = tmap
        elif etype in (EV_WRITE_ROWS_V1, EV_WRITE_ROWS_V2,
                       EV_UPDATE_ROWS_V1, EV_UPDATE_ROWS_V2,
                       EV_DELETE_ROWS_V1, EV_DELETE_ROWS_V2):
            out.extend(self._parse_rows(etype, payload))
        elif etype == EV_QUERY:
            slen = payload[8]
            # skip: thread(4) exec_time(4) schema_len(1) err(2) status_len(2)
            status_len = struct.unpack_from("<H", payload, 11)[0]
            pos = 13 + status_len
            schema = payload[pos:pos + slen].decode()
            query = payload[pos + slen + 1:].decode("utf-8", "replace")
            if query == "COMMIT":
                out.append(("commit",))
            elif query != "BEGIN":
                # DDL implicitly commits its transaction
                out.append(("ddl", schema, query))
                out.append(("commit",))
        return out

    def _parse_rows(self, etype: int, payload: bytes):
        table_id = int.from_bytes(payload[0:6], "little")
        pos = 8  # table id + flags
        if etype in (EV_WRITE_ROWS_V2, EV_UPDATE_ROWS_V2,
                     EV_DELETE_ROWS_V2):
            extra_len = struct.unpack_from("<H", payload, pos)[0]
            pos += extra_len  # includes the 2 length bytes
        n_cols, pos = _read_lenenc(payload, pos)
        bitmap_len = (n_cols + 7) // 8
        present1 = _bits(payload[pos:pos + bitmap_len], n_cols)
        pos += bitmap_len
        is_update = etype in (EV_UPDATE_ROWS_V1, EV_UPDATE_ROWS_V2)
        present2 = present1
        if is_update:
            present2 = _bits(payload[pos:pos + bitmap_len], n_cols)
            pos += bitmap_len
        tmap = self.table_maps.get(table_id)
        if tmap is None:
            logger.warning("binlog: rows event for unknown table id %d",
                           table_id)
            return []
        if not self.table_filter(tmap.schema, tmap.table):
            return []
        out = []
        while pos < len(payload):
            values, pos = _decode_row_image(payload, pos, tmap, present1)
            if is_update:
                new_values, pos = _decode_row_image(payload, pos, tmap,
                                                    present2)
                out.append(("row", tmap.schema, tmap.table, Kind.UPDATE,
                            new_values, values))
            elif etype in (EV_WRITE_ROWS_V1, EV_WRITE_ROWS_V2):
                out.append(("row", tmap.schema, tmap.table, Kind.INSERT,
                            values, None))
            else:
                out.append(("row", tmap.schema, tmap.table, Kind.DELETE,
                            None, values))
        return out


def _bits(data: bytes, n: int) -> list[bool]:
    return [(data[i // 8] >> (i % 8)) & 1 == 1 for i in range(n)]


class MySQLBinlogSource(Source):
    """CDC source: COM_BINLOG_DUMP stream -> ChangeItems with position
    checkpoints after confirmed pushes (canal.go at-least-once parity)."""

    STATE_KEY = "mysql_binlog"

    def __init__(self, params, transfer_id: str,
                 coordinator: Optional[Coordinator] = None,
                 server_id: int = 41789, batch_rows: int = 1024):
        self.params = params
        self.transfer_id = transfer_id
        self.cp = coordinator
        self.server_id = server_id
        self.batch_rows = batch_rows
        self._stop = threading.Event()
        self._schemas: dict[tuple[str, str], TableSchema] = {}
        self._gtid = GtidSet()
        self._gtid_valid = False  # True only when baselined/resumed

    def _schema_for(self, schema: str, table: str,
                    catalog: MySQLConnection) -> Optional[TableSchema]:
        key = (schema, table)
        if key not in self._schemas:
            from transferia_tpu.providers.mysql.provider import MySQLStorage

            storage = MySQLStorage(self.params)
            storage._c = catalog
            try:
                self._schemas[key] = storage.table_schema(
                    TableID(schema, table)
                )
            except MySQLError:
                return None
        return self._schemas[key]

    def run(self, sink: AsyncSink) -> None:
        conn = MySQLConnection(
            host=self.params.host, port=self.params.port,
            database="", user=self.params.user,
            password=self.params.password,
        ).connect()
        catalog = MySQLConnection(
            host=self.params.host, port=self.params.port,
            database=self.params.database, user=self.params.user,
            password=self.params.password,
        ).connect()
        try:
            # honor the server's checksum setting: MySQL >= 5.6 defaults to
            # CRC32 and appends 4 bytes per event that must be stripped
            conn.query(
                "SET @master_binlog_checksum = @@global.binlog_checksum"
            )
            checksum = str(conn.scalar(
                "SELECT @@global.binlog_checksum"
            ) or "NONE").upper()
            checksum_bytes = 4 if checksum == "CRC32" else 0
            file, pos, gtid_set = self._start_position(catalog)
            if gtid_set:
                # GTID resume survives source failover/renamed binlogs
                # (sync_binlog_position.go / MysqlGtidState parity)
                self._dump_gtid(conn, file, pos, gtid_set)
                self._gtid = gtid_set
            else:
                self._dump(conn, file, pos)
                # fresh start baselined self._gtid (+_gtid_valid) in
                # _start_position; a legacy file+pos state leaves
                # _gtid_valid False so checkpoints stay file+pos-only —
                # a partial executed set would make a later GTID resume
                # replay the whole retained history
            # GTID lifecycle: a gtid becomes EXECUTED only when its
            # transaction completes (XID/COMMIT/next GTID) — merging it
            # at first sight would let a mid-transaction flush checkpoint
            # it and a crash-restart skip the transaction's pushed tail
            open_gtid: list = [None]
            pending_gtids: list[tuple[str, int]] = []

            def table_filter(schema: str, table: str) -> bool:
                return (not self.params.database
                        or schema == self.params.database)

            reader = BinlogReader(table_filter)
            reader.binlog_file = file
            items: list[ChangeItem] = []
            futures: list = []
            last_pos = pos
            pending_pos = pos
            last_flush = time.monotonic()

            def flush():
                nonlocal items, last_pos
                for run in _runs(items):
                    if run[0].is_row_event() and run[0].table_schema:
                        futures.append(
                            sink.async_push(ColumnBatch.from_rows(run))
                        )
                    else:
                        futures.append(sink.async_push(run))
                items = []
                for f in futures:
                    f.result()
                futures.clear()
                # completed-transaction gtids merge into the executed set
                # only after the pushes above resolved (at-least-once)
                for sid, gno in pending_gtids:
                    self._gtid.add(sid, gno)
                dirty = bool(pending_gtids) or pending_pos != last_pos
                pending_gtids.clear()
                if dirty and self.cp is not None:
                    state = {"file": reader.binlog_file,
                             "pos": pending_pos}
                    if self._gtid_valid:
                        state["gtid_set"] = str(self._gtid)
                    self.cp.set_transfer_state(self.transfer_id, {
                        self.STATE_KEY: state,
                    })
                last_pos = pending_pos

            import select

            while not self._stop.is_set():
                # probe with select; only read when a packet is pending so
                # a short timeout can never abort mid-frame and desync.
                # BufferedSock may hold complete packets already pulled
                # off the wire — drain those before consulting the kernel
                # (select on the raw fd cannot see them)
                if not getattr(conn.sock, "pending", lambda: 0)():
                    readable, _, _ = select.select([conn.sock], [], [],
                                                   0.3)
                    if not readable:
                        if time.monotonic() - last_flush > 0.5:
                            flush()
                            last_flush = time.monotonic()
                        continue
                pkt = conn._read_packet()
                if pkt[:1] == b"\xff":
                    raise conn._err(pkt)
                if pkt[:1] == b"\xfe" and len(pkt) < 9:
                    break  # EOF
                event = pkt[1:len(pkt) - checksum_bytes] \
                    if checksum_bytes else pkt[1:]
                for ev in reader.parse_event(event):
                    if ev[0] == "pos":
                        pending_pos = max(pending_pos, ev[1])
                    elif ev[0] == "rotate":
                        # flush pending rows against the OLD file, THEN
                        # switch files — a crash between the two writes
                        # must never leave (new file, old position)
                        flush()
                        reader.binlog_file = ev[1]
                        pending_pos = ev[2]
                        last_pos = ev[2]
                        if self.cp is not None:
                            state = {"file": ev[1], "pos": ev[2]}
                            if self._gtid_valid:
                                state["gtid_set"] = str(self._gtid)
                            self.cp.set_transfer_state(self.transfer_id, {
                                self.STATE_KEY: state,
                            })
                    elif ev[0] == "gtid":
                        # a new GTID implies the previous txn completed
                        if open_gtid[0] is not None:
                            pending_gtids.append(open_gtid[0])
                        open_gtid[0] = (ev[1], ev[2])
                    elif ev[0] == "commit":
                        if open_gtid[0] is not None:
                            pending_gtids.append(open_gtid[0])
                            open_gtid[0] = None
                    elif ev[0] == "row":
                        _, schema, table, kind, values, old = ev
                        item = self._to_item(schema, table, kind, values,
                                             old, catalog, pending_pos)
                        if item is not None:
                            items.append(item)
                    elif ev[0] == "ddl":
                        items.append(ChangeItem(
                            kind=Kind.DDL, schema=ev[1],
                            column_names=("query",),
                            column_values=(ev[2],),
                        ))
                if len(items) >= self.batch_rows:
                    flush()
                    last_flush = time.monotonic()
            flush()
        finally:
            conn.close()
            catalog.close()

    def _start_position(self, catalog: MySQLConnection
                        ) -> tuple[str, int, Optional["GtidSet"]]:
        if self.cp is not None:
            state = self.cp.get_transfer_state(self.transfer_id).get(
                self.STATE_KEY
            )
            if state:
                gtid = GtidSet.parse(state.get("gtid_set", ""))
                if gtid:
                    self._gtid_valid = True
                    return state["file"], int(state["pos"]), gtid
                # legacy file+pos state: no executed-set baseline exists;
                # keep checkpointing file+pos only (_gtid_valid stays
                # False) rather than fabricating a partial set
                return state["file"], int(state["pos"]), None
        from transferia_tpu.providers.mysql.provider import MySQLStorage

        storage = MySQLStorage(self.params)
        storage._c = catalog
        pos = storage.position()
        if not pos.get("binlog_file"):
            raise MySQLError(
                "cannot determine binlog position; is binary logging on?"
            )
        # fresh start: baseline the executed set so future checkpoints
        # carry gtids (file+pos dump is still used for the first attach —
        # the server streams everything after that position)
        self._gtid = GtidSet.parse(pos.get("gtid_set", "") or "")
        self._gtid_valid = True
        return pos["binlog_file"], int(pos["binlog_pos"]), None

    def _dump(self, conn: MySQLConnection, file: str, pos: int) -> None:
        conn._seq = 0
        body = struct.pack("<BIHI", 0x12, max(4, pos), 0, self.server_id) \
            + file.encode()
        conn._send_packet(body)

    def _dump_gtid(self, conn: MySQLConnection, file: str, pos: int,
                   gtid_set: "GtidSet") -> None:
        """COM_BINLOG_DUMP_GTID (0x1e): resume from an executed set.

        flags carries BINLOG_THROUGH_GTID (0x04) — without it a real
        server ignores the GTID data and resumes by file+pos."""
        conn._seq = 0
        data = gtid_set.encode()
        body = (struct.pack("<BHI", 0x1E, 0x04, self.server_id)
                + struct.pack("<I", len(file)) + file.encode()
                + struct.pack("<Q", max(4, pos))
                + struct.pack("<I", len(data)) + data)
        conn._send_packet(body)

    def _to_item(self, schema: str, table: str, kind: Kind,
                 values, old, catalog, log_pos) -> Optional[ChangeItem]:
        tschema = self._schema_for(schema, table, catalog)
        if tschema is None:
            return None
        names = tuple(tschema.names())

        from transferia_tpu.abstract.schema import CanonicalType

        def normalize(vals):
            if vals is None:
                return None
            out = []
            for cs, v in zip(tschema, vals):
                # binlog frames TEXT/JSON values as blobs (bytes); decode
                # for every canonical type except raw STRING, which keeps
                # bytes by contract
                if isinstance(v, bytes) and \
                        cs.data_type != CanonicalType.STRING:
                    v = v.decode("utf-8", "replace")
                out.append(v)
            return tuple(out)

        new_vals = normalize(values)
        old_vals = normalize(old)
        old_keys = OldKeys()
        if old_vals is not None:
            key_names = tuple(
                c.name for c in tschema.key_columns()
            ) or names
            by_name = dict(zip(names, old_vals))
            old_keys = OldKeys(
                key_names, tuple(by_name.get(k) for k in key_names)
            )
        return ChangeItem(
            kind=kind, schema=schema, table=table,
            column_names=names if new_vals is not None else (),
            column_values=new_vals if new_vals is not None else (),
            table_schema=tschema,
            old_keys=old_keys,
            lsn=log_pos,
            commit_time_ns=time.time_ns(),
        )

    def stop(self) -> None:
        self._stop.set()


def _runs(items: list[ChangeItem]) -> list[list[ChangeItem]]:
    out: list[list[ChangeItem]] = []
    key = None
    for it in items:
        k = (it.table_id, id(it.table_schema), it.is_row_event())
        if not out or k != key:
            out.append([])
            key = k
        out[-1].append(it)
    return out
