"""Remaining inventory providers: BigQuery, Delta Lake, log-shipping
sinks (Coralogix/Datadog).  Airbyte lives in providers/airbyte.py.

Reference parity: pkg/providers/{bigquery,delta,coralogix,datadog}.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.abstract.interfaces import (
    Batch,
    Pusher,
    Sinker,
    Storage,
    TableInfo,
    is_columnar,
)
from transferia_tpu.abstract.schema import TableID, TableSchema
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch, arrow_to_table_schema
from transferia_tpu.events.pipeline import (
    DataObjectPart,
    EventSourceProgress,
    ProgressableEventSource,
    SnapshotProvider,
)
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.registry import Provider, register_provider

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# BigQuery sink (pkg/providers/bigquery — Sinker role only, like the ref)
# ---------------------------------------------------------------------------

@register_endpoint
@dataclass
class BigQueryTargetParams(EndpointParams):
    PROVIDER = "bigquery"
    IS_TARGET = True

    project: str = ""
    dataset: str = ""
    location: str = "US"


class BigQuerySinker(Sinker):
    """Arrow-native load jobs via the google-cloud-bigquery client (baked
    into the image); columnar batches upload as parquet without re-rowing."""

    def __init__(self, params: BigQueryTargetParams):
        try:
            from google.cloud import bigquery
        except ImportError as e:  # pragma: no cover
            raise CategorizedError(
                CategorizedError.TARGET,
                "google-cloud-bigquery is not installed",
            ) from e
        self.params = params
        self.client = bigquery.Client(project=params.project or None)

    def push(self, batch: Batch) -> None:
        import io

        import pyarrow as pa
        import pyarrow.parquet as pq
        from google.cloud import bigquery

        if not is_columnar(batch):
            rows = [it for it in batch if it.is_row_event()]
            if not rows:
                return
            batch = ColumnBatch.from_rows(rows)
        table_ref = f"{self.params.project}.{self.params.dataset}." \
                    f"{batch.table_id.name}"
        buf = io.BytesIO()
        pq.write_table(pa.Table.from_batches([batch.to_arrow()]), buf)
        buf.seek(0)
        job = self.client.load_table_from_file(
            buf, table_ref,
            job_config=bigquery.LoadJobConfig(
                source_format=bigquery.SourceFormat.PARQUET,
                write_disposition="WRITE_APPEND",
            ),
            location=self.params.location,
        )
        job.result()


@register_provider
class BigQueryProvider(Provider):
    NAME = "bigquery"

    def sinker(self):
        if isinstance(self.transfer.dst, BigQueryTargetParams):
            return BigQuerySinker(self.transfer.dst)
        return None


# ---------------------------------------------------------------------------
# Delta Lake source (pkg/providers/delta — abstract2 snapshot source)
# ---------------------------------------------------------------------------

@register_endpoint
@dataclass
class DeltaSourceParams(EndpointParams):
    PROVIDER = "delta"
    IS_SOURCE = True

    path: str = ""            # table root containing _delta_log/
    table: str = "delta"
    namespace: str = ""
    batch_rows: int = 65_536
    storage_options: dict = field(default_factory=dict)
    anon: bool = True
    endpoint_url: str = ""


class DeltaStorage(Storage):
    """Reads the Delta transaction log to find live parquet files, then
    streams them columnar (the log is JSON actions: add/remove/metaData)."""

    def __init__(self, params: DeltaSourceParams):
        self.params = params
        self.table = TableID(params.namespace, params.table)
        self._files: Optional[list[str]] = None
        self._schema: Optional[TableSchema] = None
        self._file_rows: dict[str, int] = {}   # parquet footer cache

    def file_row_count(self, path: str) -> int:
        """num_rows from the parquet footer, read at most once per file
        (table_list and a2 data_objects both need it)."""
        if path not in self._file_rows:
            import pyarrow.parquet as pq

            fs, _ = self._fs()
            with fs.open(path, "rb") as fh:
                self._file_rows[path] = pq.ParquetFile(fh).metadata.num_rows
        return self._file_rows[path]

    def _fs(self):
        from transferia_tpu.providers.s3 import _fs_for

        return _fs_for(self.params.path, self.params)

    def _resolve(self) -> list[str]:
        if self._files is not None:
            return self._files
        fs, root = self._fs()
        log_dir = f"{root.rstrip('/')}/_delta_log"
        if not fs.exists(log_dir):
            raise FileNotFoundError(
                f"delta source: no _delta_log under {self.params.path!r}"
            )
        versions = sorted(
            p for p in fs.ls(log_dir)
            if p.endswith(".json")
        )
        live: dict[str, bool] = {}
        for v in versions:
            with fs.open(v, "rb") as fh:
                for line in fh:
                    if not line.strip():
                        continue
                    action = json.loads(line)
                    if "add" in action:
                        live[action["add"]["path"]] = True
                    elif "remove" in action:
                        live.pop(action["remove"]["path"], None)
        self._files = [
            f"{root.rstrip('/')}/{p}" for p, ok in live.items() if ok
        ]
        if not self._files:
            raise FileNotFoundError(
                f"delta table at {self.params.path!r} has no live files"
            )
        return self._files

    def table_schema(self, table: TableID) -> TableSchema:
        if self._schema is None:
            import pyarrow.parquet as pq

            fs, _ = self._fs()
            with fs.open(self._resolve()[0], "rb") as fh:
                self._schema = arrow_to_table_schema(pq.read_schema(fh))
        return self._schema

    def table_list(self, include=None):
        if include and not any(
                self.table.include_matches(p) for p in include):
            return {}
        eta = sum(self.file_row_count(f) for f in self._resolve())
        return {self.table: TableInfo(
            eta_rows=eta, schema=self.table_schema(self.table)
        )}

    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        import pyarrow.parquet as pq

        fs, _ = self._fs()
        schema = self.table_schema(table.id)
        for f in self._resolve():
            with fs.open(f, "rb") as fh:
                pf = pq.ParquetFile(fh)
                for rb in pf.iter_batches(
                        batch_size=self.params.batch_rows):
                    if rb.num_rows:
                        batch = ColumnBatch.from_arrow(rb, table.id, schema)
                        batch.read_bytes = rb.nbytes
                        pusher(batch)


class DeltaSnapshotProvider(SnapshotProvider):
    """Event-model-v2 snapshot provider for Delta tables (the reference
    ships delta as an abstract2 provider: pkg/providers/delta +
    abstract2/transfer.go:212 SnapshotProvider).

    Data objects: the table; parts: one per live parquet file from the
    transaction log, so part-parallel loads never split a file."""

    def __init__(self, params: DeltaSourceParams):
        self.params = params
        self.storage = DeltaStorage(params)

    def init(self) -> None:
        self.storage._resolve()

    def ping(self) -> None:
        self.storage._resolve()

    def close(self) -> None:
        pass

    def begin_snapshot(self) -> None:
        # the file list is the snapshot: resolve once, reads stay pinned
        # to it even if the log advances mid-load
        self.storage._resolve()

    def end_snapshot(self) -> None:
        self.storage._files = None

    def data_objects(self, include=None):
        tid = self.storage.table
        if include and not any(tid.include_matches(p) for p in include):
            return {}
        parts = [
            DataObjectPart(table=tid, part_key=f,
                           eta_rows=self.storage.file_row_count(f))
            for f in self.storage._resolve()
        ]
        return {tid: parts}

    def table_schema(self, part) -> TableSchema:
        return self.storage.table_schema(part.table)

    def create_snapshot_source(self, part):
        provider = self

        class _FileSource(ProgressableEventSource):
            def __init__(self):
                self._progress = EventSourceProgress(total=part.eta_rows)
                self._running = False

            def start(self, target) -> None:
                import pyarrow.parquet as pq

                from transferia_tpu.abstract.interfaces import resolve_all
                from transferia_tpu.events.model import InsertBatchEvent

                self._running = True
                futures = []
                try:
                    fs, _ = provider.storage._fs()
                    schema = provider.storage.table_schema(part.table)
                    with fs.open(part.part_key, "rb") as fh:
                        pf = pq.ParquetFile(fh)
                        for rb in pf.iter_batches(
                                batch_size=provider.params.batch_rows):
                            if not rb.num_rows:
                                continue
                            batch = ColumnBatch.from_arrow(
                                rb, part.table, schema)
                            batch.read_bytes = rb.nbytes
                            futures.append(target.async_push(
                                [InsertBatchEvent(batch)]))
                            self._progress.current += rb.num_rows
                    resolve_all(futures)
                    self._progress.done = True
                finally:
                    self._running = False

            def running(self) -> bool:
                return self._running

            def progress(self):
                return self._progress

        return _FileSource()


@register_provider
class DeltaProvider(Provider):
    NAME = "delta"

    def storage(self):
        if isinstance(self.transfer.src, DeltaSourceParams):
            return DeltaStorage(self.transfer.src)
        return None

    def snapshot_provider(self):
        if isinstance(self.transfer.src, DeltaSourceParams):
            return DeltaSnapshotProvider(self.transfer.src)
        return None


# ---------------------------------------------------------------------------
# Log-shipping sinks (pkg/providers/coralogix, datadog)
# ---------------------------------------------------------------------------

def _http_post_json(host: str, path: str, body: object,
                    headers: dict, secure: bool = True,
                    timeout: float = 60.0) -> None:
    import http.client

    cls = http.client.HTTPSConnection if secure \
        else http.client.HTTPConnection
    conn = cls(host, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(body, default=str),
                     headers={"Content-Type": "application/json",
                              **headers})
        resp = conn.getresponse()
        data = resp.read()
        if resp.status >= 300:
            raise CategorizedError(
                CategorizedError.TARGET,
                f"log sink HTTP {resp.status}: {data[:200]!r}",
            )
    finally:
        conn.close()


@register_endpoint
@dataclass
class CoralogixTargetParams(EndpointParams):
    PROVIDER = "coralogix"
    IS_TARGET = True

    domain: str = "coralogix.com"
    private_key: str = ""
    application: str = "transferia"
    subsystem: str = "transfer"
    secure: bool = True


class CoralogixSinker(Sinker):
    def __init__(self, params: CoralogixTargetParams):
        self.params = params

    def push(self, batch: Batch) -> None:
        rows = batch.to_rows() if is_columnar(batch) else [
            it for it in batch if it.is_row_event()
        ]
        if not rows:
            return
        entries = [
            {"severity": 3,
             "text": json.dumps(it.as_dict(), default=str)}
            for it in rows
        ]
        _http_post_json(
            f"ingress.{self.params.domain}", "/logs/v1/bulk",
            {
                "applicationName": self.params.application,
                "subsystemName": self.params.subsystem,
                "logEntries": entries,
            },
            {"Authorization": f"Bearer {self.params.private_key}"},
            secure=self.params.secure,
        )


@register_endpoint
@dataclass
class DatadogTargetParams(EndpointParams):
    PROVIDER = "datadog"
    IS_TARGET = True

    site: str = "datadoghq.com"
    api_key: str = ""
    service: str = "transferia"
    source: str = "transfer"
    secure: bool = True


class DatadogSinker(Sinker):
    def __init__(self, params: DatadogTargetParams):
        self.params = params

    def push(self, batch: Batch) -> None:
        rows = batch.to_rows() if is_columnar(batch) else [
            it for it in batch if it.is_row_event()
        ]
        if not rows:
            return
        entries = [
            {
                "ddsource": self.params.source,
                "service": self.params.service,
                "message": json.dumps(it.as_dict(), default=str),
            }
            for it in rows
        ]
        _http_post_json(
            f"http-intake.logs.{self.params.site}", "/api/v2/logs",
            entries, {"DD-API-KEY": self.params.api_key},
            secure=self.params.secure,
        )


@register_provider
class CoralogixProvider(Provider):
    NAME = "coralogix"

    def sinker(self):
        if isinstance(self.transfer.dst, CoralogixTargetParams):
            return CoralogixSinker(self.transfer.dst)
        return None


@register_provider
class DatadogProvider(Provider):
    NAME = "datadog"

    def sinker(self):
        if isinstance(self.transfer.dst, DatadogTargetParams):
            return DatadogSinker(self.transfer.dst)
        return None


# Airbyte moved to providers/airbyte.py (real container runner)
