"""YTsaurus provider: snapshot source + static-table sink.

Reference parity: /root/reference/pkg/providers/yt/ — cypress listing
(cypress.go), range-sharded static-table reads (storage/), static-table
sink with schema creation and append writes
(model_ytsaurus_static_destination.go, sink/static_sink*).  The
reference rides the Go SDK (go.ytsaurus.tech/yt/go); this implementation
speaks the public HTTP proxy API directly (providers/yt/client.py) and
keeps the columnar batch as the internal currency — read_table row
batches pivot straight into ColumnBatch, never per-row ChangeItems.

Table identity: a cypress table ``//home/dir/name`` maps to
TableID(namespace="//home/dir", name="name"); the sink writes to
``<dir>/<name>`` under its configured target directory.

Binary values: the YT JSON wire format carries binary strings as
latin-1-escaped text; STRING columns encode/decode with latin-1 on the
boundary so arbitrary bytes round-trip.

Real-service behaviors intentionally NOT covered (the fake proxy
mirrors what is implemented, so e2e cannot prove these): copy/merge
operation scheduling (reference copy/ + mergejob/ run map-reduce
operations; here sinks write directly), lfstaging, type_v3 composite
columns (decimal ships as utf8), tablet-transaction atomicity semantics
beyond per-request ordering, and replicated/chaos dyntables.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.abstract.interfaces import (
    Batch,
    Pusher,
    ShardingStorage,
    Sinker,
    Storage,
    TableInfo,
    is_columnar,
)
from transferia_tpu.abstract.schema import (
    CanonicalType,
    ColSchema,
    TableID,
    TableSchema,
)
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch
from transferia_tpu.models import CleanupPolicy
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)
from transferia_tpu.providers.yt.client import YTClient, YTError
from transferia_tpu.typesystem.rules import (
    register_source_rules,
    register_target_rules,
)

logger = logging.getLogger(__name__)

# the canonical lattice IS the YT schema type set (SURVEY §2.1: typesystem
# keys on YT schema.Type) — the maps are near-identity
register_source_rules("yt", {
    "int8": CanonicalType.INT8, "int16": CanonicalType.INT16,
    "int32": CanonicalType.INT32, "int64": CanonicalType.INT64,
    "uint8": CanonicalType.UINT8, "uint16": CanonicalType.UINT16,
    "uint32": CanonicalType.UINT32, "uint64": CanonicalType.UINT64,
    "float": CanonicalType.FLOAT, "double": CanonicalType.DOUBLE,
    "boolean": CanonicalType.BOOLEAN, "bool": CanonicalType.BOOLEAN,
    "string": CanonicalType.STRING, "utf8": CanonicalType.UTF8,
    "date": CanonicalType.DATE, "datetime": CanonicalType.DATETIME,
    "timestamp": CanonicalType.TIMESTAMP,
    "interval": CanonicalType.INTERVAL,
    "any": CanonicalType.ANY, "json": CanonicalType.ANY,
    "*": CanonicalType.ANY,
})

register_target_rules("yt", {
    CanonicalType.INT8: "int8", CanonicalType.INT16: "int16",
    CanonicalType.INT32: "int32", CanonicalType.INT64: "int64",
    CanonicalType.UINT8: "uint8", CanonicalType.UINT16: "uint16",
    CanonicalType.UINT32: "uint32", CanonicalType.UINT64: "uint64",
    CanonicalType.FLOAT: "float", CanonicalType.DOUBLE: "double",
    CanonicalType.BOOLEAN: "boolean",
    CanonicalType.STRING: "string", CanonicalType.UTF8: "utf8",
    CanonicalType.DATE: "date", CanonicalType.DATETIME: "datetime",
    CanonicalType.TIMESTAMP: "timestamp",
    CanonicalType.INTERVAL: "interval",
    # parametrized decimal needs type_v3; utf8 preserves exactness
    CanonicalType.DECIMAL: "utf8",
    CanonicalType.ANY: "any",
})


@register_endpoint
@dataclass
class YTSourceParams(EndpointParams):
    PROVIDER = "yt"
    IS_SOURCE = True

    proxy: str = "localhost:80"
    paths: list[str] = field(default_factory=list)  # tables or map_nodes
    token: str = ""
    secure: bool = False
    batch_rows: int = 65_536
    desired_part_rows: int = 1_000_000  # range-shard granularity


@register_endpoint
@dataclass
class YTStaticTargetParams(EndpointParams):
    PROVIDER = "yt"
    IS_TARGET = True

    proxy: str = "localhost:80"
    dir: str = "//home/transfer"  # target cypress directory
    token: str = ""
    secure: bool = False
    cleanup_policy: CleanupPolicy = CleanupPolicy.DROP
    optimize_for: str = "scan"    # scan (columnar chunks) | lookup


@register_endpoint
@dataclass
class YTDynamicTargetParams(EndpointParams):
    """Dynamic-table destination (reference:
    pkg/providers/yt/model_ytsaurus_dynamic_destination.go + sink/):
    sorted dyntables take CDC upserts/deletes via the tablet write API;
    ordered dyntables append.  Tables are created dynamic, mounted, and
    writes wait for the mounted tablet state."""

    PROVIDER = "yt_dyn"
    IS_TARGET = True

    proxy: str = "localhost:80"
    dir: str = "//home/transfer"
    token: str = ""
    secure: bool = False
    cleanup_policy: CleanupPolicy = CleanupPolicy.DROP
    ordered: bool = False        # True: ordered dyntable (append-only)
    tablet_count: int = 0        # 0 = cluster default
    atomicity: str = "full"      # full | none (per-tablet atomic only)
    # per-request row cap; requests additionally split at tablet
    # boundaries (pivot keys) so each write lands in one tablet
    batch_rows: int = 20_000
    mount_timeout: float = 60.0


def _split_path(path: str) -> TableID:
    parent, _, name = path.rpartition("/")
    return TableID(parent, name)


def _join_path(dir_path: str, table: TableID) -> str:
    return f"{dir_path.rstrip('/')}/{table.name}"


def _schema_from_yt(attr: list[dict]) -> TableSchema:
    from transferia_tpu.typesystem.rules import map_source_type

    cols = []
    for c in attr:
        cols.append(ColSchema(
            c["name"],
            map_source_type("yt", c.get("type", "any")),
            primary_key=bool(c.get("sort_order")),
            required=bool(c.get("required")),
            original_type=f"yt:{c.get('type', 'any')}",
        ))
    return TableSchema(cols)


def _schema_to_yt(schema: TableSchema) -> list[dict]:
    from transferia_tpu.typesystem.rules import map_target_type

    out = []
    for c in schema.columns:
        entry = {"name": c.name,
                 "type": map_target_type("yt", c.data_type)}
        if c.primary_key:
            entry["sort_order"] = "ascending"
        out.append(entry)
    # YT requires key columns to be a prefix of the schema
    out.sort(key=lambda e: 0 if "sort_order" in e else 1)
    return out


def _decode_rows(rows: list[dict], schema: TableSchema) -> dict:
    """Column-pivot read_table rows, restoring latin-1 binary strings."""
    data: dict[str, list] = {c.name: [] for c in schema.columns}
    str_cols = {c.name for c in schema.columns
                if c.data_type == CanonicalType.STRING}
    for r in rows:
        for c in schema.columns:
            v = r.get(c.name)
            if v is not None and c.name in str_cols \
                    and isinstance(v, str):
                v = v.encode("latin-1", "replace")
            data[c.name].append(v)
    return data


def _encode_value(v, is_binary: bool):
    if isinstance(v, bytes):
        return v.decode("latin-1") if is_binary \
            else v.decode("utf-8", "replace")
    return v


class YTStorage(Storage, ShardingStorage):
    """Snapshot reads over the HTTP proxy with row-range sharding."""

    def __init__(self, params: YTSourceParams):
        self.params = params
        self.client = YTClient(params.proxy, token=params.token,
                               secure=params.secure)
        self._schemas: dict[TableID, TableSchema] = {}

    # -- discovery ----------------------------------------------------------
    def _table_paths(self) -> list[str]:
        out = []
        for p in self.params.paths:
            node_type = self.client.get(f"{p}/@type", default=None)
            if node_type == "table":
                out.append(p)
            elif node_type == "map_node":
                for child in sorted(self.client.list(p)):
                    cp = f"{p}/{child}"
                    if self.client.get(f"{cp}/@type",
                                       default=None) == "table":
                        out.append(cp)
            elif node_type is None:
                raise YTError(f"path {p!r} does not exist")
        return out

    def table_list(self, include=None):
        tables = {}
        for path in self._table_paths():
            tid = _split_path(path)
            if include and not any(tid.include_matches(p)
                                   for p in include):
                continue
            rows = int(self.client.get(f"{path}/@row_count", default=0))
            tables[tid] = TableInfo(eta_rows=rows,
                                    schema=self.table_schema(tid))
        return tables

    def table_schema(self, table: TableID) -> TableSchema:
        schema = self._schemas.get(table)
        if schema is None:
            attr = self.client.get(
                f"{table.namespace}/{table.name}/@schema")
            if isinstance(attr, dict):  # {"$attributes":…, "$value":[…]}
                attr = attr.get("$value", [])
            schema = _schema_from_yt(attr)
            self._schemas[table] = schema
        return schema

    def exact_table_rows_count(self, table: TableID) -> int:
        return int(self.client.get(
            f"{table.namespace}/{table.name}/@row_count", default=0))

    def table_exists(self, table: TableID) -> bool:
        return self.client.exists(f"{table.namespace}/{table.name}")

    # -- sharding -----------------------------------------------------------
    def shard_table(self, table: TableDescription
                    ) -> list[TableDescription]:
        total = self.exact_table_rows_count(table.id)
        step = max(1, self.params.desired_part_rows)
        if total <= step:
            return [table]
        parts = []
        for lo in range(0, total, step):
            hi = min(lo + step, total)
            parts.append(TableDescription(
                id=table.id, filter=f"rows:{lo}:{hi}",
                eta_rows=hi - lo,
            ))
        return parts

    # -- load ---------------------------------------------------------------
    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        schema = self.table_schema(table.id)
        path = f"{table.id.namespace}/{table.id.name}"
        if table.filter.startswith("rows:"):
            _, lo, hi = table.filter.split(":")
            path = f"{path}[#{lo}:#{hi}]"
        for rows in self.client.read_table(
                path, batch_rows=self.params.batch_rows):
            batch = ColumnBatch.from_pydict(
                table.id, schema, _decode_rows(rows, schema))
            pusher(batch)

    def ping(self) -> None:
        self.client.ping()


class YTStaticSinker(Sinker):
    """Static-table sink: create-with-schema on first push, append
    writes (the reference's static sink commits via a transaction per
    part; the HTTP proxy's write_table is atomic per request, which is
    the same per-push unit here)."""

    def __init__(self, params: YTStaticTargetParams):
        self.params = params
        self.client = YTClient(params.proxy, token=params.token,
                               secure=params.secure)
        self._created: set[TableID] = set()

    def _ensure_table(self, table: TableID, schema: TableSchema) -> None:
        if table in self._created:
            return
        path = _join_path(self.params.dir, table)
        if not self.client.exists(path):
            self.client.create("table", path, attributes={
                "schema": _schema_to_yt(schema),
                "optimize_for": self.params.optimize_for,
            }, recursive=True, ignore_existing=True)
        self._created.add(table)

    def push(self, batch: Batch) -> None:
        if not is_columnar(batch):
            rows = [it for it in batch if it.is_row_event()]
            if not rows:
                return
            batch = ColumnBatch.from_rows(rows)
        if batch.n_rows == 0:
            return
        self._ensure_table(batch.table_id, batch.schema)
        binary = {c.name for c in batch.schema.columns
                  if c.data_type == CanonicalType.STRING}
        data = batch.to_pydict()
        names = list(data)
        out_rows = [
            {n: _encode_value(data[n][i], n in binary) for n in names}
            for i in range(batch.n_rows)
        ]
        self.client.write_table(
            _join_path(self.params.dir, batch.table_id), out_rows,
            append=True)


class YTDynamicSinker(Sinker):
    """Sorted/ordered dynamic-table sink over the HTTP proxy
    (reference: pkg/providers/yt/sink/ — per-tablet batched writes;
    model_ytsaurus_dynamic_destination.go for the endpoint surface).

    Sorted mode: INSERT/UPDATE upsert via insert_rows, DELETE removes by
    key via delete_rows — the ReplacingMergeTree-free equivalent of the
    reference's dyntable sink (dyntables ARE keyed stores, so CDC maps
    1:1).  Kind runs flush in arrival order, preserving per-key
    ordering.  Ordered mode: append-only insert_rows, no keys.

    Tablet-aware batching: each request carries rows for ONE tablet
    (split on the table's pivot keys), so the proxy never coordinates a
    cross-tablet 2PC for bulk loads."""

    def __init__(self, params: YTDynamicTargetParams):
        self.params = params
        self.client = YTClient(params.proxy, token=params.token,
                               secure=params.secure)
        self._ready: set[TableID] = set()
        self._pivots: dict[TableID, list] = {}

    # -- table lifecycle -----------------------------------------------------
    def _ensure_table(self, table: TableID, schema: TableSchema) -> None:
        if table in self._ready:
            return
        path = _join_path(self.params.dir, table)
        if not self.client.exists(path):
            yt_schema = _schema_to_yt(schema)
            if self.params.ordered:
                # ordered dyntables are keyless logs
                for entry in yt_schema:
                    entry.pop("sort_order", None)
            attrs = {"schema": yt_schema, "dynamic": True}
            if self.params.tablet_count:
                attrs["tablet_count"] = self.params.tablet_count
            self.client.create("table", path, attributes=attrs,
                               recursive=True, ignore_existing=True)
        if self.client.tablet_state(path) != "mounted":
            self.client.mount_table(path)
            deadline = time.monotonic() + self.params.mount_timeout
            while self.client.tablet_state(path) != "mounted":
                if time.monotonic() > deadline:
                    raise YTError(
                        f"{path}: tablets not mounted within "
                        f"{self.params.mount_timeout}s")
                time.sleep(0.1)
        self._ready.add(table)

    def _tablet_split(self, table: TableID, key_col: str,
                      rows: list[dict]) -> list[list[dict]]:
        """Split one request's rows at tablet boundaries (pivot keys).

        Only single-component pivots split here; composite pivot keys
        compare lexicographically across components, so first-component
        bisection would mis-bucket boundary rows — those tables send
        unsplit requests (correct, just cross-tablet)."""
        pivots = self._pivots.get(table)
        if pivots is None:
            path = _join_path(self.params.dir, table)
            pivots = self.client.pivot_keys(path) or [[]]
            self._pivots[table] = pivots
        if any(len(p) > 1 for p in pivots):
            return [rows]
        bounds = [p[0] for p in pivots[1:] if p]  # first pivot = empty
        if not bounds:
            return [rows]
        import bisect

        groups: dict[int, list[dict]] = {}
        try:
            for r in rows:
                idx = bisect.bisect_right(bounds, r.get(key_col))
                groups.setdefault(idx, []).append(r)
        except TypeError:
            # pivot/row key type mismatch (yson vs string pivots, None
            # keys): degrade to one unsplit request — correct, just
            # cross-tablet — instead of failing the push
            return [rows]
        return [groups[i] for i in sorted(groups)]

    # -- push ----------------------------------------------------------------
    def push(self, batch: Batch) -> None:
        items = (batch.to_rows() if is_columnar(batch)
                 else [it for it in batch])
        rows = [it for it in items if it.is_row_event()]
        if not rows:
            return
        # CDC batches may mix tables; group by table, preserving each
        # table's arrival order
        by_table: dict = {}
        for it in rows:
            by_table.setdefault(it.table_id, []).append(it)
        for table, t_rows in by_table.items():
            self._push_table(table, t_rows)

    def _push_table(self, table: TableID, rows: list) -> None:
        schema = rows[0].table_schema
        self._ensure_table(table, schema)
        path = _join_path(self.params.dir, table)
        binary = {c.name for c in schema.columns
                  if c.data_type == CanonicalType.STRING}
        key_names = [c.name for c in schema.key_columns()]
        if self.params.ordered:
            out = [
                {n: _encode_value(it.value(n), n in binary)
                 for n in it.column_names}
                for it in rows
            ]
            for lo in range(0, len(out), self.params.batch_rows):
                self.client.insert_rows(
                    path, out[lo:lo + self.params.batch_rows],
                    atomicity=self.params.atomicity)
            return
        # sorted mode: expand items into (op, payload) — a key-changing
        # UPDATE becomes delete(old key) + upsert(new key), since a bare
        # upsert of the new key would leave the stale old-key row behind
        ops: list[tuple[str, dict]] = []
        for it in rows:
            if it.kind == Kind.DELETE:
                keys = (it.old_keys.as_dict()
                        if it.old_keys.key_names else
                        {n: it.value(n) for n in key_names})
                ops.append(("del", {
                    n: _encode_value(keys.get(n), n in binary)
                    for n in key_names}))
                continue
            if it.kind == Kind.UPDATE and it.old_keys.key_names:
                old = it.old_keys.as_dict()
                if any(old.get(n) != it.value(n) for n in key_names
                       if n in old):
                    ops.append(("del", {
                        n: _encode_value(old.get(n), n in binary)
                        for n in key_names}))
            ops.append(("ups", {
                n: _encode_value(it.value(n), n in binary)
                for n in it.column_names}))

        # flush consecutive same-op runs in arrival order so a delete
        # never reorders around an upsert of the same key
        def flush(run_kind: str, buf: list[dict]) -> None:
            if not buf:
                return
            key0 = key_names[0] if key_names else None
            chunks = (self._tablet_split(table, key0, buf)
                      if key0 else [buf])
            for chunk in chunks:
                for lo in range(0, len(chunk), self.params.batch_rows):
                    part = chunk[lo:lo + self.params.batch_rows]
                    try:
                        if run_kind == "del":
                            self.client.delete_rows(
                                path, part,
                                atomicity=self.params.atomicity)
                        else:
                            self.client.insert_rows(
                                path, part,
                                atomicity=self.params.atomicity)
                    except YTError:
                        # a reshard/remount voids the cached pivot keys
                        # (the one-tablet-per-request invariant would
                        # silently break); drop them so the sink retry
                        # re-reads tablet boundaries and mount state
                        self._pivots.pop(table, None)
                        self._ready.discard(table)
                        raise

        run_kind = ""
        buf: list[dict] = []
        for kind, payload in ops:
            if kind != run_kind:
                flush(run_kind, buf)
                buf = []
                run_kind = kind
            buf.append(payload)
        flush(run_kind, buf)

    def close(self) -> None:
        pass


@register_provider
class YTProvider(Provider):
    NAME = "yt"

    def storage(self):
        if isinstance(self.transfer.src, YTSourceParams):
            return YTStorage(self.transfer.src)
        return None

    def sinker(self):
        if isinstance(self.transfer.dst, YTStaticTargetParams):
            return YTStaticSinker(self.transfer.dst)
        if isinstance(self.transfer.dst, YTDynamicTargetParams):
            return YTDynamicSinker(self.transfer.dst)
        return None

    def cleanup(self, tables: list) -> None:
        params = self.transfer.dst
        if not isinstance(params, (YTStaticTargetParams,
                                   YTDynamicTargetParams)):
            return
        dynamic = isinstance(params, YTDynamicTargetParams)
        client = YTClient(params.proxy, token=params.token,
                          secure=params.secure)
        for td in tables or []:
            tid = td.id if hasattr(td, "id") else td
            path = _join_path(params.dir, tid)
            if not client.exists(path):
                continue
            if params.cleanup_policy == CleanupPolicy.DROP:
                client.remove(path)
            elif params.cleanup_policy == CleanupPolicy.TRUNCATE:
                if dynamic:
                    # dyntables have no truncate; drop and let the sink
                    # recreate+remount on first push
                    client.remove(path)
                else:
                    client.write_table(path, [], append=False)

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        params = self.transfer.src if isinstance(
            self.transfer.src, YTSourceParams) else self.transfer.dst
        try:
            YTClient(params.proxy, token=params.token,
                     secure=params.secure).ping()
            result.add("ping")
        except Exception as e:
            result.add("ping", e)
        if isinstance(params, YTSourceParams):
            try:
                n = len(YTStorage(params)._table_paths())
                result.add(f"list_tables({n})")
            except Exception as e:
                result.add("list_tables", e)
        return result


@register_provider
class YTDynProvider(YTProvider):
    """Provider identity for the dynamic-table destination; shares the
    YT storage/sinker wiring (sinker() dispatches on params type)."""

    NAME = "yt_dyn"
