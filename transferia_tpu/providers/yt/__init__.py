from transferia_tpu.providers.yt.client import YTClient, YTError
from transferia_tpu.providers.yt.provider import (
    YTProvider,
    YTSourceParams,
    YTStaticSinker,
    YTStaticTargetParams,
    YTStorage,
)

__all__ = [
    "YTClient",
    "YTError",
    "YTProvider",
    "YTSourceParams",
    "YTStaticSinker",
    "YTStaticTargetParams",
    "YTStorage",
]
