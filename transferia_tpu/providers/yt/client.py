"""Dependency-free YTsaurus HTTP-proxy client.

Speaks the public YT HTTP API (api/v4): light commands (get/list/exists/
create/remove/set, transactions) as JSON requests, heavy commands
(read_table/write_table) as streamed newline-delimited JSON ("json"
format, list_fragment).  Row ranges use rich-YPath suffixes
(``//path[#lo:#hi]``) so sharded snapshot parts are server-side range
reads, exactly like the Go SDK the reference uses
(/root/reference/pkg/providers/yt/cypress.go, storage/).

Auth: ``Authorization: OAuth <token>`` when a token is configured.
"""

from __future__ import annotations

import http.client
import json
import logging
import re
import urllib.parse
from typing import Any, Iterator, Optional

from transferia_tpu.abstract.errors import CategorizedError

logger = logging.getLogger(__name__)

# rich YPath row-range suffix: //path[#lo:#hi] (either bound optional)
RANGE_RE = re.compile(r"^(?P<path>.*?)\[#(?P<lo>\d*):#?(?P<hi>\d*)\]$")


class YTError(CategorizedError):
    def __init__(self, message: str, category: str = CategorizedError.SOURCE):
        super().__init__(category, message)


class YTClient:
    def __init__(self, proxy: str, token: str = "", secure: bool = False,
                 timeout: float = 300.0):
        if "://" in proxy:
            parsed = urllib.parse.urlparse(proxy)
            self.host = parsed.hostname or "localhost"
            self.port = parsed.port or (443 if parsed.scheme == "https"
                                        else 80)
            self.secure = parsed.scheme == "https"
        else:
            host, _, port = proxy.partition(":")
            self.host = host or "localhost"
            self.port = int(port) if port else 80
            self.secure = secure
        self.token = token
        self.timeout = timeout

    # -- transport ----------------------------------------------------------
    def _headers(self, extra: Optional[dict] = None) -> dict:
        h = {"Accept": "application/json"}
        if self.token:
            h["Authorization"] = f"OAuth {self.token}"
        if extra:
            h.update(extra)
        return h

    def _request(self, method: str, command: str, params: dict,
                 body: Optional[bytes] = None,
                 headers: Optional[dict] = None,
                 stream: bool = False):
        qs = urllib.parse.urlencode(
            {k: (json.dumps(v) if isinstance(v, (dict, list, bool))
                 else str(v))
             for k, v in params.items() if v is not None})
        path = f"/api/v4/{command}" + (f"?{qs}" if qs else "")
        cls = (http.client.HTTPSConnection if self.secure
               else http.client.HTTPConnection)
        conn = cls(self.host, self.port, timeout=self.timeout)
        try:
            conn.request(method, path, body=body,
                         headers=self._headers(headers))
            resp = conn.getresponse()
            if resp.status >= 300:
                data = resp.read()
                raise YTError(
                    f"yt {command} HTTP {resp.status}: "
                    f"{data[:300].decode('utf-8', 'replace')}")
            if stream:
                return resp, conn  # caller reads + closes
            data = resp.read()
            return json.loads(data) if data else {}
        except (ConnectionError, OSError,
                http.client.HTTPException) as e:
            conn.close()
            raise YTError(f"yt proxy unreachable: {e}") from e
        except YTError:
            conn.close()
            raise
        finally:
            if not stream:
                conn.close()

    # -- light commands -----------------------------------------------------
    def get(self, path: str, default: Any = ...) -> Any:
        try:
            return self._request("GET", "get", {"path": path})["value"]
        except YTError:
            if default is not ...:
                return default
            raise

    def set(self, path: str, value: Any, tx: str = "") -> None:
        self._request("PUT", "set",
                      {"path": path, "transaction_id": tx or None},
                      body=json.dumps(value).encode())

    def list(self, path: str) -> list[str]:
        return self._request("GET", "list", {"path": path})["value"]

    def exists(self, path: str) -> bool:
        return bool(
            self._request("GET", "exists", {"path": path})["value"])

    def create(self, node_type: str, path: str,
               attributes: Optional[dict] = None, recursive: bool = True,
               ignore_existing: bool = False, tx: str = "") -> None:
        self._request("POST", "create", {
            "type": node_type, "path": path,
            "attributes": attributes or {},
            "recursive": recursive,
            "ignore_existing": ignore_existing,
            "transaction_id": tx or None,
        })

    def remove(self, path: str, force: bool = True) -> None:
        self._request("POST", "remove", {"path": path, "force": force})

    # -- transactions -------------------------------------------------------
    def start_transaction(self, timeout_ms: int = 120_000) -> str:
        out = self._request("POST", "start_transaction",
                            {"timeout": timeout_ms})
        return out.get("transaction_id", out.get("value", ""))

    def commit_transaction(self, tx: str) -> None:
        self._request("POST", "commit_transaction",
                      {"transaction_id": tx})

    def abort_transaction(self, tx: str) -> None:
        self._request("POST", "abort_transaction",
                      {"transaction_id": tx})

    # -- heavy commands -----------------------------------------------------
    def read_table(self, path: str,
                   batch_rows: int = 10_000) -> Iterator[list[dict]]:
        """Stream rows as batches of dicts (json list_fragment)."""
        resp, conn = self._request(
            "GET", "read_table", {"path": path},
            headers={"X-YT-Output-Format": '"json"'}, stream=True)
        try:
            batch: list[dict] = []
            buf = b""
            while True:
                chunk = resp.read(1 << 20)
                if not chunk:
                    break
                buf += chunk
                while True:
                    nl = buf.find(b"\n")
                    if nl < 0:
                        break
                    line = buf[:nl]
                    buf = buf[nl + 1:]
                    if line.strip():
                        batch.append(json.loads(line))
                    if len(batch) >= batch_rows:
                        yield batch
                        batch = []
            if buf.strip():
                batch.append(json.loads(buf))
            if batch:
                yield batch
        finally:
            conn.close()

    def write_table(self, path: str, rows: list[dict],
                    append: bool = True, tx: str = "") -> None:
        """Write rows (json list_fragment).  append=False replaces."""
        ypath = f"<append=%{'true' if append else 'false'}>{path}"
        body = b"".join(
            json.dumps(r, default=str).encode() + b"\n" for r in rows)
        self._request(
            "PUT", "write_table",
            {"path": ypath, "transaction_id": tx or None}, body=body,
            headers={"X-YT-Input-Format": '"json"',
                     "Content-Type": "application/x-ndjson"})

    # -- dynamic tables ------------------------------------------------------
    def mount_table(self, path: str) -> None:
        self._request("POST", "mount_table", {"path": path})

    def unmount_table(self, path: str) -> None:
        self._request("POST", "unmount_table", {"path": path})

    def tablet_state(self, path: str) -> str:
        return self.get(path + "/@tablet_state", "unmounted")

    def pivot_keys(self, path: str) -> list:
        """Per-tablet pivot keys of a mounted sorted dyntable (the first
        tablet's pivot is the empty key)."""
        return self.get(path + "/@pivot_keys", [[]])

    def insert_rows(self, path: str, rows: list[dict],
                    update: bool = False,
                    atomicity: str = "full") -> None:
        """Upsert into a mounted sorted dyntable (ordered tables append)."""
        body = b"".join(
            json.dumps(r, default=str).encode() + b"\n" for r in rows)
        self._request(
            "PUT", "insert_rows",
            {"path": path, "update": update, "atomicity": atomicity},
            body=body,
            headers={"X-YT-Input-Format": '"json"',
                     "Content-Type": "application/x-ndjson"})

    def delete_rows(self, path: str, keys: list[dict],
                    atomicity: str = "full") -> None:
        """Delete by key from a mounted sorted dyntable."""
        body = b"".join(
            json.dumps(k, default=str).encode() + b"\n" for k in keys)
        self._request(
            "PUT", "delete_rows", {"path": path, "atomicity": atomicity},
            body=body,
            headers={"X-YT-Input-Format": '"json"',
                     "Content-Type": "application/x-ndjson"})

    def ping(self) -> None:
        self.exists("//")
