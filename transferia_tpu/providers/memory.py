"""In-memory mock provider for tests.

Reference parity: pkg/abstract/model/model_mock_destination.go /
model_mock_source.go and the *2mock e2e suites — a sink that captures
everything for assertions, and a storage made of pre-loaded batches.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.change_item import ChangeItem
from transferia_tpu.abstract.commit import StagedSinker
from transferia_tpu.abstract.interfaces import (
    Batch,
    IncrementalStorage,
    Pusher,
    Sinker,
    Storage,
    TableInfo,
    is_columnar,
)
from transferia_tpu.abstract.schema import TableID, TableSchema
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.registry import Provider, register_provider

# sink_id -> captured store
_STORES: dict[str, "MemoryStore"] = {}
_SOURCES: dict[str, list[ColumnBatch]] = {}


class MemoryStore:
    """Captured pushes, with row-level views for assertions.

    Staged-commit surface (abstract/commit.py): `begin_stage`/`stage`
    buffer a part's batches invisibly, `publish_stage` makes them
    visible atomically — REPLACING any batches previously published
    under the same part key (a retried/superseded part never appends
    duplicates) — behind a sink-side epoch fence (a zombie's stale-
    epoch publish raises instead of clobbering the survivor's data)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.batches: list[Batch] = []
        # staged-commit state: (part key, epoch) -> PartStage.  Keyed
        # by BOTH so a zombie and the survivor that reclaimed its part
        # never share a staging area — each owner stages its own
        # attempt and only the fenced publish decides whose wins.
        self._staged: dict[tuple[str, int], object] = {}
        self._published_by_part: dict[str, list[Batch]] = {}
        self._fence = None  # lazily a staging.EpochFence

    def push(self, batch: Batch) -> None:
        with self.lock:
            self.batches.append(batch)

    # -- staged two-phase commit -------------------------------------------
    def begin_stage(self, key: str, epoch: int) -> None:
        from transferia_tpu.providers.staging import EpochFence, PartStage

        with self.lock:
            if self._fence is None:
                self._fence = EpochFence()
            # begin replaces: a part retry restages from scratch
            self._staged[(key, epoch)] = PartStage(key, epoch, hold=True)

    def stage(self, key: str, epoch: int, batch: Batch) -> None:
        with self.lock:
            stage = self._staged.get((key, epoch))
        if stage is None:
            raise RuntimeError(f"memory sink: no open stage for {key!r}")
        # dedup/buffering outside the store lock: stages are per
        # (part, epoch) and each owner's pushes are serialized by its
        # own sink pipeline
        stage.stage(batch)

    def publish_stage(self, key: str, epoch: int) -> tuple[int, int]:
        """Returns (rows published, dedup-window rows dropped)."""
        from transferia_tpu.providers.staging import publish_guard

        with publish_guard(key, epoch):
            with self.lock:
                stage = self._staged.get((key, epoch))
                if stage is None:
                    raise RuntimeError(
                        f"memory sink: nothing staged for {key!r}")
                self._fence.check_and_advance(key, epoch)
                # replace-on-republish: drop what an earlier publish of
                # this part landed (identity-based: assertions hold
                # batch objects, never copies)
                prev = self._published_by_part.pop(key, None)
                if prev:
                    prev_ids = {id(b) for b in prev}
                    self.batches = [b for b in self.batches
                                    if id(b) not in prev_ids]
                self.batches.extend(stage.batches)
                self._published_by_part[key] = list(stage.batches)
                del self._staged[(key, epoch)]
                return stage.rows, stage.dedup_dropped

    def arm_replay(self, key: str, epoch: int) -> None:
        """Retry layer signal: the next staged push for this part may
        replay a torn prefix (providers/staging.py DedupWindow)."""
        with self.lock:
            stage = self._staged.get((key, epoch))
        if stage is not None:
            stage.note_push_retry()

    def abort_stage(self, key: str, epoch: Optional[int] = None) -> None:
        with self.lock:
            if epoch is not None:
                self._staged.pop((key, epoch), None)
            else:
                for k in [k for k in self._staged if k[0] == key]:
                    self._staged.pop(k, None)

    def staged_keys(self) -> list[str]:
        with self.lock:
            return sorted({k for k, _e in self._staged})

    # -- assertion helpers --------------------------------------------------
    def rows(self, table: Optional[TableID] = None) -> list[ChangeItem]:
        out = []
        with self.lock:
            for b in self.batches:
                items = b.to_rows() if is_columnar(b) else list(b)
                for it in items:
                    if it.is_row_event() and \
                            (table is None or it.table_id == table):
                        out.append(it)
        return out

    def control_events(self) -> list[ChangeItem]:
        out = []
        with self.lock:
            for b in self.batches:
                if not is_columnar(b):
                    out.extend(it for it in b if not it.is_row_event())
        return out

    def row_count(self, table: Optional[TableID] = None) -> int:
        n = 0
        with self.lock:
            for b in self.batches:
                if is_columnar(b):
                    if table is None or b.table_id == table:
                        n += b.n_rows
                else:
                    n += sum(
                        1 for it in b
                        if it.is_row_event()
                        and (table is None or it.table_id == table)
                    )
        return n

    def tables(self) -> set[TableID]:
        out = set()
        with self.lock:
            for b in self.batches:
                if is_columnar(b):
                    out.add(b.table_id)
                else:
                    out.update(it.table_id for it in b)
        return out

    def clear(self) -> None:
        with self.lock:
            self.batches.clear()
            self._staged.clear()
            self._published_by_part.clear()
            self._fence = None

    def drop_table(self, table: TableID) -> None:
        with self.lock:
            kept = []
            for b in self.batches:
                if is_columnar(b):
                    if b.table_id != table:
                        kept.append(b)
                else:
                    items = [it for it in b if it.table_id != table]
                    if items:
                        kept.append(items)
            self.batches = kept


def get_store(sink_id: str) -> MemoryStore:
    if sink_id not in _STORES:
        _STORES[sink_id] = MemoryStore()
    return _STORES[sink_id]


def seed_source(source_id: str, batches: list[ColumnBatch]) -> None:
    """Pre-load batches for a MemorySourceParams storage."""
    _SOURCES[source_id] = batches


@register_endpoint
@dataclass
class MemoryTargetParams(EndpointParams):
    PROVIDER = "memory"
    IS_TARGET = True

    sink_id: str = "default"
    fail_pushes: int = 0       # fail the first N pushes (retry testing)
    bufferer: Optional[dict] = None

    def bufferer_config(self):
        return self.bufferer


@register_endpoint
@dataclass
class MemorySourceParams(EndpointParams):
    PROVIDER = "memory"
    IS_SOURCE = True

    source_id: str = "default"


class MemorySinker(Sinker, StagedSinker):
    """Capture sink; staged-commit capable (the engine opens the
    stage → publish lifecycle via begin_part, otherwise pushes land
    directly — the legacy at-least-once path)."""

    def __init__(self, params: MemoryTargetParams):
        self.params = params
        self.store = get_store(params.sink_id)
        self._fails_left = params.fail_pushes
        self._stage_key: str = ""
        self._stage_epoch: int = 0

    def push(self, batch: Batch) -> None:
        if self._fails_left > 0:
            self._fails_left -= 1
            raise ConnectionError(
                f"injected failure ({self._fails_left} left)"
            )
        if self._stage_key:
            self.store.stage(self._stage_key, self._stage_epoch, batch)
        else:
            self.store.push(batch)

    # -- StagedSinker -------------------------------------------------------
    def begin_part(self, key: str, epoch: int) -> None:
        self.store.begin_stage(key, epoch)
        self._stage_key = key
        self._stage_epoch = epoch

    def publish_part(self, key: str, epoch: int) -> int:
        rows, self.last_dedup_dropped = self.store.publish_stage(
            key, epoch)
        if self._stage_key == key:
            # back to direct-push mode: the stage is gone (published)
            self._stage_key = ""
        return rows

    def abort_part(self, key: str) -> None:
        self.store.abort_stage(key, self._stage_epoch
                               if self._stage_key == key else None)
        if self._stage_key == key:
            self._stage_key = ""

    def note_push_retry(self) -> None:
        if self._stage_key:
            self.store.arm_replay(self._stage_key, self._stage_epoch)


class MemoryStorage(Storage, IncrementalStorage):
    """Also implements IncrementalStorage and predicate filters so e2e
    tests can exercise cursor-based snapshots without a real DB."""

    def __init__(self, params: MemorySourceParams):
        self.batches = _SOURCES.get(params.source_id, [])

    def _by_table(self) -> dict[TableID, list[ColumnBatch]]:
        out: dict[TableID, list[ColumnBatch]] = {}
        for b in self.batches:
            out.setdefault(b.table_id, []).append(b)
        return out

    def table_list(self, include=None):
        out = {}
        for tid, batches in self._by_table().items():
            if include and not any(tid.include_matches(p) for p in include):
                continue
            out[tid] = TableInfo(
                eta_rows=sum(b.n_rows for b in batches),
                schema=batches[0].schema,
            )
        return out

    def table_schema(self, table: TableID) -> TableSchema:
        return self._by_table()[table][0].schema

    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        mask_fn = None
        if table.filter:
            from transferia_tpu.predicate import compile_mask, parse

            mask_fn = compile_mask(parse(table.filter))
        for b in self._by_table().get(table.id, []):
            if mask_fn is not None:
                b = b.filter(mask_fn(b))
                if b.n_rows == 0:
                    continue
            pusher(b)

    # -- IncrementalStorage -------------------------------------------------
    def get_increment_state(self, tables, state):
        out = []
        for t in tables:
            cursor = state.get(str(t.table), t.initial_state or None)
            if cursor is None or cursor == "":
                out.append(TableDescription(id=t.table))
            else:
                lit = cursor if isinstance(cursor, (int, float)) \
                    else f"'{cursor}'"
                out.append(TableDescription(
                    id=t.table, filter=f"{t.cursor_field} > {lit}"
                ))
        return out

    def next_increment_state(self, tables):
        out = {}
        for t in tables:
            best = None
            for b in self._by_table().get(t.table, []):
                if t.cursor_field in b.columns:
                    for v in b.columns[t.cursor_field].to_pylist():
                        if v is not None and (best is None or v > best):
                            best = v
            if best is not None:
                out[str(t.table)] = best
        return out


class MemoryStoreStorage(Storage):
    """Storage view over a sink's captured pushes (the TARGET side).

    The seed space (_SOURCES, via seed_source) and the capture space
    (_STORES, written by MemorySinker) are distinct; destination_storage
    must read the latter or target validation vacuously compares seeds.
    """

    def __init__(self, sink_id: str):
        self._store = get_store(sink_id)

    def _by_table(self) -> dict[TableID, list]:
        out: dict[TableID, list] = {}
        for it in self._store.rows():
            out.setdefault(it.table_id, []).append(it)
        return out

    def table_list(self, include=None):
        out = {}
        for tid, items in self._by_table().items():
            if include and not any(tid.include_matches(p)
                                   for p in include):
                continue
            out[tid] = TableInfo(eta_rows=len(items),
                                 schema=items[0].table_schema)
        return out

    def table_schema(self, table: TableID) -> TableSchema:
        return self._by_table()[table][0].table_schema

    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        items = self._by_table().get(table.id, [])
        mask_fn = None
        if table.filter:
            from transferia_tpu.predicate import compile_mask, parse

            mask_fn = compile_mask(parse(table.filter))
        for lo in range(0, len(items), 4096):
            b = ColumnBatch.from_rows(items[lo:lo + 4096])
            if mask_fn is not None:
                b = b.filter(mask_fn(b))
            if b.n_rows:
                pusher(b)


@register_provider
class MemoryProvider(Provider):
    NAME = "memory"

    def storage(self):
        if isinstance(self.transfer.src, MemorySourceParams):
            return MemoryStorage(self.transfer.src)
        return None

    def cleanup(self, tables: list) -> None:
        if isinstance(self.transfer.dst, MemoryTargetParams):
            store = get_store(self.transfer.dst.sink_id)
            # empty list = no-op (like every other provider) — the store
            # may be shared by other transfers
            for t in tables or []:
                store.drop_table(getattr(t, "id", t))

    def destination_storage(self):
        if isinstance(self.transfer.dst, MemoryTargetParams):
            # read back what the sink actually captured (checksum /
            # --against-operation read the TARGET, not the seed space)
            return MemoryStoreStorage(self.transfer.dst.sink_id)
        return None

    def sinker(self):
        if isinstance(self.transfer.dst, MemoryTargetParams):
            return MemorySinker(self.transfer.dst)
        return None
